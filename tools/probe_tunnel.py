"""Exit 0 iff the axon TPU tunnel answers within the watchdog budget.

A wedged tunnel makes ``jax.devices()`` block forever (no exception), so a
plain import-and-call would hang any caller; the hard watchdog + ``os._exit``
pattern is mandatory (see bench.py). Callers should ALSO wrap this in
``timeout 120`` (comfortably above the 90 s internal watchdog) as a
belt-and-suspenders kill — tunnel_watch.sh does.
"""

import os
import threading


def _die() -> None:
    print("tunnel DOWN (init hung)", flush=True)
    os._exit(3)


t = threading.Timer(90, _die)
t.daemon = True
t.start()

import jax  # noqa: E402

kinds = [d.device_kind for d in jax.devices()]
if not kinds or all("cpu" in k.lower() for k in kinds):
    # axon failed silently and jax fell back to host CPU (or no devices at
    # all): NOT a window
    print(f"tunnel DOWN (cpu fallback: {kinds})", flush=True)
    os._exit(4)
print(f"tunnel UP: {kinds}", flush=True)
os._exit(0)
