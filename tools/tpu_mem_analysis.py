"""Diagnose the 10M-row GBM RESOURCE_EXHAUSTED on the tunneled TPU — and
model the out-of-core data plane's capacity math (``--oocore``).

The 20260731T0101Z bench lost every entry after the headline to an OOM
cascade that started in the 10M build; an isolated 10M run reproduces it
even with ~15 GB HBM allocatable (probed) and an estimated ~3 GB working
set. CPU memory_analysis of the same program shows 13.4 GB temp at 10M —
but that's the scatter path; the TPU program (Pallas kernel) should be far
smaller. This tool gets the REAL number from the TPU compiler:

  1. AOT-compile the scanned-tree program for 1M/4M/10M rows on the TPU
     backend and print XLA's memory_analysis (temp/argument/output bytes).
  2. If the analysis looks fine, run an actual GBM train at increasing row
     counts (each in THIS process — run the tool fresh per investigation)
     to find where execution, as opposed to allocation plan, fails.

Usage (tunnel up): python tools/tpu_mem_analysis.py [--train]
       python tools/tpu_mem_analysis.py --oocore [--out FILE]
          # analytic capacity model of compressed/binned frames + the HBM
          # window (ISSUE 11): largest trainable rows per pod bracket
          # before/after compression, and the streamed geometry that makes
          # Higgs-1B trainable through a fixed window. Pure host math —
          # runs anywhere, artifact committed alongside the PR.
       python tools/tpu_mem_analysis.py --live [URL]
          # read the devmem ledger + flight-recorder ring from a RUNNING
          # server (GET /3/Metrics?format=json + /3/FlightRecorder,
          # default http://127.0.0.1:54321) and print the measured
          # attribution table — per-owner live/peak bytes, per-device
          # in_use/limit, the unattributed (XLA program/temp) share —
          # next to the static capacity model, flagging an unattributed
          # share > 25% of in_use (the OOM-forensics threshold).
"""

from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def oocore_model(out_path: str | None = None) -> dict:
    """Largest-trainable-rows per bracket, resident f32 vs compressed
    (binned uint8) vs streamed through an HBM window (frame/chunkstore.py).

    Per-row device bytes during a GBM build:
    - resident f32 frame: C*4 (columns) + C (bins_u8) + 24 (w/y/F/wy/wh f32
      + nid i32) — the pre-ISSUE-11 layout keeps BOTH the f32 columns and
      the binned matrix resident;
    - compressed (H2O3_TPU_FRAME_COMPRESS): C (bins_u8) + 24 — the f32
      columns are released to the host tier after binning;
    - streamed (H2O3_TPU_HBM_WINDOW_BYTES): device holds only the window;
      rows are bounded by HOST RAM at (C + 24 + C*4) bytes/row host tier
      (the f32 mirrors + lanes), not by HBM.
    ``usable`` reserves HBM for compiled programs/temporaries (the 10M-row
    RESOURCE_EXHAUSTED above is exactly what ignoring that costs).

    The per-row math and the usable fraction live in
    ``h2o3_tpu/utils/overload.py`` (ISSUE 19): the SAME model the runtime's
    memory-aware admission preflight checks against measured
    ``devmem.headroom()`` — this offline table and the live gate cannot
    drift apart.
    """
    import json

    from h2o3_tpu.utils import overload as _ov

    GiB = 1 << 30
    C = 28  # Higgs feature width
    usable = _ov.USABLE_FRACTION
    state = _ov.STATE_BYTES  # per-row f32 lanes + nid
    brackets = [
        ("v5e-1", 1), ("v5e-4", 4), ("v5e-8", 8), ("v5e-16", 16),
        ("v5e-32", 32),
    ]
    hbm_per_chip = 16 * GiB
    per_row_res = _ov.per_row_device_bytes(C, "gbm", compressed=False)
    per_row_cmp = _ov.per_row_device_bytes(C, "gbm", compressed=True)
    rows_resident = lambda hbm: int(usable * hbm // per_row_res)
    rows_compressed = lambda hbm: int(usable * hbm // per_row_cmp)
    out = {"phase": "oocore_mem_model", "cols": C, "usable_fraction": usable,
           "hbm_per_chip_gib": hbm_per_chip / GiB, "brackets": []}
    for name, chips in brackets:
        hbm = chips * hbm_per_chip
        r_res, r_cmp = rows_resident(hbm), rows_compressed(hbm)
        out["brackets"].append({
            "bracket": name, "chips": chips, "hbm_gib": hbm / GiB,
            "max_rows_resident_f32": r_res,
            "max_rows_compressed_u8": r_cmp,
            "compression_capacity_ratio": round(r_cmp / max(r_res, 1), 2),
            "higgs_1b_fits_resident": r_res >= 1_000_000_000,
            "higgs_1b_fits_compressed": r_cmp >= 1_000_000_000,
        })
    # streamed geometry: Higgs-1B through a fixed per-chip window
    window = int(0.25 * usable * hbm_per_chip)
    host_bytes_per_row = C * 4 + C + state  # f32 mirrors + lanes, host tier
    out["streamed"] = {
        "window_bytes_per_chip": window,
        "bytes_per_row_device_lanes": C + state,
        "block_rows_per_chip_window": int(window // (2 * (C + state))),
        "higgs_1b_host_tier_gib": round(1e9 * host_bytes_per_row / GiB, 1),
        "note": "rows are host-RAM bound, not HBM bound: the device holds "
                "only the LRU window; Higgs-1B streams through any bracket "
                "whose hosts carry the spill tier",
    }
    # compiled-munging exchange geometry (ISSUE 20): the radix join's
    # all_to_all moves, per side, an i32 key lane + a bool validity lane
    # out and an i32 gid lane back, through (nd, cap) bucket buffers whose
    # cap the skew guard bounds at 4x the balanced share — so the exchange
    # working set is the padding factor times the row bytes, NOT the raw
    # frame. The sort lane moves no rows at all (one replicated order
    # vector + the payload gather).
    jx_bytes_per_row = 4 + 4  # key out + gid back (empty slots carry the
    # canonical-NaN key code, so no validity plane rides the exchange)
    skew_pad_max = 4.0            # tuple_gids_exchange's cap guard
    per_row_join = int(2 * jx_bytes_per_row * skew_pad_max + 8)  # both
    # sides' buckets live at once + the i64 staging codes
    out["munge_exchange"] = {
        "join_exchange_bytes_per_row_balanced": 2 * jx_bytes_per_row,
        "join_exchange_bytes_per_row_skew_capped": per_row_join,
        "sort_exchange_bytes_per_row": 4,  # replicated order i32 only
        "brackets": [{
            "bracket": name, "chips": chips,
            "max_join_rows_per_side": int(
                usable * chips * hbm_per_chip // per_row_join),
        } for name, chips in brackets],
        "note": "join capacity is exchange-buffer bound (cap*nd padding), "
                "not key bound: the skew guard falls back to the lexsort "
                "lane before the padded buckets can exceed 4x the data",
    }
    print(json.dumps(out), flush=True)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
    return out


def live_attribution(url: str = "http://127.0.0.1:54321") -> dict:
    """The measured twin of :func:`oocore_model`: pull the devmem ledger
    and the flight-recorder ring off a running server and print the
    attribution table. Returns the combined dict (and exits nonzero from
    __main__ when the unattributed share exceeds 25% — that much
    unclaimed HBM means XLA temps/programs, not the residency planes,
    are what an OOM investigation should chase)."""
    import json
    import urllib.request

    def _get(path):
        with urllib.request.urlopen(url.rstrip("/") + path, timeout=10) as r:
            return json.loads(r.read())

    fr = _get("/3/FlightRecorder?n=64")
    dm = fr.get("devmem", {})
    owned = dm.get("owned_bytes", {})
    peaks = dm.get("peak_owned_bytes", {})
    in_use = dm.get("in_use_bytes")
    unattr = dm.get("unattributed_bytes")

    print(f"== live HBM attribution ({url}) ==")
    print(f"{'owner':16s} {'live_bytes':>14s} {'peak_bytes':>14s}")
    for owner in sorted(set(owned) | set(peaks)):
        print(f"{owner:16s} {owned.get(owner, 0):>14,} "
              f"{peaks.get(owner, 0):>14,}")
    print(f"{'TOTAL owned':16s} {sum(owned.values()):>14,}")
    if in_use is not None:
        share = (unattr or 0) / max(in_use, 1)
        print(f"{'device in_use':16s} {in_use:>14,}")
        print(f"{'unattributed':16s} {unattr or 0:>14,}  "
              f"({share:.0%} of in_use — XLA program/temp share)")
        if share > 0.25:
            print("FLAG: unattributed share > 25% — the residency planes "
                  "are not what is eating HBM; dump the flight ring and "
                  "check compiled-program temps (memory_analysis)")
    else:
        print("device in_use: unavailable (backend reports no "
              "memory_stats — CPU proxy); per-owner ledger only")
    for d in dm.get("devices", []):
        if "in_use" in d or d.get("error"):
            print(f"  device {d['id']}: in_use={d.get('in_use')} "
                  f"limit={d.get('limit')} peak={d.get('peak')} "
                  f"err={d.get('error')}")
    ring = fr.get("ring", {})
    print(f"flight ring: {ring.get('next_seq', 0)} events recorded, "
          f"size {ring.get('size')}, last incident: "
          f"{fr.get('last_incident')}")
    for ev in fr.get("events", [])[-8:]:
        print(f"  [{ev['seq']}] {ev['kind']}: "
              + ", ".join(f"{k}={v}" for k, v in ev.items()
                          if k not in ("seq", "ts", "kind")))
    print()
    print("== static capacity model (for comparison) ==")
    model = oocore_model(None)
    out = {"live": dm, "ring": ring, "static_model": model}
    out["unattributed_flag"] = bool(
        in_use is not None and (unattr or 0) / max(in_use, 1) > 0.25)
    return out


def main() -> None:
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    import h2o3_tpu
    from h2o3_tpu.models.tree import shared_tree as st
    from h2o3_tpu.models.tree.distributions import grad_hess

    h2o3_tpu.init(log_level="WARN")
    print("backend:", jax.default_backend(), jax.devices()[0].device_kind, flush=True)

    C, n_trees, depth, n_bins = 28, 5, 6, 256
    kw = dict(
        grad_fn=lambda F_, y_, w_: grad_hess("bernoulli", F_, y_, w_, 0.0),
        grad_key=("memdiag", "bernoulli"),
        sample_rate=1.0, n_bins=n_bins, is_cat_cols=np.zeros(C, bool),
        max_depth=depth, min_rows=10.0, min_split_improvement=1e-5,
        learn_rates=np.full(n_trees, 0.1, np.float32),
        max_abs_leaf=float("inf"), col_sample_rate=1.0,
        col_sample_rate_per_tree=1.0,
    )
    t0 = time.time()
    st.build_trees_scanned(
        jnp.zeros((512, C), jnp.uint8), jnp.ones(512), jnp.zeros(512),
        jnp.zeros(512), jnp.zeros(C), jr.PRNGKey(0), n_trees, **kw,
    )
    print("warm trace+exec", round(time.time() - t0, 1), "s", flush=True)
    prog = [v for k, v in st._STEP_CACHE.items() if k[0] == "scan"][-1]

    for n in (1_048_576, 4_194_304, 10_485_760):
        bins = jax.ShapeDtypeStruct((n, C), jnp.uint8)
        f32 = jax.ShapeDtypeStruct((n,), jnp.float32)
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        t0 = time.time()
        try:
            c = prog.lower(
                bins, f32, f32, f32,
                jax.ShapeDtypeStruct((C,), jnp.float32), key, key,
                jnp.int32(0), jax.ShapeDtypeStruct((n_trees,), jnp.float32),
                jax.ShapeDtypeStruct((C,), jnp.bool_), jnp.float32(10.0),
                jnp.float32(1e-5), jnp.float32(np.inf), jnp.float32(1.0),
                None,
            ).compile()
            ma = c.memory_analysis()
            print(
                f"rows={n}: temp={ma.temp_size_in_bytes / 2**30:.3f} GB "
                f"args={ma.argument_size_in_bytes / 2**30:.3f} GB "
                f"out={ma.output_size_in_bytes / 2**30:.3f} GB "
                f"(compile {time.time() - t0:.1f} s)",
                flush=True,
            )
        except Exception as e:
            print(f"rows={n}: compile FAILED: {e!r}"[:500], flush=True)

    if "--train" not in sys.argv:
        return
    # execution-level bisect: fresh data per size, freed before the next
    import bench
    from h2o3_tpu.cluster.registry import DKV
    from h2o3_tpu.models.tree import GBM

    for n in (2_000_000, 5_000_000, 10_000_000):
        fr = bench._make_data_device(n)
        m = None
        try:
            t0 = time.time()
            m = GBM(ntrees=5, max_depth=depth, learn_rate=0.1, min_rows=10.0,
                    score_tree_interval=1000, seed=42).train(
                y="label", training_frame=fr)
            print(f"train rows={n}: OK {time.time() - t0:.1f} s "
                  f"auc={float(m.training_metrics.auc):.4f}", flush=True)
        except Exception as e:
            print(f"train rows={n}: FAILED {e!r}"[:300], flush=True)
            break
        finally:
            bench._drop_models(m)
            DKV.remove(fr.key)
            del fr


if __name__ == "__main__":
    if "--live" in sys.argv:
        i = sys.argv.index("--live")
        url = (sys.argv[i + 1] if i + 1 < len(sys.argv)
               and not sys.argv[i + 1].startswith("--")
               else "http://127.0.0.1:54321")
        res = live_attribution(url)
        sys.exit(1 if res.get("unattributed_flag") else 0)
    elif "--oocore" in sys.argv:
        out = None
        if "--out" in sys.argv:
            out = sys.argv[sys.argv.index("--out") + 1]
        oocore_model(out)
    else:
        main()
