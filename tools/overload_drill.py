#!/usr/bin/env python
"""Overload-survival drill (ISSUE 19): prove the overload plane's three
survival paths end-to-end and emit one gated artifact
(``OVERLOAD_DRILL_<stamp>.json``; tools/latest_bench_ok.py checks its pins).

Scenarios:

1. **storm** — an admission storm at 4x capacity: 16 concurrent mutating
   REST requests against ``H2O3_TPU_MAX_INFLIGHT=4`` while a ``slow:rest``
   fault holds every handler open. The pins: some requests land 200, the
   rest shed 429/503 with an honest numeric Retry-After (>= 1 s), the
   server answers normally the moment the storm ends (zero server deaths),
   and the reservation ledger sums back to zero. A second wave drives the
   ISSUE-19 memory gate: with synthetic device stats reporting no headroom
   and ``H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES`` armed, mutating requests shed
   503 ``reason=memory`` — and admit again once headroom returns.

2. **oom** — a ``RESOURCE_EXHAUSTED`` at the ``tree`` dispatch site (the
   one-shot ``oom:tree`` fault raises the real XlaRuntimeError signature
   inside the flight-recorder span): ``recovery.run_supervised`` retries
   the job exactly ONCE under ``overload.degrade_scope`` (streamed /
   halved window), the healed model lands within 1e-6 logloss of the
   resident control, the incident bundle names the OOM dispatch site, and
   the cloud generation does NOT tick — an OOM degrade is not a reform.

3. **hang** — a wedged dispatch (``hang:tree`` sleeps inside the open
   span, armed only after an interval snapshot exists): the watchdog trips
   ``dispatch_hangs_total{site=tree}`` within its budget, captures the
   incident, latches the cloud degraded; the unwedged dispatch fail-stops
   at its own exit and the supervisor reforms + resumes from the latest
   snapshot to a model within 1e-6 of the uninterrupted reference.

Queued in tools/run_tpu_backlog.sh; runs on the CPU proxy too (CI's
tests/test_overload.py is the assert-only version of the same drill).
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU proxy runs the drill on the same 8-device sharded mesh the bench
# artifacts use (real accelerators keep their native device count)
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu" and \
        "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def _frame(n=4000, seed=3):
    import numpy as np
    import pandas as pd

    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return Frame.from_pandas(df)


# -- scenario 1: admission storm ---------------------------------------------

def _post(url, path, payload):
    """POST form-encoded; returns (status, retry_after_or_None, reason)."""
    import urllib.error
    import urllib.parse
    import urllib.request

    data = urllib.parse.urlencode(payload).encode()
    req = urllib.request.Request(url + path, data=data, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, None, None
    except urllib.error.HTTPError as e:
        ra = e.headers.get("Retry-After")
        try:
            reason = json.loads(e.read()).get("reason")
        except Exception:  # noqa: BLE001 — shed body parse is best-effort
            reason = None
        return e.code, ra, reason


def _drill_storm():
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.utils import devmem, faults

    cap, waves = 4, 16
    saved = {k: os.environ.get(k) for k in (
        "H2O3_TPU_MAX_INFLIGHT", "H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES")}
    os.environ["H2O3_TPU_MAX_INFLIGHT"] = str(cap)
    srv = start_server(port=0)
    orig_stats = devmem._stats_fn
    try:
        # ---- wave 1: 4x capacity with every handler held open ----
        faults.configure(slow={"rest": 1.0})
        barrier = threading.Barrier(waves)
        out: list[tuple] = [None] * waves

        def _one(i):
            barrier.wait()
            out[i] = _post(srv.url, "/3/CreateFrame",
                           {"dest": f"storm_{i}", "rows": 200, "cols": 3,
                            "seed": i})

        threads = [threading.Thread(target=_one, args=(i,))
                   for i in range(waves)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        faults.reset()

        assert all(r is not None for r in out), "a storm request never returned"
        ok = [r for r in out if r[0] == 200]
        shed = [r for r in out if r[0] in (429, 503)]
        assert ok, "storm starved every request (no 200s at all)"
        assert shed, f"{waves} concurrent vs capacity {cap} shed nothing"
        assert len(ok) + len(shed) == waves, \
            f"unexpected statuses in {sorted(r[0] for r in out)}"
        for status, ra, reason in shed:
            assert ra is not None and float(ra) >= 1, \
                f"shed {status} carried a dishonest Retry-After {ra!r}"
            assert reason in ("inflight_full", "queue_full", "memory",
                              "draining"), f"shed {status} reason {reason!r}"
        # zero server deaths: the server answers normally post-storm
        st, _, _ = _post(srv.url, "/3/CreateFrame",
                         {"dest": "storm_after", "rows": 50, "cols": 2})
        assert st == 200, f"server did not survive the storm (post-storm {st})"
        assert devmem.reservations() == {}, \
            f"reservations leaked: {devmem.reservations()}"

        # ---- wave 2: the memory gate (synthetic zero headroom) ----
        devmem._stats_fn = lambda d: {"bytes_in_use": 8 << 30,
                                      "bytes_limit": 8 << 30}
        devmem.poll(force=True)
        os.environ["H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES"] = str(64 << 20)
        st, ra, reason = _post(srv.url, "/3/CreateFrame",
                               {"dest": "storm_mem", "rows": 50, "cols": 2})
        assert st == 503 and reason == "memory", \
            f"memory gate did not shed (status={st} reason={reason!r})"
        assert ra is not None and float(ra) >= 1, \
            f"memory shed carried a dishonest Retry-After {ra!r}"
        mem_shed = {"status": st, "reason": reason, "retry_after": float(ra)}
        # headroom returns -> the valve opens again
        devmem._stats_fn = orig_stats
        devmem.poll(force=True)
        os.environ["H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES"] = "0"
        st, _, _ = _post(srv.url, "/3/CreateFrame",
                         {"dest": "storm_mem_after", "rows": 50, "cols": 2})
        assert st == 200, f"server kept shedding after headroom returned ({st})"

        return {"sent": waves, "capacity": cap, "ok": len(ok),
                "shed": len(shed),
                "shed_statuses": sorted({r[0] for r in shed}),
                "retry_after_min": min(float(r[1]) for r in shed),
                "retry_after_max": max(float(r[1]) for r in shed),
                "memory_shed": mem_shed,
                "reservations_after": 0, "server_alive": True}
    finally:
        faults.reset()
        devmem._stats_fn = orig_stats
        devmem.poll(force=True)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        srv.stop()


# -- scenario 2: OOM catch-and-degrade ---------------------------------------

def _drill_oom(fr, ckdir):
    import numpy as np

    from h2o3_tpu.cluster import cloud, recovery
    from h2o3_tpu.models import GBM
    from h2o3_tpu.utils import faults, flightrec
    from h2o3_tpu.utils import metrics as mx

    kw = dict(ntrees=16, max_depth=4, seed=11, learn_rate=0.2,
              score_tree_interval=4)
    full = GBM(**kw).train(y="y", training_frame=fr)
    gen0 = cloud.generation()

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(**kw2).train(y="y", training_frame=fr)

    t0 = time.perf_counter()
    with faults.inject(oom={"tree"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="oom drill")
    wall = time.perf_counter() - t0

    delta = abs(healed.training_metrics.logloss - full.training_metrics.logloss)
    assert delta <= 1e-6, f"oom degrade parity violated: {delta}"
    assert healed.output["ntrees_actual"] == kw["ntrees"]
    pa = full.predict(fr).vec("p").to_numpy()
    pb = healed.predict(fr).vec("p").to_numpy()
    # an OOM degrade is NOT a reform: the cloud was healthy the whole time
    assert cloud.generation() == gen0, "oom degrade ticked the generation"
    bundle_path = flightrec.last_incident()
    assert bundle_path, "no incident bundle captured for the OOM"
    with open(bundle_path) as f:
        bundle = json.load(f)
    assert bundle["trigger"] == "oom", f"trigger {bundle['trigger']!r}"
    assert "'tree'" in bundle["reason"], \
        f"incident does not name the OOM dispatch site: {bundle['reason']!r}"
    fam = json.dumps(mx.REGISTRY.snapshot().get("oom_degrades_total"))
    assert "retried" in fam and "recovered" in fam, \
        f"oom_degrades_total missing outcomes: {fam}"
    return {"logloss_delta": delta, "wall_s": wall,
            "pred_max_delta": float(np.max(np.abs(pa - pb))),
            "incident": bundle_path, "incident_trigger": "oom",
            "generation_ticked": 0}


# -- scenario 3: dispatch hang -> watchdog trip -> supervised resume ----------

def _drill_hang(fr, ckdir):
    from h2o3_tpu.cluster import cloud, recovery
    from h2o3_tpu.models import GBM
    from h2o3_tpu.utils import faults, flightrec, overload
    from h2o3_tpu.utils import metrics as mx

    saved = {k: os.environ.get(k) for k in (
        "H2O3_TPU_HANG_MIN_SECS", "H2O3_TPU_HANG_POLL_SECS",
        "H2O3_TPU_HANG_FACTOR")}
    # the tree site dispatches once per score interval and its rolling mean
    # is compile-inflated (~2.4s with the 8s first-chunk trace on the CPU
    # proxy), so the drill pins factor=2 to keep budget x sleep inside a
    # CI-sized wall; poll fast enough to trip mid-sleep
    os.environ["H2O3_TPU_HANG_MIN_SECS"] = "0.6"
    os.environ["H2O3_TPU_HANG_POLL_SECS"] = "0.1"
    os.environ["H2O3_TPU_HANG_FACTOR"] = "2"

    kw = dict(ntrees=24, max_depth=4, seed=11, learn_rate=0.2,
              score_tree_interval=4)
    full = GBM(**kw).train(y="y", training_frame=fr)
    gen0 = cloud.generation()
    armed_after_snapshot = threading.Event()

    def _armer():
        # arm the wedge only once an interval snapshot exists, so the
        # supervised resume has something real to resume from; once the
        # watchdog trips, raise the floor back up so the resumed run's
        # recompile can never false-trip
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            if _glob.glob(os.path.join(ckdir, "gbm_ckpt_*")):
                faults.configure(hang={"tree": 8.0})
                armed_after_snapshot.set()
                break
            time.sleep(0.002)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60:
            if flightrec.events(kind="watchdog_trip"):
                os.environ["H2O3_TPU_HANG_MIN_SECS"] = "120"
                break
            time.sleep(0.01)

    overload.install_watchdog()
    armer = threading.Thread(target=_armer, daemon=True)
    try:
        def _launch(ckpt):
            kw2 = dict(kw, export_checkpoints_dir=ckdir)
            if ckpt:
                kw2["checkpoint"] = ckpt
            return GBM(**kw2).train(y="y", training_frame=fr)

        t0 = time.perf_counter()
        armer.start()
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="hang drill")
        wall = time.perf_counter() - t0
    finally:
        armer.join(timeout=10)
        faults.reset()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    assert armed_after_snapshot.is_set(), \
        "the hang was never armed (no snapshot appeared) — drill vacuous"
    trips = flightrec.events(kind="watchdog_trip")
    assert trips and any(e.get("site") == "tree" for e in trips), \
        f"watchdog never tripped on the wedged tree dispatch: {trips}"
    fam = json.dumps(mx.REGISTRY.snapshot().get("dispatch_hangs_total"))
    assert "tree" in fam, f"dispatch_hangs_total missing the site: {fam}"
    bundle_path = flightrec.last_incident()
    assert bundle_path, "no incident bundle captured for the hang"
    with open(bundle_path) as f:
        bundle = json.load(f)
    assert bundle["trigger"] == "hang", f"trigger {bundle['trigger']!r}"
    # the fail-stop handed the job to the supervisor: reform ticked the
    # generation and the resumed run completed from the interval snapshot
    assert cloud.generation() > gen0, "supervisor never re-formed the cloud"
    assert cloud.degraded_reason() is None, "cloud left degraded"
    delta = abs(healed.training_metrics.logloss - full.training_metrics.logloss)
    assert delta <= 1e-6, f"hang resume parity violated: {delta}"
    assert healed.output["ntrees_actual"] == kw["ntrees"]
    return {"logloss_delta": delta, "wall_s": wall,
            "trips": [{"site": e.get("site"), "age_s": e.get("age_s"),
                       "budget_s": e.get("budget_s")} for e in trips],
            "incident": bundle_path, "incident_trigger": "hang",
            "generations_ticked": cloud.generation() - gen0}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="artifact path (default: "
                    "OVERLOAD_DRILL_<stamp>.json in the repo root)")
    ap.add_argument("--scenarios", default="storm,oom,hang")
    args = ap.parse_args(argv)

    os.environ.setdefault("H2O3_TPU_RECOVERY", "1")
    os.environ.setdefault("H2O3_TPU_RECOVERY_BACKOFF", "0.05")
    os.environ.setdefault("H2O3_TPU_OVERLOAD", "1")

    import jax

    import h2o3_tpu
    from h2o3_tpu.cluster import cloud
    from h2o3_tpu.utils import flightrec, overload
    from h2o3_tpu.utils import metrics as mx

    h2o3_tpu.init()
    scen = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    results = {}
    if "storm" in scen:
        results["storm"] = _drill_storm()
        print(f"storm: ok={results['storm']['ok']} "
              f"shed={results['storm']['shed']} "
              f"retry_after=[{results['storm']['retry_after_min']}, "
              f"{results['storm']['retry_after_max']}] server alive")
    fr = _frame()
    if "oom" in scen:
        flightrec._reset_incidents_for_tests()
        with tempfile.TemporaryDirectory(prefix="ovl_oom_") as ckdir:
            results["oom"] = _drill_oom(fr, ckdir)
        assert cloud.degraded_reason() is None, "cloud left degraded"
        print(f"oom: logloss_delta={results['oom']['logloss_delta']:.2e} "
              f"incident={os.path.basename(results['oom']['incident'])}")
    if "hang" in scen:
        flightrec._reset_incidents_for_tests()
        try:
            with tempfile.TemporaryDirectory(prefix="ovl_hang_") as ckdir:
                results["hang"] = _drill_hang(fr, ckdir)
        finally:
            overload.uninstall_watchdog()
        assert cloud.degraded_reason() is None, "cloud left degraded"
        print(f"hang: trips={len(results['hang']['trips'])} "
              f"logloss_delta={results['hang']['logloss_delta']:.2e} "
              f"generations={results['hang']['generations_ticked']}")

    snap = mx.REGISTRY.snapshot()
    fam = {name: snap.get(name) for name in (
        "oom_degrades_total", "dispatch_hangs_total", "dispatch_hung",
        "hbm_reserved_bytes", "rest_rejected_total")}
    artifact = {
        "kind": "overload_drill",
        "stamp": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        "backend": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "results": results,
        "overload_metrics": fam,
        "ok": True,
    }
    out = args.out or f"OVERLOAD_DRILL_{artifact['stamp']}.json"
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    sys.exit(main())
