#!/usr/bin/env python
"""Pallas histogram kernel tile sweep on the REAL TPU (run when the tunnel
is up): measures hist time per (ROW_TILE, COL_TILE, n_bins, n_nodes) so the
next kernel iteration picks tiles from data, not guesses.

The kernel's per-step cost is dominated by the VPU indicator build
(∝ ROWS·CT·Bpad) and the MXU dot (M = 4·nt); below 64 nodes the node count
barely matters — bin count and tile sizes are the levers.

    python tools/bench_kernel_sweep.py        # prints one JSON line per cfg
    python tools/bench_kernel_sweep.py --split-ab [--rows N]
        # sharded-vs-replicated split pipeline A/B (H2O3_TPU_SPLIT_SHARD):
        # one JSON line per mode with fused_tree_s + psum_bytes_per_tree,
        # then a {"split_ab": ...} summary line. Runs on any backend (the
        # 8-device CPU mesh is the CI proxy; queue on TPU for real numbers).
    python tools/bench_kernel_sweep.py --fused-ab [--rows N]
        # fused-vs-unfused Pallas split pipeline A/B (H2O3_TPU_SPLIT_FUSE,
        # ISSUE 6): both modes pin H2O3_TPU_HIST=pallas (interpret mode on
        # CPU — slow but like-for-like), one JSON line per mode with
        # fused_tree_s + hist_hbm_bytes_per_tree (the modeled HBM traffic
        # of the hist+split phases), then a {"fused_ab": ...} summary.

    python tools/bench_kernel_sweep.py --fallback-ab [--rows N]
        # fallback-matrix closure A/B (ISSUE 15): monotone GBM, multinomial
        # GLM and dropout DL each run the NOW-fused lane vs the forced
        # fallback it replaces (kill-switch knobs), with parity pins and
        # dispatch/wall ratios in a {"fallback_ab": ...} summary line.

    python tools/bench_kernel_sweep.py --wave2-ab [--rows N]
        # tree-kernel wave-2 A/B (ISSUE 16): GOSS row sampling, EFB column
        # bundling, the u8-code cache, int16 hist lanes and lossguide
        # growth each run knob-on vs knob-off with parity/quality pins
        # (bit-identical controls, AUC/RMSE envelopes, shrink ratios),
        # then a {"wave2_ab": ...} summary line.

    python tools/bench_kernel_sweep.py --munge-ab [--rows N]
        # compiled-munging-plane A/B (H2O3_TPU_MUNGE_FUSE, ISSUE 20):
        # group-by / join / sort each run the fused mesh-sharded lane vs
        # the eager seed path on the SAME data, plus the 10-op expression
        # chain's dispatch-count pin, then a {"munge_ab": ...} summary
        # with the acceptance pins (fused wall <= 0.5x eager for group-by
        # and join, sort no worse, chain dispatches cut >= 5x, joins /
        # sort / chain bit-equal, group-by counts exact + sums allclose).

    python tools/bench_kernel_sweep.py --oocore-ab [--rows N]
        # streamed-vs-resident out-of-core A/B (ISSUE 11): forces an HBM
        # window of 1/10th the frame's training lanes, measures wall time,
        # AUC and the peak frame device bytes per mode (+ a COMPRESS=0
        # control), then an {"oocore_ab": ...} summary with the acceptance
        # pins (peak bounded by the window, rows >= 10x window).

The tile sweep varies ROW/COL/NODE tiles through the H2O3_TPU_PALLAS_TILES
knob (a static compile key — every setting gets its own executable), so no
module monkeypatching and no jit-cache clearing is needed.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def split_ab(rows: int = 10_000, cols: int = 28, depth: int = 6,
             trees: int = 4) -> None:
    """A/B the column-sharded split pipeline against the replicated path on
    the SAME mesh and data: per-tree fused seconds (median of 3 timed chunk
    dispatches after a compile warmup) and the per-tree collective byte
    tally, per mode. The env toggle works in-process because the tree
    program caches key on the mode."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.models.tree import shared_tree as st
    from h2o3_tpu.parallel.mesh import get_mesh, pad_to_shards, shard_rows
    from h2o3_tpu.utils import metrics as mx

    n = pad_to_shards(rows)
    rng = np.random.default_rng(0)
    bins = shard_rows(jnp.asarray(
        rng.integers(0, 128, (n, cols)).astype(np.uint8)))
    y = shard_rows(jnp.asarray(rng.normal(size=n).astype(np.float32)))
    w = shard_rows(jnp.ones(n, jnp.float32))

    def grad_fn(F, y_, w_):  # gaussian residuals, unit hessian
        return y_ - F, jnp.ones_like(F)

    results = {}
    for mode in ("1", "0"):
        os.environ["H2O3_TPU_SPLIT_SHARD"] = mode
        times = []
        h0 = mx.counter_value(
            "tree_collective_bytes_total", phase="hist_reduce")
        w0 = mx.counter_value(
            "tree_collective_bytes_total", phase="winner_gather")
        for rep in range(4):  # rep 0 = compile warmup
            preds = shard_rows(jnp.zeros(n, jnp.float32))
            varimp = jnp.zeros(cols, jnp.float32)
            t0 = time.perf_counter()
            out = st.build_trees_scanned(
                bins, w, y, preds, varimp, jax.random.PRNGKey(7), trees,
                grad_fn=grad_fn, grad_key="gaussian-ab", sample_rate=1.0,
                n_bins=128, is_cat_cols=np.zeros(cols, bool),
                max_depth=depth, min_rows=10.0, min_split_improvement=1e-5,
                learn_rates=np.full(trees, 0.1, np.float32),
                max_abs_leaf=float("inf"), col_sample_rate=1.0,
                col_sample_rate_per_tree=1.0,
            )
            jax.block_until_ready(out[0])
            if rep:
                times.append(time.perf_counter() - t0)
        built = 4 * trees
        rec = {
            "phase": "split_ab",
            "mode": "sharded" if mode == "1" else "replicated",
            "n_devices": get_mesh().devices.size,
            "rows": n, "cols": cols, "depth": depth, "trees": trees,
            "fused_tree_s": round(sorted(times)[len(times) // 2] / trees, 4),
            "psum_bytes_per_tree": round((
                mx.counter_value(
                    "tree_collective_bytes_total", phase="hist_reduce")
                + mx.counter_value(
                    "tree_collective_bytes_total", phase="winner_gather")
                - h0 - w0) / built, 1),
        }
        print(json.dumps(rec), flush=True)
        results[rec["mode"]] = rec
    os.environ.pop("H2O3_TPU_SPLIT_SHARD", None)
    if len(results) == 2 and results["sharded"]["psum_bytes_per_tree"] > 0:
        print(json.dumps({"split_ab": {
            "bytes_ratio_replicated_over_sharded": round(
                results["replicated"]["psum_bytes_per_tree"]
                / results["sharded"]["psum_bytes_per_tree"], 2),
            "time_ratio_replicated_over_sharded": round(
                results["replicated"]["fused_tree_s"]
                / max(results["sharded"]["fused_tree_s"], 1e-9), 3),
        }}), flush=True)


def fused_ab(rows: int = 4_000, cols: int = 28, depth: int = 6,
             trees: int = 2) -> None:
    """A/B the fused Pallas histogram→split pipeline (H2O3_TPU_SPLIT_FUSE)
    against the unfused Pallas path on the SAME mesh and data: per-tree
    fused seconds (median of 3 timed chunk dispatches after a compile
    warmup) plus the modeled hist+split HBM bytes per tree
    (tree_hist_hbm_bytes_total — the traffic the fusion removes). Both
    modes pin H2O3_TPU_HIST=pallas so the comparison isolates the split
    pipeline; on CPU both run the Pallas interpreter (like-for-like proxy —
    queue on TPU for real numbers). The env toggle works in-process because
    the tree program caches key on the mode (_kernel_key)."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.models.tree import shared_tree as st
    from h2o3_tpu.parallel.mesh import get_mesh, pad_to_shards, shard_rows
    from h2o3_tpu.utils import metrics as mx

    os.environ["H2O3_TPU_HIST"] = "pallas"
    n = pad_to_shards(rows)
    rng = np.random.default_rng(0)
    bins = shard_rows(jnp.asarray(
        rng.integers(0, 128, (n, cols)).astype(np.uint8)))
    y = shard_rows(jnp.asarray(rng.normal(size=n).astype(np.float32)))
    w = shard_rows(jnp.ones(n, jnp.float32))

    def grad_fn(F, y_, w_):  # gaussian residuals, unit hessian
        return y_ - F, jnp.ones_like(F)

    hbm_paths = ("fused", "pallas_unfused", "dense", "fused_via_dense")
    results = {}
    for mode in ("1", "0"):
        os.environ["H2O3_TPU_SPLIT_FUSE"] = mode
        times = []
        b0 = {p: mx.counter_value("tree_hist_hbm_bytes_total", path=p)
              for p in hbm_paths}
        for rep in range(4):  # rep 0 = compile warmup
            preds = shard_rows(jnp.zeros(n, jnp.float32))
            varimp = jnp.zeros(cols, jnp.float32)
            t0 = time.perf_counter()
            out = st.build_trees_scanned(
                bins, w, y, preds, varimp, jax.random.PRNGKey(7), trees,
                grad_fn=grad_fn, grad_key="gaussian-fab", sample_rate=1.0,
                n_bins=128, is_cat_cols=np.zeros(cols, bool),
                max_depth=depth, min_rows=10.0, min_split_improvement=1e-5,
                learn_rates=np.full(trees, 0.1, np.float32),
                max_abs_leaf=float("inf"), col_sample_rate=1.0,
                col_sample_rate_per_tree=1.0,
            )
            jax.block_until_ready(out[0])
            if rep:
                times.append(time.perf_counter() - t0)
        built = 4 * trees
        hbm = sum(
            mx.counter_value("tree_hist_hbm_bytes_total", path=p) - b0[p]
            for p in hbm_paths
        )
        rec = {
            "phase": "fused_ab",
            "mode": "fused" if mode == "1" else "unfused",
            "backend": jax.default_backend(),
            "n_devices": get_mesh().devices.size,
            "rows": n, "cols": cols, "depth": depth, "trees": trees,
            "fused_tree_s": round(sorted(times)[len(times) // 2] / trees, 4),
            "hist_hbm_bytes_per_tree": round(hbm / built, 1),
        }
        print(json.dumps(rec), flush=True)
        results[rec["mode"]] = rec
    os.environ.pop("H2O3_TPU_SPLIT_FUSE", None)
    os.environ.pop("H2O3_TPU_HIST", None)
    if len(results) == 2 and results["fused"]["hist_hbm_bytes_per_tree"] > 0:
        print(json.dumps({"fused_ab": {
            "hbm_ratio_unfused_over_fused": round(
                results["unfused"]["hist_hbm_bytes_per_tree"]
                / results["fused"]["hist_hbm_bytes_per_tree"], 2),
            "time_ratio_unfused_over_fused": round(
                results["unfused"]["fused_tree_s"]
                / max(results["fused"]["fused_tree_s"], 1e-9), 3),
        }}), flush=True)


def _ab_frame(rows: int, cols: int, seed: int = 0, classify: bool = True):
    """Synthetic numeric frame + binary/real response for the GLM/DL A/Bs."""
    import pandas as pd

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(rows, cols)).astype(np.float32)
    eta = X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2]
    df = pd.DataFrame(X, columns=[f"x{i}" for i in range(cols)])
    if classify:
        y = rng.random(rows) < 1.0 / (1.0 + np.exp(-eta))
        df["label"] = np.where(y, "s", "b")
    else:
        df["label"] = (eta + 0.3 * rng.normal(size=rows)).astype(np.float32)
    from h2o3_tpu.frame.frame import Frame

    return Frame.from_pandas(df)


def _hist_sum_count(name: str):
    """(sum, count) of an unlabeled registry histogram."""
    from h2o3_tpu.utils import metrics as mx

    for labels, _cum, s, n in mx.REGISTRY.histogram(name).samples():
        if not labels:
            return float(s), int(n)
    return 0.0, 0


def glm_ab(rows: int = 8_000, cols: int = 12) -> None:
    """Fused-vs-unfused whole-program GLM IRLS A/B (H2O3_TPU_GLM_FUSE,
    ISSUE 8) on the SAME mesh and frame: hot-loop iterations/sec from the
    glm_irls_iteration_seconds histogram (whole-train wall time is
    dominated by transform/scoring overhead both lanes share), host
    dispatches per model (O(iters/K) fused vs O(iters) unfused) and the
    Gram collective byte tally, per mode, then a {"glm_ab": ...} summary.
    The env toggle works in-process because the fused chunk programs key
    on the knob-derived lanes and the unfused path never touches them."""
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.parallel.mesh import get_mesh
    from h2o3_tpu.utils import metrics as mx

    fr = _ab_frame(rows, cols)
    # epsilons pinned to zero-ish so BOTH lanes run the full iteration
    # budget: the A/B measures steady-state iterations/sec of the hot
    # loop, not time-to-convergence on an easy synthetic problem
    kw = dict(family="binomial", lambda_=1e-4, max_iterations=20, seed=1,
              beta_epsilon=0.0, objective_epsilon=0.0)
    results = {}
    for mode in ("fused", "unfused"):
        if mode == "unfused":
            os.environ["H2O3_TPU_GLM_FUSE"] = "0"
        else:
            os.environ.pop("H2O3_TPU_GLM_FUSE", None)
        GLM(**kw).train(y="label", training_frame=fr)  # compile warmup
        g0 = sum(mx.counter_value("tree_collective_bytes_total", phase=ph)
                 for ph in ("gram_reduce", "gram_gather"))
        d0 = mx.counter_value("glm_dispatches_total")
        s0, c0 = _hist_sum_count("glm_irls_iteration_seconds")
        n_rep = 3
        times = []
        for _ in range(n_rep):
            t0 = time.perf_counter()
            m = GLM(**kw).train(y="label", training_frame=fr)
            times.append(time.perf_counter() - t0)
        s1, c1 = _hist_sum_count("glm_irls_iteration_seconds")
        iters = c1 - c0
        disp = int(mx.counter_value("glm_dispatches_total") - d0)
        gbytes = sum(
            mx.counter_value("tree_collective_bytes_total", phase=ph)
            for ph in ("gram_reduce", "gram_gather")) - g0
        med = sorted(times)[len(times) // 2]
        rec = {
            "phase": "glm_ab", "mode": mode,
            "n_devices": get_mesh().devices.size,
            "rows": rows, "cols": cols,
            "train_s": round(med, 4),
            "iters_per_s": round(iters / max(s1 - s0, 1e-9), 3),
            "iteration_ms": round((s1 - s0) / max(iters, 1) * 1000, 3),
            "dispatches_per_model": round(disp / n_rep, 2),
            "gram_bytes_per_model": round(gbytes / n_rep, 1),
            "auc": round(float(m.training_metrics.auc), 4),
        }
        print(json.dumps(rec), flush=True)
        results[mode] = rec
    os.environ.pop("H2O3_TPU_GLM_FUSE", None)
    if len(results) == 2 and results["unfused"]["iters_per_s"] > 0:
        print(json.dumps({"glm_ab": {
            "iters_per_s_ratio_fused_over_unfused": round(
                results["fused"]["iters_per_s"]
                / results["unfused"]["iters_per_s"], 3),
            "dispatch_ratio_unfused_over_fused": round(
                results["unfused"]["dispatches_per_model"]
                / max(results["fused"]["dispatches_per_model"], 1e-9), 2),
            "auc_delta": round(
                abs(results["fused"]["auc"] - results["unfused"]["auc"]), 5),
        }}), flush=True)


def dl_ab(rows: int = 20_000, cols: int = 16) -> None:
    """Chunked-vs-per-epoch DeepLearning A/B (H2O3_TPU_DL_EPOCH_CHUNK +
    H2O3_TPU_DL_GRAD_SHARD, ISSUE 8) on the SAME mesh and frame: measured
    epochs/sec, host dispatches per model and the gradient collective byte
    tally, per mode, then a {"dl_ab": ...} summary. The control pins
    chunk=1 + shard=0 (the pre-fusion lane)."""
    from h2o3_tpu.models.deeplearning import DeepLearning
    from h2o3_tpu.parallel.mesh import get_mesh
    from h2o3_tpu.utils import metrics as mx

    fr = _ab_frame(rows, cols)
    kw = dict(hidden=[64, 64], epochs=4, mini_batch_size=256, seed=3)
    results = {}
    for mode in ("chunked", "per_epoch"):
        if mode == "per_epoch":
            os.environ["H2O3_TPU_DL_EPOCH_CHUNK"] = "1"
            os.environ["H2O3_TPU_DL_GRAD_SHARD"] = "0"
        else:
            os.environ.pop("H2O3_TPU_DL_EPOCH_CHUNK", None)
            os.environ.pop("H2O3_TPU_DL_GRAD_SHARD", None)
        DeepLearning(**kw).train(y="label", training_frame=fr)  # warmup
        d0 = mx.counter_value("dl_dispatches_total")
        g0 = sum(mx.counter_value("tree_collective_bytes_total", phase=ph)
                 for ph in ("dl_grad_reduce", "dl_param_gather"))
        s0, c0 = _hist_sum_count("dl_epoch_seconds")
        n_rep = 3
        times = []
        for _ in range(n_rep):
            t0 = time.perf_counter()
            m = DeepLearning(**kw).train(y="label", training_frame=fr)
            times.append(time.perf_counter() - t0)
        s1, c1 = _hist_sum_count("dl_epoch_seconds")
        epochs = c1 - c0
        disp = int(mx.counter_value("dl_dispatches_total") - d0)
        gbytes = sum(
            mx.counter_value("tree_collective_bytes_total", phase=ph)
            for ph in ("dl_grad_reduce", "dl_param_gather")) - g0
        med = sorted(times)[len(times) // 2]
        rec = {
            "phase": "dl_ab", "mode": mode,
            "n_devices": get_mesh().devices.size,
            "rows": rows, "cols": cols,
            "train_s": round(med, 4),
            "epochs_per_s": round(epochs / max(s1 - s0, 1e-9), 3),
            "epoch_s": round((s1 - s0) / max(epochs, 1), 4),
            "dispatches_per_model": round(disp / n_rep, 2),
            "grad_bytes_per_model": round(gbytes / n_rep, 1),
            "auc": round(float(m.training_metrics.auc), 4),
        }
        print(json.dumps(rec), flush=True)
        results[mode] = rec
    for k in ("H2O3_TPU_DL_EPOCH_CHUNK", "H2O3_TPU_DL_GRAD_SHARD"):
        os.environ.pop(k, None)
    if len(results) == 2 and results["per_epoch"]["epochs_per_s"] > 0:
        print(json.dumps({"dl_ab": {
            "epochs_per_s_ratio_chunked_over_per_epoch": round(
                results["chunked"]["epochs_per_s"]
                / results["per_epoch"]["epochs_per_s"], 3),
            "dispatch_ratio_per_epoch_over_chunked": round(
                results["per_epoch"]["dispatches_per_model"]
                / max(results["chunked"]["dispatches_per_model"], 1e-9), 2),
            "auc_delta": round(
                abs(results["chunked"]["auc"] - results["per_epoch"]["auc"]),
                5),
        }}), flush=True)


def quant_ab(rows: int = 16_000, cols: int = 12) -> None:
    """Quantized-collective-lane A/B (H2O3_TPU_COLLECTIVE_QUANT, ISSUE 9)
    on the SAME mesh and frames: per mode (quant / exact), a GBM train
    (modeled per-phase collective bytes WITH the {lane} split, train wall
    seconds, AUC) plus a GLM train (Gram bytes, coefficient vector) plus
    MEASURED reduce seconds at the bench histogram/Gram shapes through the
    active lane — then a {"quant_ab": ...} summary with the byte ratios and
    the accuracy deltas the acceptance pins (hist_reduce >= 2x fewer
    modeled bytes, GBM AUC delta <= 1e-3, GLM coefficient parity). The env
    toggle works in-process because every program cache keys on the lane
    through mesh_key(). On the CPU proxy the quantized lane's measured
    seconds are usually SLOWER (the int8 encode + all_to_all emulation of a
    fused quantized collective is extra host-side work); the wire-byte
    model is the claim, and the real-TPU/DCN window decides the wall-clock
    question — which is why the measured seconds ride along."""
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as Spec

    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree import GBM
    from h2o3_tpu.ops import collectives
    from h2o3_tpu.parallel.mesh import (
        col_axis_name, get_mesh, pad_cols_to_shards, shard_map)
    from h2o3_tpu.utils import metrics as mx

    mesh = get_mesh()
    n_dev = mesh.devices.size
    fr = _ab_frame(rows, cols)
    phases = ("hist_reduce", "winner_gather", "gram_reduce", "gram_gather")

    def measured_reduce_s(iters=10):
        hist = jnp.ones((pad_cols_to_shards(28), 64 * 128, 3), jnp.float32)
        fn = jax.jit(shard_map(
            lambda v: collectives.psum_scatter(
                v, n_dev=n_dev, lane_axis=-1),
            mesh=mesh, in_specs=(Spec(),),
            out_specs=Spec(col_axis_name(mesh)),
            check_vma=False))
        out = fn(hist)
        jax.block_until_ready(out)
        t0 = _time.perf_counter()
        for _ in range(iters):
            out = fn(hist)
        jax.block_until_ready(out)
        return (_time.perf_counter() - t0) / iters

    results = {}
    for mode in ("quant", "exact"):
        os.environ["H2O3_TPU_COLLECTIVE_QUANT"] = (
            "1" if mode == "quant" else "0")
        b0 = {(ph, ln): mx.counter_value(
            "tree_collective_bytes_total", phase=ph, **(
                {"lane": ln} if ln else {}))
            for ph in phases for ln in ("", "quant", "exact")}

        GBM(ntrees=10, max_depth=5, seed=7).train(
            y="label", training_frame=fr)  # compile warmup
        t0 = _time.perf_counter()
        m = GBM(ntrees=10, max_depth=5, seed=7).train(
            y="label", training_frame=fr)
        gbm_s = _time.perf_counter() - t0
        glm = GLM(family="binomial", lambda_=1e-4, max_iterations=20,
                  seed=1).train(y="label", training_frame=fr)

        db = {}
        for ph in phases:
            for ln in ("", "quant", "exact"):
                v = mx.counter_value(
                    "tree_collective_bytes_total", phase=ph, **(
                        {"lane": ln} if ln else {})) - b0[(ph, ln)]
                if v:
                    db[ph if not ln else f"{ph}{{lane={ln}}}"] = round(v, 1)
        rec = {
            "phase": "quant_ab", "mode": mode, "n_devices": n_dev,
            "rows": rows, "cols": cols,
            "quant_block": collectives.quant_block(),
            "gbm_train_s": round(gbm_s, 4),
            "gbm_auc": round(float(m.training_metrics.auc), 5),
            "glm_coef": {k: round(v, 8) for k, v in glm.coef.items()},
            "glm_auc": round(float(glm.training_metrics.auc), 5),
            "collective_bytes": db,
            "measured_hist_reduce_s": round(measured_reduce_s(), 6),
        }
        print(json.dumps(rec), flush=True)
        results[mode] = rec
    os.environ.pop("H2O3_TPU_COLLECTIVE_QUANT", None)
    if len(results) == 2:
        q, e = results["quant"], results["exact"]
        hq = q["collective_bytes"].get("hist_reduce", 0)
        he = e["collective_bytes"].get("hist_reduce", 0)
        coef_delta = max(
            abs(q["glm_coef"][k] - e["glm_coef"][k]) for k in e["glm_coef"])
        print(json.dumps({"quant_ab": {
            "hist_bytes_ratio_exact_over_quant": round(he / max(hq, 1), 2),
            "gram_bytes_ratio_exact_over_quant": round(
                e["collective_bytes"].get("gram_reduce", 0)
                / max(q["collective_bytes"].get("gram_reduce", 0), 1), 2),
            "gbm_auc_delta": round(abs(q["gbm_auc"] - e["gbm_auc"]), 5),
            "glm_coef_max_delta": round(coef_delta, 8),
            "time_ratio_exact_over_quant": round(
                e["gbm_train_s"] / max(q["gbm_train_s"], 1e-9), 3),
            "measured_hist_reduce_s": {
                "quant": q["measured_hist_reduce_s"],
                "exact": e["measured_hist_reduce_s"],
            },
        }}), flush=True)


def oocore_ab(rows: int = 120_000, cols: int = 12) -> None:
    """Streamed-vs-resident out-of-core A/B (H2O3_TPU_HBM_WINDOW_BYTES /
    H2O3_TPU_FRAME_COMPRESS, ISSUE 11) on the SAME mesh and data: the
    streamed mode forces an HBM window of 1/10th of the frame's training
    lanes (rows >= 10x window — the acceptance geometry), the resident
    mode runs today's whole-frame path, and a COMPRESS=0 control proves
    the kill switch routes back to resident. Per mode: GBM train wall
    seconds, AUC, and the peak frame device bytes (streamed = the
    ChunkStore's measured peak, resident = the frame lanes' modeled
    residency), then an {"oocore_ab": ...} summary carrying the acceptance
    pins (peak bounded by the window, rows_over_window >= 10, AUC delta)."""
    import time as _time

    from h2o3_tpu.frame import chunkstore as cs
    from h2o3_tpu.models.tree import GBM
    from h2o3_tpu.parallel.mesh import get_mesh, pad_to_shards
    from h2o3_tpu.utils import metrics as mx

    bytes_per_row = cols + 28  # bins u8 + six f32 lanes + nid i32
    npad = pad_to_shards(rows)
    window = int(npad * bytes_per_row // 10)
    kw = dict(ntrees=10, max_depth=5, seed=7, score_tree_interval=5)
    results = {}
    for mode in ("resident", "streamed", "compress0"):
        os.environ.pop("H2O3_TPU_HBM_WINDOW_BYTES", None)
        os.environ.pop("H2O3_TPU_FRAME_COMPRESS", None)
        if mode == "streamed":
            os.environ["H2O3_TPU_HBM_WINDOW_BYTES"] = str(window)
        elif mode == "compress0":
            os.environ["H2O3_TPU_HBM_WINDOW_BYTES"] = str(window)
            os.environ["H2O3_TPU_FRAME_COMPRESS"] = "0"
        cs.LAST_STORE_STATS.clear()
        e0 = mx.counter_value("frame_chunk_evictions_total")
        fr = _ab_frame(rows, cols)
        GBM(**kw).train(y="label", training_frame=fr)  # compile warmup
        t0 = _time.perf_counter()
        m = GBM(**kw).train(y="label", training_frame=fr)
        dt = _time.perf_counter() - t0
        # the window stats now come from the REGISTRY (ChunkStore.close
        # publishes frame_window_peak_bytes there — same numbers
        # /3/Metrics serves); the dict stays as the geometry alias
        stats = dict(cs.LAST_STORE_STATS)
        streamed = bool(stats.get("n_blocks", 0) > 1)
        peak = (mx.counter_value("frame_window_peak_bytes")
                if streamed else npad * bytes_per_row)
        rec = {
            "phase": "oocore_ab", "mode": mode,
            "n_devices": get_mesh().devices.size,
            "rows": rows, "cols": cols,
            "window_bytes": window if mode != "resident" else 0,
            "streamed": streamed,
            "train_s": round(dt, 4),
            "auc": round(float(m.training_metrics.auc), 5),
            "peak_frame_device_bytes": int(peak),
            "n_blocks": stats.get("n_blocks", 1),
            "block_rows": stats.get("block_rows", npad),
            "evictions": int(
                mx.counter_value("frame_chunk_evictions_total") - e0),
            "prefetch_overlap_s": round(mx.counter_value(
                "frame_prefetch_overlap_seconds"), 4),
        }
        print(json.dumps(rec), flush=True)
        results[mode] = rec
    for k in ("H2O3_TPU_HBM_WINDOW_BYTES", "H2O3_TPU_FRAME_COMPRESS"):
        os.environ.pop(k, None)
    if len(results) == 3:
        r, s, c0 = (results[m] for m in ("resident", "streamed", "compress0"))
        print(json.dumps({"oocore_ab": {
            "rows_over_window": round(
                npad * bytes_per_row / max(window, 1), 2),
            "streamed_engaged": s["streamed"],
            "compress0_stayed_resident": not c0["streamed"],
            "peak_within_window": s["peak_frame_device_bytes"] <= window,
            "peak_bytes_ratio_resident_over_streamed": round(
                r["peak_frame_device_bytes"]
                / max(s["peak_frame_device_bytes"], 1), 2),
            "time_ratio_streamed_over_resident": round(
                s["train_s"] / max(r["train_s"], 1e-9), 3),
            "auc_delta": round(abs(s["auc"] - r["auc"]), 5),
            "compress0_auc_delta": round(abs(c0["auc"] - r["auc"]), 5),
        }}), flush=True)


def fallback_ab(rows: int = 8_000, cols: int = 12) -> None:
    """Fallback-matrix closure A/B (ISSUE 15): for each production shape
    that used to hit a slow lane — monotone GBM, multinomial GLM, dropout
    DL — run the NOW-fused lane against the forced fallback it replaces
    (the respective kill-switch knob), on the SAME mesh and data. Per mode:
    wall seconds + host dispatches; then a {"fallback_ab": ...} summary
    with the parity pins (mono preds allclose fused-vs-fallback on the
    integer-exact data, GLM coef delta <= 2e-3, DL preds <= 1e-4 vs the
    =ctl same-masks control) and the dispatch/wall ratios. The tree lanes
    pin H2O3_TPU_HIST=pallas so the comparison isolates the pipeline."""
    import jax

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.deeplearning import DeepLearning
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.tree import GBM
    from h2o3_tpu.parallel.mesh import get_mesh
    from h2o3_tpu.utils import metrics as mx

    n_dev = int(get_mesh().devices.size)
    summary = {}

    def timed(fn, counter):
        fn()  # compile warmup
        d0 = mx.counter_value(counter)
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        return out, dt, int(mx.counter_value(counter) - d0)

    # ---- (a) monotone GBM: fused whole-tree lane vs the legacy per-level
    # mono loop (H2O3_TPU_SPLIT_FUSE=0) ----
    rng = np.random.default_rng(0)
    df = {"a": rng.integers(0, 50, rows).astype(np.float64)}
    for i in range(cols - 1):
        df[f"x{i}"] = rng.normal(size=rows)
    import pandas as pd

    dfp = pd.DataFrame(df)
    dfp["label"] = (dfp["a"] * 0.1 + 0.5 * dfp["x0"]
                    + 0.1 * rng.normal(size=rows))
    fr_m = Frame.from_pandas(dfp)
    kw_m = dict(ntrees=8, max_depth=5, seed=7,
                monotone_constraints={"a": 1})
    os.environ["H2O3_TPU_HIST"] = "pallas"
    preds = {}
    for mode, fuse in (("fused", "1"), ("fallback", "0")):
        os.environ["H2O3_TPU_SPLIT_FUSE"] = fuse

        def run_m():
            m = GBM(**kw_m).train(y="label", training_frame=fr_m)
            pr = m.predict(fr_m)
            return pr.vec(pr.names[-1]).to_numpy()

        p, dt, disp = timed(run_m, "tree_dispatches_total")
        preds[mode] = p
        rec = {"phase": "fallback_ab", "case": "mono_gbm", "mode": mode,
               "n_devices": n_dev, "rows": rows,
               "train_s": round(dt, 4), "dispatches": disp}
        print(json.dumps(rec), flush=True)
        summary[f"mono_{mode}"] = rec
    os.environ.pop("H2O3_TPU_SPLIT_FUSE", None)
    os.environ.pop("H2O3_TPU_HIST", None)
    mono_delta = float(np.max(np.abs(preds["fused"] - preds["fallback"])))

    # ---- (b) multinomial GLM: fused class-scan chunk vs the host f64
    # cycling loop (H2O3_TPU_GLM_FUSE=0) ----
    K = 3
    X = rng.normal(size=(rows, 5)).astype(np.float32)
    eta = np.stack([X[:, 0], -X[:, 1], 0.5 * X[:, 2]], 1)
    pmat = np.exp(eta)
    pmat /= pmat.sum(1, keepdims=True)
    yk = np.array([rng.choice(K, p=pr_) for pr_ in pmat])
    dfg = pd.DataFrame(X, columns=[f"g{i}" for i in range(5)])
    dfg["label"] = np.array(["a", "b", "c"])[yk]
    fr_g = Frame.from_pandas(dfg)
    kw_g = dict(family="multinomial", max_iterations=10, seed=1,
                objective_epsilon=0.0)
    betas = {}
    for mode, fuse in (("fused", ""), ("fallback", "0")):
        if fuse:
            os.environ["H2O3_TPU_GLM_FUSE"] = fuse
        else:
            os.environ.pop("H2O3_TPU_GLM_FUSE", None)

        def run_g():
            m = GLM(**kw_g).train(y="label", training_frame=fr_g)
            return np.asarray(m.output["beta_multinomial_std"])

        B, dt, disp = timed(run_g, "glm_dispatches_total")
        betas[mode] = B
        rec = {"phase": "fallback_ab", "case": "multinomial_glm",
               "mode": mode, "n_devices": n_dev, "rows": rows,
               "classes": K, "train_s": round(dt, 4), "dispatches": disp}
        print(json.dumps(rec), flush=True)
        summary[f"glm_{mode}"] = rec
    os.environ.pop("H2O3_TPU_GLM_FUSE", None)
    glm_delta = float(np.max(np.abs(betas["fused"] - betas["fallback"])))

    # ---- (c) dropout DL: sharded-grad lane vs the =ctl same-masks
    # replicated control (the parity pin) AND the =0 replicated lane (the
    # wall-clock fallback it replaces) ----
    fr_d = _ab_frame(rows, cols)
    kw_d = dict(hidden=[64], epochs=4, mini_batch_size=256, seed=3,
                activation="RectifierWithDropout",
                hidden_dropout_ratios=[0.3], input_dropout_ratio=0.1)
    dpreds = {}
    for mode, knob in (("fused", None), ("ctl", "ctl"), ("fallback", "0")):
        if knob is None:
            os.environ.pop("H2O3_TPU_DL_GRAD_SHARD", None)
        else:
            os.environ["H2O3_TPU_DL_GRAD_SHARD"] = knob

        def run_d():
            m = DeepLearning(**kw_d).train(y="label", training_frame=fr_d)
            pr = m.predict(fr_d)
            return pr.vec(pr.names[-1]).to_numpy()

        p, dt, disp = timed(run_d, "dl_dispatches_total")
        dpreds[mode] = p
        rec = {"phase": "fallback_ab", "case": "dropout_dl", "mode": mode,
               "n_devices": n_dev, "rows": rows,
               "train_s": round(dt, 4), "dispatches": disp}
        print(json.dumps(rec), flush=True)
        summary[f"dl_{mode}"] = rec
    os.environ.pop("H2O3_TPU_DL_GRAD_SHARD", None)
    dl_ctl_delta = float(np.max(np.abs(dpreds["fused"] - dpreds["ctl"])))

    print(json.dumps({"fallback_ab": {
        # parity pins
        "mono_pred_max_delta": round(mono_delta, 9),
        "glm_coef_max_delta": round(glm_delta, 7),
        "dl_ctl_pred_max_delta": round(dl_ctl_delta, 7),
        # dispatch contracts (the raw-speed coverage claim)
        "mono_dispatch_ratio_fallback_over_fused": round(
            summary["mono_fallback"]["dispatches"]
            / max(summary["mono_fused"]["dispatches"], 1), 2),
        "glm_dispatch_ratio_fallback_over_fused": round(
            summary["glm_fallback"]["dispatches"]
            / max(summary["glm_fused"]["dispatches"], 1), 2),
        # wall ratios (fused must be no worse than the lane it replaces)
        "mono_time_ratio_fused_over_fallback": round(
            summary["mono_fused"]["train_s"]
            / max(summary["mono_fallback"]["train_s"], 1e-9), 3),
        "glm_time_ratio_fused_over_fallback": round(
            summary["glm_fused"]["train_s"]
            / max(summary["glm_fallback"]["train_s"], 1e-9), 3),
        "dl_time_ratio_fused_over_fallback": round(
            summary["dl_fused"]["train_s"]
            / max(summary["dl_fallback"]["train_s"], 1e-9), 3),
    }}), flush=True)


def mesh2d_ab(rows: int = 10_000, cols: int = 28, depth: int = 6,
              trees: int = 4) -> None:
    """1-D vs 2-D mesh A/B (H2O3_TPU_MESH_ROWS, ISSUE 14) on the SAME
    device set and data: the legacy 1-D rows mesh against the 2x4 (and
    4x2) rows×cols pod meshes — per mode, fused tree seconds plus the
    collective bytes BY PHASE (hist_reduce including the 2-D stage-1 exact
    rows psum, winner_gather shrinking to the cols width), then a
    {"mesh2d_ab": ...} summary with the acceptance pins (per-phase bytes
    recorded on every shape; 2-D fused_tree_s no worse than ~1-D on the
    proxy). On the CPU proxy all 8 'devices' are one host's threads — the
    placement claim (exact stage intra-host, quantized stage cross) is the
    queued v5e-16 pod bracket's number; the proxy pins correctness and the
    no-regression bound."""
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.models.tree import shared_tree as st
    from h2o3_tpu.parallel import mesh as pm
    from h2o3_tpu.utils import metrics as mx

    def grad_fn(F, y_, w_):  # gaussian residuals, unit hessian
        return y_ - F, jnp.ones_like(F)

    phases = ("hist_reduce", "winner_gather")
    results = {}
    for mode, shape in (("1d", None), ("2x4", (2, 4)), ("4x2", (4, 2))):
        pm.set_mesh(None if shape is None else pm.make_mesh_2d(*shape))
        n = pm.pad_to_shards(rows)
        rng = np.random.default_rng(0)
        bins = pm.shard_rows(jnp.asarray(
            rng.integers(0, 128, (n, cols)).astype(np.uint8)))
        y = pm.shard_rows(jnp.asarray(rng.normal(size=n).astype(np.float32)))
        w = pm.shard_rows(jnp.ones(n, jnp.float32))
        times = []
        b0 = {ph: mx.counter_value("tree_collective_bytes_total", phase=ph)
              for ph in phases}
        for rep in range(4):  # rep 0 = compile warmup
            preds = pm.shard_rows(jnp.zeros(n, jnp.float32))
            varimp = jnp.zeros(cols, jnp.float32)
            t0 = time.perf_counter()
            out = st.build_trees_scanned(
                bins, w, y, preds, varimp, jax.random.PRNGKey(7), trees,
                grad_fn=grad_fn, grad_key="gaussian-m2d", sample_rate=1.0,
                n_bins=128, is_cat_cols=np.zeros(cols, bool),
                max_depth=depth, min_rows=10.0, min_split_improvement=1e-5,
                learn_rates=np.full(trees, 0.1, np.float32),
                max_abs_leaf=float("inf"), col_sample_rate=1.0,
                col_sample_rate_per_tree=1.0,
            )
            jax.block_until_ready(out[0])
            if rep:
                times.append(time.perf_counter() - t0)
        built = 4 * trees
        by_phase = {
            ph: round((mx.counter_value(
                "tree_collective_bytes_total", phase=ph) - b0[ph]) / built, 1)
            for ph in phases
        }
        rec = {
            "phase": "mesh2d_ab", "mode": mode,
            "mesh": dict(pm.get_mesh().shape),
            "n_devices": int(pm.get_mesh().devices.size),
            "rows": n, "cols": cols, "depth": depth, "trees": trees,
            "fused_tree_s": round(sorted(times)[len(times) // 2] / trees, 4),
            "psum_bytes_by_phase": by_phase,
            "psum_bytes_per_tree": round(sum(by_phase.values()), 1),
        }
        print(json.dumps(rec), flush=True)
        results[mode] = rec
    pm.set_mesh(None)
    if len(results) == 3:
        r1, r2 = results["1d"], results["2x4"]
        print(json.dumps({"mesh2d_ab": {
            "time_ratio_2x4_over_1d": round(
                r2["fused_tree_s"] / max(r1["fused_tree_s"], 1e-9), 3),
            "time_ratio_4x2_over_1d": round(
                results["4x2"]["fused_tree_s"]
                / max(r1["fused_tree_s"], 1e-9), 3),
            "winner_gather_ratio_1d_over_2x4": round(
                r1["psum_bytes_by_phase"]["winner_gather"]
                / max(r2["psum_bytes_by_phase"]["winner_gather"], 1), 2),
            "phases_recorded_all_modes": all(
                all(v > 0 for v in r["psum_bytes_by_phase"].values())
                for r in results.values()),
        }}), flush=True)


def wave2_ab(rows: int = 8_000) -> None:
    """Tree kernel wave-2 A/B (ISSUE 16): GOSS, EFB, u8-code-native frames,
    int16 hist lanes and lossguide growth, each against the baseline path
    on the SAME data, with the forced-off knob controls pinned bit-for-bit.
    One JSON line per case, then a {"wave2_ab": ...} summary carrying the
    acceptance pins: GOSS row-stats ratio >= 2x at AUC delta <= 1e-3, EFB
    C shrink >= 1.5x with bit-equal splits, u8-native rebin traffic cut
    >= 2x across repeated builds, every knob=0 control bit-for-bit."""
    import pandas as pd

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.tree import GBM
    from h2o3_tpu.utils import metrics as mx

    rng = np.random.default_rng(0)
    summary = {}

    def envs(**kv):
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)

    def pred(m, fr, col):
        pr = m.predict(fr)
        return pr.vec(col if col in pr.names else pr.names[-1]).to_numpy()

    def timed(fn):
        t0 = time.perf_counter()
        out = fn()
        return out, time.perf_counter() - t0

    # ---- (a) GOSS: (a=0.2, b=0.1) vs full rows, binomial AUC pin ----
    from sklearn.metrics import roc_auc_score

    # 4x the base rows, a strong signal and modest capacity: the AUC-delta
    # pin wants the CONVERGED regime (both models capture the same signal),
    # not the overfit regime where the sampled fit drifts by more than the
    # pin just from which rows each tree saw
    rows_g = rows * 4
    X = rng.normal(size=(rows_g, 8)).astype(np.float32)
    eta = 3.0 * (1.5 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] * X[:, 3])
    yb = rng.random(rows_g) < 1 / (1 + np.exp(-eta))
    dfc = pd.DataFrame(X, columns=[f"x{i}" for i in range(8)])
    dfc["label"] = np.where(yb, "a", "b")
    fr_c = Frame.from_pandas(dfc)
    trees = 60
    kw_c = dict(ntrees=trees, max_depth=3, seed=7, distribution="bernoulli")
    aucs, gpreds = {}, {}
    for mode, knob in (("baseline", None), ("goss", "0.2,0.1"),
                       ("goss_off", "")):
        envs(H2O3_TPU_TREE_GOSS=knob)
        r0 = mx.counter_value("tree_rows_sampled_total")
        m, dt = timed(lambda: GBM(**kw_c).train(
            y="label", training_frame=fr_c))
        p = pred(m, fr_c, "a")
        gpreds[mode] = p
        aucs[mode] = roc_auc_score(yb, p)
        rec = {"phase": "wave2_ab", "case": "goss", "mode": mode,
               "rows": rows_g, "trees": trees, "train_s": round(dt, 4),
               "auc": round(aucs[mode], 6),
               "rows_sampled": mx.counter_value(
                   "tree_rows_sampled_total") - r0}
        print(json.dumps(rec), flush=True)
        summary[f"goss_{mode}"] = rec
    envs(H2O3_TPU_TREE_GOSS=None)
    # modeled per-level row-stat work: kept rows vs all rows
    kept_frac = summary["goss_goss"]["rows_sampled"] / (fr_c.npad * trees)
    summary["goss_row_stats_ratio"] = round(1.0 / max(kept_frac, 1e-9), 2)
    summary["goss_auc_delta"] = round(
        abs(aucs["baseline"] - aucs["goss"]), 6)
    summary["goss_off_bit_identical"] = bool(
        np.array_equal(gpreds["baseline"], gpreds["goss_off"]))

    # ---- (b) EFB: one-hot design, C shrink + bit-equal splits. The
    # parity frame uses an INTEGER exactly-zero-mean response so the stat
    # lanes stay in-range integers and the default-cell reconstruction is
    # bit-exact (the theorem regime; float lanes carry an f32-associativity
    # envelope and may break equal-gain threshold ties differently) ----
    levels, dense = 12, 3
    g = rng.integers(0, levels, rows // 2)
    yh = (g % 3 - 1).astype(np.float32)
    g = np.concatenate([g, g])
    dfe = pd.DataFrame(
        {f"oh{j}": (g == j).astype(np.float32) for j in range(levels)})
    for j in range(dense):
        dfe[f"d{j}"] = rng.normal(size=rows).astype(np.float32)
    dfe["label"] = (0.7 * (g % 3) + dfe["d0"] - 0.5 * dfe["d1"]
                    + 0.2 * rng.normal(size=rows))
    fr_e = Frame.from_pandas(dfe)
    kw_e = dict(ntrees=8, max_depth=5, seed=7, distribution="gaussian")
    dfp = dfe.drop(columns=["label"]).copy()
    dfp["label"] = np.concatenate([yh, -yh])  # integer sum == exactly 0
    fr_p = Frame.from_pandas(dfp)
    kw_p = dict(ntrees=1, max_depth=5, seed=7, distribution="gaussian")

    def split_structure(m):
        out = []
        for it in m.output["trees"]:
            for t in it:
                h = t.to_host()
                for lv, mk in zip(h.levels, h.real_level_masks()):
                    out.append((np.asarray(lv.split_col)[mk],
                                np.asarray(lv.split_bin)[mk],
                                np.asarray(lv.leaf_now)[mk]))
        return out

    emodels = {}
    for mode, knob in (("baseline", None), ("efb", "1")):
        envs(H2O3_TPU_TREE_EFB=knob)
        c0 = mx.counter_value("tree_cols_bundled_total")
        m, dt = timed(lambda: GBM(**kw_e).train(
            y="label", training_frame=fr_e))
        emodels[mode] = GBM(**kw_p).train(y="label", training_frame=fr_p)
        rec = {"phase": "wave2_ab", "case": "efb", "mode": mode,
               "rows": rows, "cols": levels + dense,
               "train_s": round(dt, 4),
               "cols_bundled": mx.counter_value(
                   "tree_cols_bundled_total") - c0}
        print(json.dumps(rec), flush=True)
        summary[f"efb_{mode}"] = rec
    envs(H2O3_TPU_TREE_EFB=None)
    # C shrink straight from the plan (counter tallies per build/chunk)
    from h2o3_tpu.models.tree.binning import bin_frame, fit_bins, fit_efb

    cols_e = [c for c in dfe.columns if c != "label"]
    spec_e = fit_bins(fr_e, cols_e)
    plan_e = fit_efb(spec_e, bin_frame(spec_e, fr_e), nrow=fr_e.nrow)
    summary["efb_c_shrink"] = round(
        plan_e.n_cols / plan_e.n_cols_b, 2) if plan_e else 1.0
    summary["efb_splits_bit_equal"] = bool(all(
        all(np.array_equal(a, b) for a, b in zip(s0, s1))
        for s0, s1 in zip(split_structure(emodels["baseline"]),
                          split_structure(emodels["efb"]))))

    # ---- (c) u8-code-native frames: rebin HBM traffic across 3 repeated
    # builds over one frame, cache on vs off ----
    rebin = {}
    upreds = {}
    for mode, knob in (("u8cache", None), ("u8cache_off", "0")):
        envs(H2O3_TPU_TREE_U8CACHE=knob)
        fr_u = Frame.from_pandas(dfe)  # fresh frame: empty bin cache
        r0 = mx.counter_value("tree_hist_hbm_bytes_total", path="rebin")
        for rep in range(3):
            m = GBM(**kw_e).train(y="label", training_frame=fr_u)
        upreds[mode] = pred(m, fr_u, "predict")
        rebin[mode] = mx.counter_value(
            "tree_hist_hbm_bytes_total", path="rebin") - r0
        rec = {"phase": "wave2_ab", "case": "u8_native", "mode": mode,
               "rows": rows, "builds": 3, "rebin_bytes": rebin[mode]}
        print(json.dumps(rec), flush=True)
    envs(H2O3_TPU_TREE_U8CACHE=None)
    summary["u8_rebin_bytes_ratio"] = round(
        rebin["u8cache_off"] / max(rebin["u8cache"], 1.0), 2)
    summary["u8_off_bit_identical"] = bool(
        np.array_equal(upreds["u8cache"], upreds["u8cache_off"]))

    # ---- (d) int16 hist lanes: envelope + forced-off control ----
    ipreds = {}
    for mode, knob in (("f32", None), ("i16", "1"), ("i16_off", "0")):
        envs(H2O3_TPU_HIST_I16=knob)
        o0 = mx.counter_value("tree_hist_i16_overflows_total")
        m, dt = timed(lambda: GBM(**kw_e).train(
            y="label", training_frame=fr_e))
        ipreds[mode] = pred(m, fr_e, "predict")
        rec = {"phase": "wave2_ab", "case": "i16", "mode": mode,
               "rows": rows, "train_s": round(dt, 4),
               "overflows": mx.counter_value(
                   "tree_hist_i16_overflows_total") - o0}
        print(json.dumps(rec), flush=True)
    envs(H2O3_TPU_HIST_I16=None)
    yl = dfe["label"].to_numpy()
    rmse = {m: float(np.sqrt(np.mean((p - yl) ** 2)))
            for m, p in ipreds.items()}
    # quantized near-tie splits diverge tree-by-tree; model QUALITY is the
    # envelope that holds (same contract as the parity tests)
    summary["i16_rmse_ratio"] = round(rmse["i16"] / max(rmse["f32"], 1e-9), 4)
    summary["i16_off_bit_identical"] = bool(
        np.array_equal(ipreds["f32"], ipreds["i16_off"]))

    # ---- (e) lossguide: bounded-leaves headline + unbound control ----
    for mode, kw_l in (
            ("depthwise", {}),
            ("lossguide", dict(grow_policy="lossguide", max_leaves=16)),
            ("lossguide_unbound",
             dict(grow_policy="lossguide", max_leaves=2 ** 5))):
        m, dt = timed(lambda: GBM(**kw_e, **kw_l).train(
            y="label", training_frame=fr_e))
        rec = {"phase": "wave2_ab", "case": "lossguide", "mode": mode,
               "rows": rows, "train_s": round(dt, 4),
               "max_n_leaves": max(t.n_leaves
                                   for it in m.output["trees"] for t in it)}
        print(json.dumps(rec), flush=True)
        summary[f"lossguide_{mode}"] = rec
        ipreds[mode] = pred(m, fr_e, "predict")
    summary["lossguide_leaves_bounded"] = bool(
        summary["lossguide_lossguide"]["max_n_leaves"] <= 16)
    summary["lossguide_unbound_bit_identical"] = bool(np.array_equal(
        ipreds["depthwise"], ipreds["lossguide_unbound"]))

    print(json.dumps({"wave2_ab": {
        k: summary[k] for k in (
            "goss_row_stats_ratio", "goss_auc_delta",
            "goss_off_bit_identical", "efb_c_shrink",
            "efb_splits_bit_equal", "u8_rebin_bytes_ratio",
            "u8_off_bit_identical", "i16_rmse_ratio",
            "i16_off_bit_identical", "lossguide_leaves_bounded",
            "lossguide_unbound_bit_identical")
    }}), flush=True)


def munge_ab(rows: int = 200_000) -> None:
    """Compiled munging plane A/B (H2O3_TPU_MUNGE_FUSE, ISSUE 20) on the
    SAME host data per mode: group-by (all value columns' segment stats in
    one mesh-sharded dispatch vs one eager segment-reduce per column),
    join (radix all_to_all gid exchange + device expansion vs global
    lexsort + host np.repeat), sort (one cached key-prep+lexsort program
    vs staged eager), and the 10-op rapids-style expression chain (ONE
    fused program vs 10 eager kernels, counter-proven). One JSON line per
    (case, mode), then a {"munge_ab": ...} summary carrying the acceptance
    pins: fused wall <= 0.5x eager for group-by and join, sort no worse,
    chain dispatches cut >= 5x, joins/sort/chain bit-equal, group-by
    counts/extrema exact with float sums allclose (per-shard accumulation
    + psum reorder f32 addition — bit-parity there is not the contract)."""
    from h2o3_tpu.frame import ops as fops
    from h2o3_tpu.frame.frame import CAT, NUM, Frame, Vec
    from h2o3_tpu.parallel.mesh import get_mesh
    from h2o3_tpu.utils import metrics as mx

    n = rows
    n_dev = int(get_mesh().devices.size)
    rng = np.random.default_rng(0)

    # one host copy of every input: both modes build their frames from the
    # SAME bytes, so parity failures can only come from the compute lanes
    gcard = max(64, n // 2000)
    a = rng.normal(size=n)
    a[::97] = np.nan
    b = rng.normal(size=n)
    c = rng.normal(size=n)
    g = rng.integers(0, gcard, size=n).astype(np.int64)
    # join geometry mirrors bench.py join_10m: right side unique keys
    # (dimension-table shape), left random over them -> out rows == n
    nr = max(n // 10, 8)
    kl = rng.integers(0, nr, size=n).astype(np.float64)
    kr = rng.permutation(nr).astype(np.float64)
    yr = rng.normal(size=nr)

    def gb_frame():
        return Frame(
            [Vec.from_numpy(a, NUM, name="a"),
             Vec.from_numpy(b, NUM, name="b"),
             Vec.from_numpy(c, NUM, name="c"),
             Vec.from_numpy(g, CAT, name="g",
                            domain=[str(i) for i in range(gcard)])],
            ["a", "b", "c", "g"])

    def join_frames():
        L = Frame([Vec.from_numpy(kl, NUM, name="k"),
                   Vec.from_numpy(a, NUM, name="x")], ["k", "x"])
        R = Frame([Vec.from_numpy(kr, NUM, name="k"),
                   Vec.from_numpy(yr, NUM, name="y")], ["k", "y"])
        return L, R

    GB_SPEC = {"a": ["sum", "mean", "min", "max", "count"],
               "b": ["sum", "sd"], "c": ["max", "count"]}

    def timed(fn):
        fn()  # compile warmup
        t0 = time.perf_counter()
        out = fn()
        return out, time.perf_counter() - t0

    results, outs = {}, {}
    for mode in ("fused", "eager"):
        os.environ["H2O3_TPU_MUNGE_FUSE"] = "1" if mode == "fused" else "0"
        fr = gb_frame()
        gb, gb_s = timed(
            lambda: fops.group_by(fr, "g").agg(GB_SPEC).to_pandas())
        L, R = join_frames()
        jn, join_s = timed(
            lambda: fops.merge(L, R, by=["k"]).to_pandas())
        so, sort_s = timed(
            lambda: fops.sort(fr, ["g", "a"],
                              ascending=[True, False]).to_pandas())

        def chain():
            va, vb = fr.vec("a"), fr.vec("b")
            cx = (va * 2.0 + vb) / 3.0          # 3 ops
            d = (cx > 0) & (vb < 1.0)           # 3 ops
            e = fops.ifelse(d, cx, va - vb)     # 2 ops
            return (e * e + 1.0).to_numpy()     # 2 ops
        chain()  # compile warmup (outside the dispatch-count window)
        d0 = {op: mx.counter_value("munge_dispatches_total", op=op)
              for op in ("elementwise", "expr_fuse")}
        t0 = time.perf_counter()
        ch = chain()
        chain_s = time.perf_counter() - t0
        disp = sum(mx.counter_value("munge_dispatches_total", op=op) - d0[op]
                   for op in ("elementwise", "expr_fuse"))

        outs[mode] = {"gb": gb, "jn": jn, "so": so, "ch": ch}
        rec = {"phase": "munge_ab", "mode": mode, "rows": n,
               "n_devices": n_dev, "groupby_groups": gcard,
               "join_out_rows": int(len(jn)),
               "groupby_s": round(gb_s, 4), "join_s": round(join_s, 4),
               "sort_s": round(sort_s, 4), "chain_s": round(chain_s, 4),
               "chain_dispatches": int(disp)}
        print(json.dumps(rec), flush=True)
        results[mode] = rec
    os.environ.pop("H2O3_TPU_MUNGE_FUSE", None)

    def frames_equal(fa, fb, close=()):
        if list(fa.columns) != list(fb.columns) or fa.shape != fb.shape:
            return False
        for col in fa.columns:
            xa, xb = fa[col].to_numpy(), fb[col].to_numpy()
            if xa.dtype == object:
                ok = list(xa) == list(xb)
            elif col in close:
                ok = np.allclose(xa, xb, rtol=1e-5, atol=1e-4,
                                 equal_nan=True)
            else:
                ok = np.array_equal(xa, xb, equal_nan=True)
            if not ok:
                return False
        return True

    f, e = results["fused"], results["eager"]
    gb_close = ("sum_a", "mean_a", "sum_b", "sd_b")
    parity = {
        "groupby_parity_ok": frames_equal(
            outs["fused"]["gb"], outs["eager"]["gb"], close=gb_close),
        "join_bit_equal": frames_equal(outs["fused"]["jn"],
                                       outs["eager"]["jn"]),
        "sort_bit_equal": frames_equal(outs["fused"]["so"],
                                       outs["eager"]["so"]),
        "chain_bit_equal": bool(np.array_equal(
            outs["fused"]["ch"], outs["eager"]["ch"], equal_nan=True)),
    }
    print(json.dumps({"munge_ab": {
        "rows": n, "n_devices": n_dev,
        "groupby_wall_ratio_fused_over_eager": round(
            f["groupby_s"] / max(e["groupby_s"], 1e-9), 3),
        "join_wall_ratio_fused_over_eager": round(
            f["join_s"] / max(e["join_s"], 1e-9), 3),
        "sort_wall_ratio_fused_over_eager": round(
            f["sort_s"] / max(e["sort_s"], 1e-9), 3),
        "chain_wall_ratio_fused_over_eager": round(
            f["chain_s"] / max(e["chain_s"], 1e-9), 3),
        "chain_dispatch_ratio": round(
            e["chain_dispatches"] / max(f["chain_dispatches"], 1), 2),
        **parity,
        "parity_ok": all(parity.values()),
    }}), flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.ops import hist_pallas

    n, c = 1_000_000, 28
    rng = np.random.default_rng(0)
    base_bins = rng.integers(0, 255, (n, c)).astype(np.uint8)
    w = jnp.ones(n, jnp.float32)
    wy = jnp.asarray(rng.normal(size=n).astype(np.float32))

    results = []
    for row_tile in (256, 512, 1024, 2048):
        for col_tile in (4, 8, 14, 28):
            for n_bins in (255, 127, 63):
                for n_nodes in (16, 64):
                    # tiles flow through the knob (static compile key: each
                    # setting compiles its own executable — no stale-cache
                    # clearing, and the exact production read path is what
                    # gets swept)
                    os.environ["H2O3_TPU_PALLAS_TILES"] = (
                        f"{row_tile},{col_tile},{hist_pallas.NODE_TILE}"
                    )
                    bins = jnp.asarray(
                        (base_bins % n_bins).astype(np.uint8)
                    )
                    nid = jnp.asarray(
                        rng.integers(0, n_nodes, n).astype(np.int32)
                    )
                    try:
                        stats = jnp.stack([w, wy, w], 1)  # 3-lane GBM shape
                        fn = lambda: hist_pallas.hist_pallas_local(
                            bins, nid, stats, n_nodes, n_bins,
                            tiles=hist_pallas._tiles(),
                        )
                        out = fn()
                        jax.block_until_ready(out)
                        t0 = time.perf_counter()
                        for _ in range(3):
                            out = fn()
                        jax.block_until_ready(out)
                        dt = (time.perf_counter() - t0) / 3
                        rec = {"row_tile": row_tile, "col_tile": col_tile,
                               "n_bins": n_bins, "n_nodes": n_nodes,
                               "hist_s": round(dt, 4)}
                    except Exception as e:  # noqa: BLE001 — sweep must finish
                        rec = {"row_tile": row_tile, "col_tile": col_tile,
                               "n_bins": n_bins, "n_nodes": n_nodes,
                               "error": repr(e)[:200]}
                    print(json.dumps(rec), flush=True)
                    results.append(rec)
    os.environ.pop("H2O3_TPU_PALLAS_TILES", None)

    ok = [r for r in results if "hist_s" in r]
    if ok:
        best = min(ok, key=lambda r: r["hist_s"])
        print(json.dumps({"best": best}))


if __name__ == "__main__":
    kw = {}
    if "--rows" in sys.argv:
        kw["rows"] = int(sys.argv[sys.argv.index("--rows") + 1])
    if "--split-ab" in sys.argv:
        split_ab(**kw)
    elif "--fused-ab" in sys.argv:
        fused_ab(**kw)
    elif "--glm-ab" in sys.argv:
        glm_ab(**kw)
    elif "--dl-ab" in sys.argv:
        dl_ab(**kw)
    elif "--quant-ab" in sys.argv:
        quant_ab(**kw)
    elif "--oocore-ab" in sys.argv:
        oocore_ab(**kw)
    elif "--fallback-ab" in sys.argv:
        fallback_ab(**kw)
    elif "--mesh2d-ab" in sys.argv:
        mesh2d_ab(**kw)
    elif "--wave2-ab" in sys.argv:
        wave2_ab(**kw)
    elif "--munge-ab" in sys.argv:
        munge_ab(**kw)
    else:
        main()
