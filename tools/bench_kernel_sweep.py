#!/usr/bin/env python
"""Pallas histogram kernel tile sweep on the REAL TPU (run when the tunnel
is up): measures hist time per (ROW_TILE, COL_TILE, n_bins, n_nodes) so the
next kernel iteration picks tiles from data, not guesses.

The kernel's per-step cost is dominated by the VPU indicator build
(∝ ROWS·CT·Bpad) and the MXU dot (M = 4·nt); below 64 nodes the node count
barely matters — bin count and tile sizes are the levers.

    python tools/bench_kernel_sweep.py        # prints one JSON line per cfg
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from h2o3_tpu.ops import hist_pallas

    n, c = 1_000_000, 28
    rng = np.random.default_rng(0)
    base_bins = rng.integers(0, 255, (n, c)).astype(np.uint8)
    w = jnp.ones(n, jnp.float32)
    wy = jnp.asarray(rng.normal(size=n).astype(np.float32))

    results = []
    for row_tile in (256, 512, 1024, 2048):
        for col_tile in (4, 8, 14, 28):
            for n_bins in (255, 127, 63):
                for n_nodes in (16, 64):
                    hist_pallas.ROW_TILE = row_tile
                    hist_pallas.COL_TILE = col_tile
                    # hist_pallas_local is JITTED and its cache keys on
                    # shapes/static args only — the tile module globals are
                    # baked in at trace time, so without this clear every
                    # config after the first would silently re-time the
                    # first-compiled executable under a wrong label
                    hist_pallas.hist_pallas_local.clear_cache()
                    bins = jnp.asarray(
                        (base_bins % n_bins).astype(np.uint8)
                    )
                    nid = jnp.asarray(
                        rng.integers(0, n_nodes, n).astype(np.int32)
                    )
                    try:
                        stats = jnp.stack([w, wy, w], 1)  # 3-lane GBM shape
                        fn = lambda: hist_pallas.hist_pallas_local(
                            bins, nid, stats, n_nodes, n_bins
                        )
                        out = fn()
                        jax.block_until_ready(out)
                        t0 = time.perf_counter()
                        for _ in range(3):
                            out = fn()
                        jax.block_until_ready(out)
                        dt = (time.perf_counter() - t0) / 3
                        rec = {"row_tile": row_tile, "col_tile": col_tile,
                               "n_bins": n_bins, "n_nodes": n_nodes,
                               "hist_s": round(dt, 4)}
                    except Exception as e:  # noqa: BLE001 — sweep must finish
                        rec = {"row_tile": row_tile, "col_tile": col_tile,
                               "n_bins": n_bins, "n_nodes": n_nodes,
                               "error": repr(e)[:200]}
                    print(json.dumps(rec), flush=True)
                    results.append(rec)

    ok = [r for r in results if "hist_s" in r]
    if ok:
        best = min(ok, key=lambda r: r["hist_s"])
        print(json.dumps({"best": best}))


if __name__ == "__main__":
    main()
