#!/usr/bin/env python
"""Regenerate tests/accuracy_expectations.json (the h2o-test-accuracy
successor's stored expectations — SURVEY.md §4).

Run deliberately when an algorithm change is SUPPOSED to move metrics, and
review the JSON diff like any other expectation change:

    python tools/gen_accuracy_expectations.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def main() -> None:
    # same topology as tests/conftest.py: 8-device CPU mesh. The axon TPU
    # plugin registers in sitecustomize at interpreter START, so in-process
    # env edits are too late — re-exec once with the corrected environment
    # (same pattern as __graft_entry__.dryrun_multichip).
    if os.environ.get("_H2O3_ACC_CHILD") != "1":
        env = dict(
            os.environ,
            _H2O3_ACC_CHILD="1",
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS=(
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
        )
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
    sys.path.insert(0, str(ROOT))
    sys.path.insert(0, str(ROOT / "tests"))

    import h2o3_tpu

    h2o3_tpu.init(log_level="WARN")
    from accuracy_cases import run_cases

    results = run_cases(progress=True)
    out = ROOT / "tests" / "accuracy_expectations.json"
    out.write_text(json.dumps(results, indent=2, sort_keys=True) + "\n")
    print(f"wrote {out}")
    for case, metrics in sorted(results.items()):
        print(f"  {case}: {metrics}")


if __name__ == "__main__":
    main()
