#!/bin/sh
# Poll the axon tunnel; the moment it answers, run the TPU measurement
# backlog (which commits each artifact immediately) and exit. Meant to run
# detached (nohup) for the whole round — tunnel windows open without warning
# and last ~2.5 h historically, so reaction latency matters.
cd "$(dirname "$0")/.."

log() { echo "$(date -u +%FT%TZ) $*"; }

while :; do
  if timeout 120 python tools/probe_tunnel.py; then
    log "tunnel UP — running TPU backlog"
    bash tools/run_tpu_backlog.sh
    log "backlog finished rc=$?"
    # The backlog script's exit code is useless as a success signal (its
    # pipelines end in tee, bench.py emits error JSON instead of crashing).
    # Stand down only if the window survived: tunnel still answers AND the
    # newest bench artifact carries a real headline value. A mid-run wedge
    # (the documented failure mode of both previous windows) fails either
    # check and puts us back on watch for the next window.
    if timeout 120 python tools/probe_tunnel.py \
       && python tools/latest_bench_ok.py; then
      log "window captured — standing down"
      exit 0
    fi
    log "window lost mid-run — resuming watch"
  fi
  log "tunnel down; sleeping"
  sleep 480
done
