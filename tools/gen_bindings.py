#!/usr/bin/env python
"""Bindings codegen — successor of the ``h2o-bindings`` generator
[UNVERIFIED upstream paths, SURVEY.md §2.3]: upstream generates the per-algo
Python/R estimator classes from the live REST schemas; here the params
dataclasses ARE the schema source, and this tool renders them into a
standalone, dependency-explicit estimators module (one class per algo, every
parameter an explicit keyword argument with its default and type in the
signature — greppable and IDE-completable, unlike the runtime-generated
classes in h2o3_tpu/estimators.py which stay the import-light default).

Usage:  python tools/gen_bindings.py [out.py]
"""

from __future__ import annotations

import dataclasses
import sys

HEADER = '''"""GENERATED FILE — do not edit. Regenerate with tools/gen_bindings.py.

Explicit per-algorithm estimator classes rendered from the builder params
dataclasses (the codegen analog of upstream's h2o-bindings output).
"""

from h2o3_tpu.estimators import _EstimatorBase


'''

ALGOS = [
    ("H2OGradientBoostingEstimator", "GBM"),
    ("H2OXGBoostEstimator", "XGBoost"),
    ("H2ORandomForestEstimator", "DRF"),
    ("H2OXRTEstimator", "XRT"),
    ("H2OGeneralizedLinearEstimator", "GLM"),
    ("H2ODeepLearningEstimator", "DeepLearning"),
    ("H2OKMeansEstimator", "KMeans"),
    ("H2OPrincipalComponentAnalysisEstimator", "PCA"),
    ("H2OSingularValueDecompositionEstimator", "SVD"),
    ("H2ONaiveBayesEstimator", "NaiveBayes"),
    ("H2OIsolationForestEstimator", "IsolationForest"),
    ("H2OExtendedIsolationForestEstimator", "ExtendedIsolationForest"),
    ("H2OGeneralizedLowRankEstimator", "GLRM"),
    ("H2OCoxProportionalHazardsEstimator", "CoxPH"),
    ("H2OIsotonicRegressionEstimator", "IsotonicRegression"),
    ("H2OAdaBoostEstimator", "AdaBoost"),
    ("H2ODecisionTreeEstimator", "DT"),
    ("H2OWord2vecEstimator", "Word2Vec"),
    ("H2OStackedEnsembleEstimator", "StackedEnsemble"),
    ("H2OTargetEncoderEstimator", "TargetEncoder"),
    ("H2ORuleFitEstimator", "RuleFit"),
    ("H2OUpliftRandomForestEstimator", "UpliftDRF"),
    ("H2OGeneralizedAdditiveEstimator", "GAM"),
    ("H2OModelSelectionEstimator", "ModelSelection"),
    ("H2OANOVAGLMEstimator", "ANOVAGLM"),
    ("H2OAggregatorEstimator", "Aggregator"),
    ("H2OInfogramEstimator", "Infogram"),
    ("H2OSupportVectorMachineEstimator", "PSVM"),
    ("H2OHGLMEstimator", "HGLM"),
]


def _val_repr(v) -> str:
    if isinstance(v, float):
        if v != v:
            return 'float("nan")'
        if v in (float("inf"), float("-inf")):
            return f'float("{"" if v > 0 else "-"}inf")'
    return repr(v)


def _default_repr(f: dataclasses.Field) -> str:
    if f.default is not dataclasses.MISSING:
        return _val_repr(f.default)
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        return _val_repr(f.default_factory())
    return "None"


def render() -> str:
    from h2o3_tpu import models as M

    out = [HEADER]
    for cls_name, builder in ALGOS:
        params_cls = getattr(M, builder).PARAMS_CLS
        fields = [f for f in dataclasses.fields(params_cls)
                  if f.name not in ("training_frame", "validation_frame")]
        sig_lines = [f"        {f.name}={_default_repr(f)}," for f in fields]
        kw_lines = [f"            {f.name}={f.name}," for f in fields]
        doc_lines = [
            f"    {f.name}: {getattr(f.type, '__name__', f.type)}"
            f" (default {_default_repr(f)})"
            for f in fields
        ]
        out.append(
            f"class {cls_name}(_EstimatorBase):\n"
            f'    """{builder} estimator (generated).\n\n'
            "    Parameters\n    ----------\n"
            + "\n".join(doc_lines)
            + '\n    """\n\n'
            f'    _BUILDER = "{builder}"\n\n'
            "    def __init__(\n        self,\n        model_id=None,\n"
            + "\n".join(sig_lines)
            + "\n    ):\n"
            "        kw = dict(\n"
            + "\n".join(kw_lines)
            + "\n        )\n"
            "        defaults = {\n"
            + "\n".join(
                f"            {f.name!r}: {_default_repr(f)}," for f in fields
            )
            + "\n        }\n"
            "        kw = {k: v for k, v in kw.items() if v != defaults[k]}\n"
            "        super().__init__(model_id=model_id, **kw)\n\n"
        )
    out.append(
        "__all__ = [\n"
        + "\n".join(f"    {n!r}," for n, _ in ALGOS)
        + "\n]\n"
    )
    return "\n".join(out)


if __name__ == "__main__":
    dest = sys.argv[1] if len(sys.argv) > 1 else "h2o3_tpu/estimators_gen.py"
    code = render()
    with open(dest, "w") as f:
        f.write(code)
    print(f"wrote {dest} ({len(code.splitlines())} lines, {len(ALGOS)} classes)")
