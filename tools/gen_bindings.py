#!/usr/bin/env python
"""Bindings codegen — successor of the ``h2o-bindings`` generator
[UNVERIFIED upstream paths, SURVEY.md §2.3]: upstream generates the per-algo
Python/R estimator classes from the live REST schemas; here the params
dataclasses ARE the schema source, and this tool renders them into a
standalone, dependency-explicit estimators module (one class per algo, every
parameter an explicit keyword argument with its default and type in the
signature — greppable and IDE-completable, unlike the runtime-generated
classes in h2o3_tpu/estimators.py which stay the import-light default).

Usage:  python tools/gen_bindings.py [out.py]
"""

from __future__ import annotations

import dataclasses
import sys

HEADER = '''"""GENERATED FILE — do not edit. Regenerate with tools/gen_bindings.py.

Explicit per-algorithm estimator classes rendered from the builder params
dataclasses (the codegen analog of upstream's h2o-bindings output).
"""

from h2o3_tpu.estimators import _EstimatorBase


'''

ALGOS = [
    ("H2OGradientBoostingEstimator", "GBM"),
    ("H2OXGBoostEstimator", "XGBoost"),
    ("H2ORandomForestEstimator", "DRF"),
    ("H2OXRTEstimator", "XRT"),
    ("H2OGeneralizedLinearEstimator", "GLM"),
    ("H2ODeepLearningEstimator", "DeepLearning"),
    ("H2OKMeansEstimator", "KMeans"),
    ("H2OPrincipalComponentAnalysisEstimator", "PCA"),
    ("H2OSingularValueDecompositionEstimator", "SVD"),
    ("H2ONaiveBayesEstimator", "NaiveBayes"),
    ("H2OIsolationForestEstimator", "IsolationForest"),
    ("H2OExtendedIsolationForestEstimator", "ExtendedIsolationForest"),
    ("H2OGeneralizedLowRankEstimator", "GLRM"),
    ("H2OCoxProportionalHazardsEstimator", "CoxPH"),
    ("H2OIsotonicRegressionEstimator", "IsotonicRegression"),
    ("H2OAdaBoostEstimator", "AdaBoost"),
    ("H2ODecisionTreeEstimator", "DT"),
    ("H2OWord2vecEstimator", "Word2Vec"),
    ("H2OStackedEnsembleEstimator", "StackedEnsemble"),
    ("H2OTargetEncoderEstimator", "TargetEncoder"),
    ("H2ORuleFitEstimator", "RuleFit"),
    ("H2OUpliftRandomForestEstimator", "UpliftDRF"),
    ("H2OGeneralizedAdditiveEstimator", "GAM"),
    ("H2OModelSelectionEstimator", "ModelSelection"),
    ("H2OANOVAGLMEstimator", "ANOVAGLM"),
    ("H2OAggregatorEstimator", "Aggregator"),
    ("H2OInfogramEstimator", "Infogram"),
    ("H2OSupportVectorMachineEstimator", "PSVM"),
    ("H2OHGLMEstimator", "HGLM"),
]


def _val_repr(v) -> str:
    if isinstance(v, float):
        if v != v:
            return 'float("nan")'
        if v in (float("inf"), float("-inf")):
            return f'float("{"" if v > 0 else "-"}inf")'
    return repr(v)


def _default_repr(f: dataclasses.Field) -> str:
    if f.default is not dataclasses.MISSING:
        return _val_repr(f.default)
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        return _val_repr(f.default_factory())
    return "None"


def render() -> str:
    from h2o3_tpu import models as M

    out = [HEADER]
    for cls_name, builder in ALGOS:
        params_cls = getattr(M, builder).PARAMS_CLS
        fields = [f for f in dataclasses.fields(params_cls)
                  if f.name not in ("training_frame", "validation_frame")]
        sig_lines = [f"        {f.name}={_default_repr(f)}," for f in fields]
        kw_lines = [f"            {f.name}={f.name}," for f in fields]
        doc_lines = [
            f"    {f.name}: {getattr(f.type, '__name__', f.type)}"
            f" (default {_default_repr(f)})"
            for f in fields
        ]
        out.append(
            f"class {cls_name}(_EstimatorBase):\n"
            f'    """{builder} estimator (generated).\n\n'
            "    Parameters\n    ----------\n"
            + "\n".join(doc_lines)
            + '\n    """\n\n'
            f'    _BUILDER = "{builder}"\n\n'
            "    def __init__(\n        self,\n        model_id=None,\n"
            + "\n".join(sig_lines)
            + "\n    ):\n"
            "        kw = dict(\n"
            + "\n".join(kw_lines)
            + "\n        )\n"
            "        defaults = {\n"
            + "\n".join(
                f"            {f.name!r}: {_default_repr(f)}," for f in fields
            )
            + "\n        }\n"
            "        kw = {k: v for k, v in kw.items() if v != defaults[k]}\n"
            "        super().__init__(model_id=model_id, **kw)\n\n"
        )
    out.append(
        "__all__ = [\n"
        + "\n".join(f"    {n!r}," for n, _ in ALGOS)
        + "\n]\n"
    )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# R emitter — the gen_R.py analog: explicit-argument h2o.* functions (the
# upstream R package's per-algo surface) rendered from the same dataclasses.

R_HEADER = '''# GENERATED FILE — do not edit. Regenerate with tools/gen_bindings.py.
#
# Explicit per-algorithm h2o.* training functions with every parameter as a
# named argument with its default (the gen_R.py codegen analog, SURVEY.md
# §2.3 [UNVERIFIED upstream path h2o-bindings/bin/gen_R.py]). Requires
# h2o3tpu.R to be sourced first (.h2o.req / .h2o.train helpers). Only
# arguments the caller actually supplies are sent to the server (missing()
# check), so server-side defaults stay authoritative.

.h2o.train_params <- function(algo, y, x, training_frame, validation_frame,
                              params) {
  stopifnot(inherits(training_frame, "H2O3Frame"))
  # delegate to h2o3tpu.R's .h2o.train so job-wait / model-resolution
  # logic lives in exactly one place
  do.call(.h2o.train, c(
    list(algo, y = y, x = x, training_frame = training_frame,
         validation_frame = validation_frame),
    params))
}

'''

# h2o.* function name per builder (upstream R verb where one exists)
R_NAMES = {
    "GBM": "h2o.gbm", "XGBoost": "h2o.xgboost", "DRF": "h2o.randomForest",
    "XRT": "h2o.xrt", "GLM": "h2o.glm", "DeepLearning": "h2o.deeplearning",
    "KMeans": "h2o.kmeans", "PCA": "h2o.prcomp", "SVD": "h2o.svd",
    "NaiveBayes": "h2o.naiveBayes", "IsolationForest": "h2o.isolationForest",
    "ExtendedIsolationForest": "h2o.extendedIsolationForest",
    "GLRM": "h2o.glrm", "CoxPH": "h2o.coxph",
    "IsotonicRegression": "h2o.isotonicregression", "AdaBoost": "h2o.adaBoost",
    "DT": "h2o.decision_tree", "Word2Vec": "h2o.word2vec",
    "StackedEnsemble": "h2o.stackedEnsemble",
    "TargetEncoder": "h2o.targetencoder", "RuleFit": "h2o.rulefit",
    "UpliftDRF": "h2o.upliftRandomForest", "GAM": "h2o.gam",
    "ModelSelection": "h2o.modelSelection", "ANOVAGLM": "h2o.anovaglm",
    "Aggregator": "h2o.aggregator", "Infogram": "h2o.infogram",
    "PSVM": "h2o.psvm", "HGLM": "h2o.hglm",
}

# REST algo path per builder (mirrors the server's builder registry names)
R_ALGOS = {
    "GBM": "gbm", "XGBoost": "xgboost", "DRF": "drf", "XRT": "xrt",
    "GLM": "glm", "DeepLearning": "deeplearning", "KMeans": "kmeans",
    "PCA": "pca", "SVD": "svd", "NaiveBayes": "naivebayes",
    "IsolationForest": "isolationforest",
    "ExtendedIsolationForest": "extendedisolationforest", "GLRM": "glrm",
    "CoxPH": "coxph", "IsotonicRegression": "isotonicregression",
    "AdaBoost": "adaboost", "DT": "decisiontree", "Word2Vec": "word2vec",
    "StackedEnsemble": "stackedensemble", "TargetEncoder": "targetencoder",
    "RuleFit": "rulefit", "UpliftDRF": "upliftdrf", "GAM": "gam",
    "ModelSelection": "modelselection", "ANOVAGLM": "anovaglm",
    "Aggregator": "aggregator", "Infogram": "infogram", "PSVM": "psvm",
    "HGLM": "hglm",
}


def _r_val(v) -> str:
    """Python default -> R literal."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, float):
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "Inf"
        if v == float("-inf"):
            return "-Inf"
        return repr(v)
    if isinstance(v, int):
        return str(v)
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (tuple, list)):
        if not v:
            return "c()"
        return "c(" + ", ".join(_r_val(x) for x in v) + ")"
    if isinstance(v, dict):
        if not v:
            return "list()"
        return "list(" + ", ".join(
            f"{k} = {_r_val(x)}" for k, x in v.items()) + ")"
    raise TypeError(f"no R literal for {v!r} ({type(v)})")


# R argument names where upstream's differs from the dataclass field (the
# server accepts these as PARAM_ALIASES on the builder)
R_FIELD_NAMES = {"lambda_": "lambda"}


def _r_name(name: str) -> str:
    """Upstream R argument name, escaped if it collides with R syntax."""
    name = R_FIELD_NAMES.get(name, name)
    reserved = {
        "if", "else", "repeat", "while", "function", "for", "in", "next",
        "break", "TRUE", "FALSE", "NULL", "Inf", "NaN", "NA",
    }
    return f"`{name}`" if name in reserved else name


def render_r() -> str:
    import dataclasses as dc

    from h2o3_tpu import models as M

    out = [R_HEADER]
    for _, builder in ALGOS:
        rname = R_NAMES[builder]
        algo = R_ALGOS[builder]
        params_cls = getattr(M, builder).PARAMS_CLS
        fields = [
            f for f in dc.fields(params_cls)
            if f.name not in ("training_frame", "validation_frame",
                              "response_column")
        ]
        defaults = {}
        for f in fields:
            if f.default is not dc.MISSING:
                defaults[f.name] = f.default
            elif f.default_factory is not dc.MISSING:  # type: ignore[misc]
                defaults[f.name] = f.default_factory()
            else:
                defaults[f.name] = None
        args = [f"{_r_name(f.name)} = {_r_val(defaults[f.name])}"
                for f in fields]
        sig = ",\n    ".join(
            ["y = NULL", "x = NULL", "training_frame", "validation_frame = NULL"]
            + args
        )
        collect = "\n".join(
            f'  if (!missing({_r_name(f.name)})) p${_r_name(f.name)} <- '
            f'{_r_name(f.name)}'
            for f in fields
        )
        out.append(
            f"{rname} <- function(\n    {sig}\n) {{\n"
            "  p <- list()\n"
            f"{collect}\n"
            f'  .h2o.train_params("{algo}", y, x, training_frame, '
            "validation_frame, p)\n"
            "}\n\n"
        )
    return "".join(out)


if __name__ == "__main__":
    dest = sys.argv[1] if len(sys.argv) > 1 else "h2o3_tpu/estimators_gen.py"
    code = render()
    with open(dest, "w") as f:
        f.write(code)
    print(f"wrote {dest} ({len(code.splitlines())} lines, {len(ALGOS)} classes)")
    r_dest = sys.argv[2] if len(sys.argv) > 2 else "r/estimators_gen.R"
    r_code = render_r()
    with open(r_dest, "w") as f:
        f.write(r_code)
    print(f"wrote {r_dest} ({len(r_code.splitlines())} lines, {len(R_NAMES)} functions)")
