#!/usr/bin/env python
"""Open-loop load harness for the scoring tier (ISSUE 7): Poisson arrivals
against ``POST /3/Predictions/rows``, swept over offered QPS, measuring
p50/p99 latency, shed rate, and the server's batch-occupancy histogram.

Open loop is the point: arrivals are scheduled by a Poisson process at the
OFFERED rate regardless of completions (a closed loop self-throttles and
hides saturation — the classic coordinated-omission trap). Each mode runs
against a fresh server SUBPROCESS so client and server never share a GIL and
the A/B is honest:

- ``batched``  — the coalescing tier at its default window
  (H2O3_TPU_SCORE_BATCH_WINDOW_MS), one device dispatch per micro-batch;
- ``control``  — the same route with the window forced to 0: one device
  dispatch per request, the pre-tier behavior.

Artifact (one JSON line on stdout, also written to --out): per-step
latency/shed/occupancy numbers plus a summary with each mode's sustained
QPS (highest offered rate with shed+error rate <= 1% and achieved >= 90% of
offered), the p99 at that rate, and a batched-vs-control byte-parity probe.
``tools/latest_bench_ok.py`` sanity-checks the newest artifact; the A/B is
queued for real-TPU windows in ``tools/run_tpu_backlog.sh``.

Usage::

    python tools/load_test.py                          # spawn servers, both modes
    python tools/load_test.py --mode batched --qps 200,800
    python tools/load_test.py --url http://host:54321 --model gbm_x  # external

The committed CPU-proxy artifact runs with JAX_PLATFORMS=cpu and
XLA_FLAGS=--xla_force_host_platform_device_count=8 (the same 8-device mesh
the tier-1 suite uses).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# the scoring workload: a fixed synthetic model + row pool, deterministic on
# both sides of the subprocess boundary


def _train_df(n: int = 40_000, seed: int = 9):
    import pandas as pd

    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n), "b": rng.normal(size=n),
        "c": rng.normal(size=n), "d": rng.normal(size=n),
        "e": rng.normal(size=n),
        "f": rng.choice(["u", "v", "w"], n),
    })
    logit = df["a"] * 0.8 - df["b"] * 0.5 + (df["f"] == "v") * 0.7
    df["y"] = np.where(
        rng.random(n) < 1 / (1 + np.exp(-logit)), "pos", "neg")
    df.loc[::31, "a"] = np.nan
    return df


def _row_pool(n: int = 512, seed: int = 123) -> list[dict]:
    rng = np.random.default_rng(seed)
    pool = []
    for i in range(n):
        row = {
            "a": None if i % 29 == 0 else float(rng.normal()),
            "b": float(rng.normal()), "c": float(rng.normal()),
            "d": float(rng.normal()), "e": float(rng.normal()),
            "f": ["u", "v", "w", "NEW_LEVEL"][int(rng.integers(0, 4))],
        }
        pool.append(row)
    return pool


def _serve(args) -> None:
    """Server-subprocess mode: boot a cloud, train the workload model,
    serve REST, print the READY line the parent parses."""
    import h2o3_tpu
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models import GBM

    h2o3_tpu.init(log_level="WARN")
    fr = Frame.from_pandas(_train_df(), destination_frame="load_train")
    model = GBM(ntrees=20, max_depth=5, seed=1).train(
        y="y", training_frame=fr)
    # warm the scorer program for the single-row bucket so the first
    # measured request doesn't pay the compile
    from h2o3_tpu import serving

    serving.scorer_for(model)
    serving.score_rows(model, [_row_pool(1)[0]])
    srv = start_server(port=args.port)
    print(f"READY {srv.url} {model.key}", flush=True)
    while True:
        time.sleep(3600)


def _serve_fleet(args) -> None:
    """Fleet server-subprocess mode (--fleet): train M models, export each
    through serialize_model into the watch dir (H2O3_TPU_SERVE_WATCH_DIR —
    set by the parent), let the serving REGISTRY load them (the real
    rollout path), size the HBM budget to H2O3_TPU_FLEET_OVERSUB× less
    than the fleet's total scorer bytes (0 = unbounded, the all-resident
    control), and serve REST."""
    import h2o3_tpu
    from h2o3_tpu import persist, serving
    from h2o3_tpu.api.server import start_server
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models import GBM
    from h2o3_tpu.serving.registry import REGISTRY
    from h2o3_tpu.serving.residency import MANAGER

    h2o3_tpu.init(log_level="WARN")
    watch = os.environ["H2O3_TPU_SERVE_WATCH_DIR"]
    oversub = int(os.environ.get("H2O3_TPU_FLEET_OVERSUB", "0"))
    fr = Frame.from_pandas(_train_df(), destination_frame="fleet_train")
    keys = []
    for i in range(args.models):
        m = GBM(ntrees=8, max_depth=4, seed=100 + i).train(
            y="y", training_frame=fr)
        persist.save_model(m, os.path.join(watch, f"fleet_model_{i:03d}"))
        keys.append(m.key)
    loaded = REGISTRY.poll_once()
    assert loaded == args.models, (loaded, args.models)
    # stack every registry-served model's HOST payload first (scorer_for
    # uploads nothing), size the budget from the measured fleet bytes,
    # THEN warm-score — so every device upload happens under the budget
    # and hbm_peak_bytes is an honest bound
    for k in keys:
        serving.scorer_for(REGISTRY.resolve(k))
    total = MANAGER.status()["host_bytes"]
    if oversub > 0:
        os.environ["H2O3_TPU_SERVE_HBM_BYTES"] = str(
            max(total // oversub, 1))
    probe = _row_pool(1)[0]
    for k in keys:
        serving.score_rows(REGISTRY.resolve(k), [probe])
    srv = start_server(port=args.port)
    print(f"READY {srv.url} {','.join(keys)} total_bytes={total} "
          f"budget={os.environ.get('H2O3_TPU_SERVE_HBM_BYTES', '0')}",
          flush=True)
    while True:
        time.sleep(3600)


# ---------------------------------------------------------------------------
# client side


def _post_rows(url: str, model_key: str, rows: list[dict],
               timeout: float = 15.0):
    body = json.dumps({"model": model_key, "rows": rows}).encode()
    req = urllib.request.Request(
        url + "/3/Predictions/rows", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _scrape_hist(url: str, family: str, labels: dict | None = None):
    """(buckets, sum, count) of one histogram child. ``labels`` selects the
    child whose labels contain them (dispatch_device_seconds{site=...});
    None keeps the old first-child behavior (unlabeled families)."""
    try:
        with urllib.request.urlopen(url + "/3/Metrics?format=json",
                                    timeout=10) as r:
            fam = json.loads(r.read())["families"].get(family)
        if not fam or not fam["values"]:
            return {}, 0.0, 0
        v = None
        if labels is None:
            v = fam["values"][0]
        else:
            for cand in fam["values"]:
                if all(cand["labels"].get(k) == lv
                       for k, lv in labels.items()):
                    v = cand
                    break
        if v is None:
            return {}, 0.0, 0
        return dict(v["buckets"]), float(v["sum"]), int(v["count"])
    except Exception as e:  # noqa: BLE001 — metrics are best-effort here
        _log(f"metrics scrape failed: {e!r}")
        return {}, 0.0, 0


def _leg_stats(h0, h1) -> dict:
    """Per-step delta stats for one latency leg (two _scrape_hist results):
    request count, mean ms, and the bucket upper bound covering p99 —
    bucket-resolution, which is what the batch-window tuner needs."""
    b0, s0, c0 = h0
    b1, s1, c1 = h1
    n = c1 - c0
    if n <= 0:
        return {"count": 0}
    out = {"count": n, "mean_ms": round((s1 - s0) / n * 1e3, 3)}
    prev1 = prev0 = 0
    acc = 0.0
    for le in b1:
        c0le = b0.get(le, 0) if b0 else 0
        acc += (b1[le] - prev1) - (c0le - prev0)
        prev1, prev0 = b1[le], c0le
        if acc >= 0.99 * n:
            out["p99_le_ms"] = (None if le == "+Inf"
                                else round(float(le) * 1e3, 3))
            break
    return out


def _run_step(url: str, model_key: str, qps: float, duration: float,
              rows_per_req: int, threads: int, pool: list[dict],
              model_pick=None) -> dict:
    """One offered-QPS step. ``model_pick`` (fleet mode) is a deterministic
    per-arrival model-key array — Zipf-distributed traffic over the fleet
    instead of one hot key."""
    rng = np.random.default_rng(int(qps * 1000) ^ 0x5EED)
    gaps = rng.exponential(1.0 / qps, size=int(qps * duration * 1.2) + 8)
    arrivals = np.cumsum(gaps)
    arrivals = arrivals[arrivals < duration]
    occ0 = _scrape_hist(url, "serving_batch_occupancy")
    rows0 = _scrape_hist(url, "serving_batch_rows")
    # per-request latency legs, from the tracing plane: time queued in the
    # batcher, device time in the coalesced dispatch, residency page-ins
    qw0 = _scrape_hist(url, "job_queue_wait_seconds")
    dd0 = _scrape_hist(url, "dispatch_device_seconds",
                       {"site": "serving_batch"})
    pi0 = _scrape_hist(url, "serving_page_in_seconds")

    idx_lock = threading.Lock()
    nxt = [0]
    lat_ms: list[float] = []
    shed = [0]
    errors = [0]
    unsent = [0]
    last_done = [0.0]  # span of actual completions — the throughput base
    lat_lock = threading.Lock()
    t0 = time.monotonic()
    # hard wall for the step: an overloaded server must not let the client
    # spend minutes draining its arrival backlog — arrivals the client could
    # not even ISSUE inside the window are unsustained offered load and are
    # counted against the rate like sheds
    cutoff = t0 + duration + 2.0

    def worker():
        import urllib.error

        while True:
            with idx_lock:
                i = nxt[0]
                if i >= len(arrivals):
                    return
                nxt[0] += 1
            if time.monotonic() > cutoff:
                with lat_lock:
                    unsent[0] += 1
                continue
            delay = t0 + arrivals[i] - time.monotonic()
            if delay > 0:
                time.sleep(delay)  # behind schedule -> fire immediately
            rows = [pool[(i * rows_per_req + j) % len(pool)]
                    for j in range(rows_per_req)]
            mk = (model_key if model_pick is None
                  else model_pick[i % len(model_pick)])
            r0 = time.monotonic()
            try:
                _post_rows(url, mk, rows)
                done = time.monotonic()
                with lat_lock:
                    lat_ms.append((done - r0) * 1e3)
                    last_done[0] = max(last_done[0], done - t0)
            except urllib.error.HTTPError as e:
                with lat_lock:
                    if e.code in (429, 503, 504):
                        shed[0] += 1
                    else:
                        errors[0] += 1
            except Exception:  # noqa: BLE001 — timeouts/conn resets
                with lat_lock:
                    errors[0] += 1

    ts = [threading.Thread(target=worker, daemon=True)
          for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=duration + 90)
    wall = max(last_done[0], duration)

    occ1 = _scrape_hist(url, "serving_batch_occupancy")
    rows1 = _scrape_hist(url, "serving_batch_rows")
    d_occ_count = occ1[2] - occ0[2]
    d_occ_sum = occ1[1] - occ0[1]
    hist = {}
    if rows1[0]:
        # de-cumulate the Prometheus buckets into per-bucket deltas
        prev1 = prev0 = 0
        for le in rows1[0]:
            c1 = rows1[0][le]
            c0 = rows0[0].get(le, 0) if rows0[0] else 0
            hist[le] = (c1 - prev1) - (c0 - prev0)
            prev1, prev0 = c1, c0
        hist = {k: v for k, v in hist.items() if v}
    sent = len(arrivals)
    ok = len(lat_ms)
    lat = np.sort(np.asarray(lat_ms)) if lat_ms else np.asarray([])

    def pct(p):
        return round(float(lat[min(int(len(lat) * p), len(lat) - 1)]), 3) \
            if len(lat) else None

    step = {
        "offered_qps": qps, "duration_s": duration, "sent": sent,
        "ok": ok, "shed": shed[0], "errors": errors[0],
        "unsent": unsent[0],
        "achieved_qps": round(ok / wall, 1) if wall > 0 else 0.0,
        "shed_rate": round(
            (shed[0] + errors[0] + unsent[0]) / max(sent, 1), 4),
        "p50_ms": pct(0.50), "p90_ms": pct(0.90), "p99_ms": pct(0.99),
        "mean_batch_occupancy": (
            round(d_occ_sum / d_occ_count, 2) if d_occ_count else None),
        "batch_rows_hist": hist,
        "latency_breakdown": {
            "queue_wait": _leg_stats(
                qw0, _scrape_hist(url, "job_queue_wait_seconds")),
            "dispatch": _leg_stats(
                dd0, _scrape_hist(url, "dispatch_device_seconds",
                                  {"site": "serving_batch"})),
            "page_in": _leg_stats(
                pi0, _scrape_hist(url, "serving_page_in_seconds")),
        },
    }
    return step


def _spawn_server(mode: str, window_ms: str | None) -> tuple:
    env = dict(os.environ)
    env.setdefault("H2O3_TPU_LOG_LEVEL", "WARN")
    if mode == "control":
        env["H2O3_TPU_SCORE_BATCH_WINDOW_MS"] = "0"
    elif window_ms is not None:
        env["H2O3_TPU_SCORE_BATCH_WINDOW_MS"] = window_ms
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=ROOT)
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline:
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(f"{mode} server died (rc={p.poll()})")
        if line.startswith("READY "):
            _, url, model_key = line.split()
            _log(f"{mode} server up at {url} (model {model_key})")
            return p, url, model_key
    p.kill()
    raise RuntimeError(f"{mode} server never became ready")


def _spawn_fleet_server(mode: str, args, watch_dir: str) -> tuple:
    """mode 'oversub' bounds HBM to total/oversub; 'resident' leaves the
    budget unbounded (the all-resident control)."""
    env = dict(os.environ)
    env.setdefault("H2O3_TPU_LOG_LEVEL", "WARN")
    env["H2O3_TPU_SERVE_WATCH_DIR"] = watch_dir
    env["H2O3_TPU_FLEET_OVERSUB"] = (
        str(args.oversub) if mode == "oversub" else "0")
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve-fleet",
         "--port", "0", "--models", str(args.models)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=ROOT)
    deadline = time.monotonic() + 900
    while time.monotonic() < deadline:
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(f"fleet {mode} server died (rc={p.poll()})")
        if line.startswith("READY "):
            parts = line.split()
            url, keys = parts[1], parts[2].split(",")
            extra = dict(kv.split("=") for kv in parts[3:])
            _log(f"fleet {mode} server up at {url}: {len(keys)} models, "
                 f"total_bytes={extra.get('total_bytes')} "
                 f"budget={extra.get('budget')}")
            return p, url, keys, extra
    p.kill()
    raise RuntimeError(f"fleet {mode} server never became ready")


def _scrape_registry(url: str) -> dict:
    try:
        with urllib.request.urlopen(url + "/3/ServingRegistry",
                                    timeout=10) as r:
            return json.loads(r.read())
    except Exception as e:  # noqa: BLE001 — observability is best-effort
        _log(f"registry scrape failed: {e!r}")
        return {}


def _zipf_pick(keys: list[str], n: int, s: float, seed: int) -> list[str]:
    """Deterministic Zipf-ranked model choice: p_i ∝ 1/(i+1)^s."""
    rng = np.random.default_rng(seed)
    w = 1.0 / np.power(np.arange(1, len(keys) + 1, dtype=np.float64), s)
    w /= w.sum()
    idx = rng.choice(len(keys), size=n, p=w)
    return [keys[i] for i in idx]


def _run_fleet(args, stamp: str) -> int:
    """The fleet A/B (ISSUE 12 acceptance): Zipf traffic over M models at
    K× HBM oversubscription vs the all-resident control — sustained QPS,
    eviction/page-in counters, the peak-bytes-under-budget pin, and
    byte-parity per model before/after the sweep AND across modes."""
    import tempfile

    qps_list = [float(q) for q in args.qps.split(",") if q.strip()]
    pool = _row_pool()
    probe_rows = pool[:8]
    artifact = {
        "schema": "fleet-loadtest/v1", "stamp": stamp,
        "models": args.models, "oversub": args.oversub,
        "zipf_s": args.zipf, "rows_per_request": args.rows,
        "duration_s_per_step": args.duration, "steps": [],
        "env": {
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", ""),
            "XLA_FLAGS": os.environ.get("XLA_FLAGS", ""),
        },
    }
    parity: dict[str, dict] = {}
    registry_stats: dict[str, dict] = {}
    budgets: dict[str, int] = {}

    for mode in ("oversub", "resident"):
        watch = tempfile.mkdtemp(prefix=f"fleet_store_{mode}_")
        proc, url, keys, extra = _spawn_fleet_server(mode, args, watch)
        budgets[mode] = int(extra.get("budget") or 0)
        try:
            # ordered by training seed, NOT keyed by model key: keys are
            # per-process uuids, but seed i's model is identical across the
            # two servers (deterministic training)
            before = [_post_rows(url, k, probe_rows)["predictions"]
                      for k in keys]
            for q in qps_list:
                pick = _zipf_pick(keys, max(int(q * args.duration * 2), 64),
                                  args.zipf, seed=int(q))
                step = _run_step(url, keys[0], q, args.duration, args.rows,
                                 args.threads, pool, model_pick=pick)
                step["mode"] = mode
                artifact["steps"].append(step)
                _log(f"[fleet {mode}] offered={q:>7.0f}/s achieved="
                     f"{step['achieved_qps']:>7.1f}/s shed_rate="
                     f"{step['shed_rate']:.3f} p50={step['p50_ms']}ms "
                     f"p99={step['p99_ms']}ms")
            # byte-parity per model across the whole sweep's page-out/in
            after = [_post_rows(url, k, probe_rows)["predictions"]
                     for k in keys]
            parity[mode] = {"before": before, "after": after,
                            "stable": before == after}
            registry_stats[mode] = _scrape_registry(url)
        finally:
            proc.kill()
            proc.wait(timeout=30)

    summary: dict = {}
    for mode in ("oversub", "resident"):
        steps = [s for s in artifact["steps"] if s["mode"] == mode]
        best = _sustained(steps)
        summary[f"{mode}_sustained_qps"] = best["offered_qps"] if best else 0.0
        summary[f"{mode}_p99_ms_at_sustained"] = (best["p99_ms"] if best
                                                  else None)
        if best:
            summary[f"{mode}_breakdown_at_sustained"] = best.get(
                "latency_breakdown")
        res = (registry_stats.get(mode) or {}).get("residency") or {}
        summary[f"{mode}_hbm_peak_bytes"] = res.get("hbm_peak_bytes")
        summary[f"{mode}_evictions"] = res.get("evictions")
        summary[f"{mode}_page_ins"] = res.get("page_ins")
        summary[f"{mode}_parity_stable"] = parity[mode]["stable"]
    summary["hbm_budget_bytes"] = budgets["oversub"]
    peak = summary.get("oversub_hbm_peak_bytes") or 0
    summary["peak_within_budget"] = bool(
        budgets["oversub"] and peak <= budgets["oversub"])
    # cross-mode parity: same seeds, same data -> same models; paging must
    # not perturb a single bit
    summary["parity_across_modes"] = (
        parity["oversub"]["after"] == parity["resident"]["after"])
    c = summary.get("resident_sustained_qps") or 0.0
    b = summary.get("oversub_sustained_qps") or 0.0
    summary["qps_ratio_vs_resident"] = round(b / c, 3) if c else None
    artifact["summary"] = summary
    artifact["registry"] = {
        m: (registry_stats.get(m) or {}).get("residency")
        for m in registry_stats
    }

    out_path = args.out or os.path.join(ROOT, f"FLEET_{stamp}.json")
    line = json.dumps(artifact)
    with open(out_path, "w") as f:
        f.write(line + "\n")
    print(line)
    _log(f"fleet artifact written to {out_path}")
    ok = (summary["peak_within_budget"]
          and summary["parity_across_modes"]
          and summary["oversub_parity_stable"]
          and (summary["qps_ratio_vs_resident"] or 0) >= 0.5)
    _log(f"fleet acceptance {'OK' if ok else 'NOT MET'}: {summary}")
    return 0


def _sustained(steps: list[dict]) -> dict | None:
    """Highest offered rate the tier sustains: <= 1% of the offered load was
    shed, errored, or left unissued inside the step window (shed_rate
    already folds all three in). Judged against what was actually SENT, not
    the nominal rate — Poisson draws undershoot the nominal by a few
    percent and must not fail a healthy step."""
    best = None
    for s in steps:
        if s["shed_rate"] <= 0.01:
            if best is None or s["offered_qps"] > best["offered_qps"]:
                best = s
    return best


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--serve", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--serve-fleet", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--port", type=int, default=0, help=argparse.SUPPRESS)
    ap.add_argument("--mode", default="both",
                    choices=("both", "batched", "control"))
    ap.add_argument("--fleet", action="store_true",
                    help="fleet A/B: Zipf traffic over --models models at "
                         "--oversub x HBM oversubscription through the "
                         "serving registry, vs the all-resident control")
    ap.add_argument("--models", type=int, default=10,
                    help="fleet mode: how many models to train/serve")
    ap.add_argument("--oversub", type=int, default=10,
                    help="fleet mode: HBM budget = fleet bytes / this")
    ap.add_argument("--zipf", type=float, default=1.2,
                    help="fleet mode: Zipf skew of the per-model traffic")
    ap.add_argument("--qps", default="25,50,100,200,400,800,1600,3200",
                    help="comma list of offered QPS steps")
    ap.add_argument("--duration", type=float, default=6.0,
                    help="seconds per step")
    ap.add_argument("--rows", type=int, default=1,
                    help="rows per request (1 = the per-user pattern)")
    ap.add_argument("--threads", type=int, default=48)
    ap.add_argument("--window-ms", default=None,
                    help="override the batched server's coalescing window")
    ap.add_argument("--url", default=None,
                    help="drive an existing server instead of spawning")
    ap.add_argument("--model", default=None,
                    help="model key on the existing server (--url)")
    ap.add_argument("--out", default=None,
                    help="artifact path (default LOADTEST_<stamp>.json)")
    args = ap.parse_args(argv)

    if args.serve_fleet:
        _serve_fleet(args)
        return 0
    if args.serve:
        _serve(args)
        return 0

    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    if args.fleet:
        return _run_fleet(args, stamp)
    qps_list = [float(q) for q in args.qps.split(",") if q.strip()]
    pool = _row_pool()
    modes = (["batched", "control"] if args.mode == "both" else [args.mode])
    artifact = {
        "schema": "loadtest/v1", "stamp": stamp, "rows_per_request": args.rows,
        "duration_s_per_step": args.duration, "modes": modes, "steps": [],
        "env": {
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", ""),
            "XLA_FLAGS": os.environ.get("XLA_FLAGS", ""),
            "window_ms": args.window_ms
            or os.environ.get("H2O3_TPU_SCORE_BATCH_WINDOW_MS", "(default)"),
        },
    }
    parity_probe = pool[:16]
    parity: dict[str, list] = {}

    for mode in modes:
        if args.url:
            proc, url, model_key = None, args.url.rstrip("/"), args.model
            if not model_key:
                _log("--url needs --model")
                return 2
        else:
            proc, url, model_key = _spawn_server(mode, args.window_ms)
        try:
            # parity probe: the same 16 rows through each mode's server —
            # batched and per-request answers must be byte-identical
            resp = _post_rows(url, model_key, parity_probe)
            parity[mode] = resp["predictions"].get(
                "pos", resp["predictions"].get("predict"))
            for q in qps_list:
                step = _run_step(url, model_key, q, args.duration,
                                 args.rows, args.threads, pool)
                step["mode"] = mode
                artifact["steps"].append(step)
                _log(f"[{mode}] offered={q:>7.0f}/s achieved="
                     f"{step['achieved_qps']:>7.1f}/s shed_rate="
                     f"{step['shed_rate']:.3f} p50={step['p50_ms']}ms "
                     f"p99={step['p99_ms']}ms occupancy="
                     f"{step['mean_batch_occupancy']}")
        finally:
            if proc is not None:
                proc.kill()
                proc.wait(timeout=30)

    summary: dict = {}
    for mode in modes:
        steps = [s for s in artifact["steps"] if s["mode"] == mode]
        best = _sustained(steps)
        summary[f"{mode}_sustained_qps"] = best["offered_qps"] if best else 0.0
        summary[f"{mode}_p99_ms_at_sustained"] = best["p99_ms"] if best else None
        if best:
            summary[f"{mode}_breakdown_at_sustained"] = best.get(
                "latency_breakdown")
        if mode == "batched" and best:
            summary["batched_occupancy_at_sustained"] = best[
                "mean_batch_occupancy"]
    if len(modes) == 2:
        c = summary.get("control_sustained_qps") or 0.0
        b = summary.get("batched_sustained_qps") or 0.0
        summary["speedup"] = round(b / c, 2) if c else None
        summary["parity_byte_equal"] = (parity.get("batched")
                                        == parity.get("control"))
        # the operational comparison: serve >= 3x the control's capacity —
        # what does each mode's tail look like AT THAT RATE?
        target = 3 * c
        cand = sorted(
            (s for s in artifact["steps"] if s["offered_qps"] >= target),
            key=lambda s: s["offered_qps"])
        by_mode = {}
        for s in cand:
            by_mode.setdefault(s["mode"], s)
        if "batched" in by_mode and "control" in by_mode:
            summary["p99_at_3x_control"] = {
                "offered_qps": by_mode["batched"]["offered_qps"],
                "batched_ms": by_mode["batched"]["p99_ms"],
                "control_ms": by_mode["control"]["p99_ms"],
            }
    artifact["summary"] = summary

    out_path = args.out or os.path.join(ROOT, f"LOADTEST_{stamp}.json")
    line = json.dumps(artifact)
    with open(out_path, "w") as f:
        f.write(line + "\n")
    print(line)
    _log(f"artifact written to {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
