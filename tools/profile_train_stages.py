#!/usr/bin/env python
"""Stage-level wall-time attribution for one bench-config GBM train on the
real TPU: where do the ~0.13 s/tree of non-fused-builder time go?

Monkeypatches timers around fit_bins / bin_frame / build_trees_scanned /
trees_from_stacked / metrics and prints one JSON line. Run when the tunnel
is up:

    python tools/profile_train_stages.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGES: dict[str, float] = {}


def _wrap(mod, name, label):
    fn = getattr(mod, name)

    def timed(*a, **k):
        t0 = time.perf_counter()
        out = fn(*a, **k)
        try:  # block so the timer sees device completion, not dispatch
            import jax

            jax.tree.map(
                lambda x: x.block_until_ready() if hasattr(x, "block_until_ready") else x,
                out,
            )
        except Exception:
            pass
        dt = time.perf_counter() - t0
        STAGES[label] = STAGES.get(label, 0.0) + dt
        try:  # stamp the flight ring so stage walls cross-reference the
            # dispatch_device_seconds events by timestamp (ISSUE 13)
            from h2o3_tpu.utils import flightrec

            flightrec.record("stage", stage=label, dur_ms=round(dt * 1e3, 3))
        except Exception:
            pass
        return out

    setattr(mod, name, timed)
    return fn


def main() -> None:
    import bench
    import h2o3_tpu

    h2o3_tpu.init(log_level="WARN")

    from h2o3_tpu.models.tree import binning, gbm, shared_tree

    # gbm binds fit_bins/bin_frame at module import (patch gbm's refs) but
    # imports the scanned builder at call time (patch shared_tree's attrs)
    _wrap(gbm, "fit_bins", "fit_bins")
    _wrap(gbm, "bin_frame", "bin_frame")
    _wrap(shared_tree, "build_trees_scanned", "fused_builder")
    _wrap(shared_tree, "trees_from_stacked", "record_unpack")
    _wrap(gbm, "_metrics_from_F", "metrics")

    df = bench.make_data()
    fr = h2o3_tpu.upload_file(df)
    from h2o3_tpu.models.tree import GBM

    kw = dict(max_depth=6, learn_rate=0.1, min_rows=10.0,
              score_tree_interval=1000, seed=42, ntrees=20)
    GBM(**kw).train(y="label", training_frame=fr)  # warmup/compile
    STAGES.clear()
    t0 = time.time()
    GBM(**kw).train(y="label", training_frame=fr)
    total = time.time() - t0
    other = total - sum(STAGES.values())
    print(json.dumps({"total_s": round(total, 4), "unattributed_s": round(other, 4),
                      **{k: round(v, 4) for k, v in STAGES.items()}}))


if __name__ == "__main__":
    main()
