#!/usr/bin/env python
"""Weak-scaling measurement for the histogram hot loop on a virtual CPU mesh
(VERDICT r3 item 4 / SURVEY §4 "real stack, local topology").

Fixed rows PER SHARD; mesh sizes 1/2/4/8. On this box the virtual devices
share the physical cores, so wall time CANNOT weak-scale by construction;
the honest signal (VERDICT r4 weak #3) is ``psum_share`` — the fraction the
cross-shard reduction adds over the local pass — reported as median with a
min-max band over repetitions. Writes WEAKSCALING_r05.json at the repo root.

    python tools/bench_weak_scaling.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

ROOT = pathlib.Path(__file__).resolve().parent.parent

ROWS_PER_SHARD = 262_144
N_COLS = 28
N_NODES = 32
N_BINS = 255


def main() -> None:
    if os.environ.get("_H2O3_WS_CHILD") != "1":
        env = dict(
            os.environ,
            _H2O3_WS_CHILD="1",
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS=(
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            ).strip(),
        )
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)

    sys.path.insert(0, str(ROOT))
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from h2o3_tpu.ops.histogram import histogram_in_jit
    from h2o3_tpu.parallel.mesh import shard_map

    devices = jax.devices()
    rng = np.random.default_rng(0)
    results = []
    for k in (1, 2, 4, 8):
        if k > len(devices):
            break
        mesh = Mesh(np.array(devices[:k]), ("rows",))
        sh = NamedSharding(mesh, P("rows"))
        n = ROWS_PER_SHARD * k
        bins = jax.device_put(
            rng.integers(0, N_BINS, (n, N_COLS)).astype(np.uint8), sh
        )
        nid = jax.device_put(rng.integers(0, N_NODES, n).astype(np.int32), sh)
        w = jax.device_put(np.ones(n, np.float32), sh)
        wy = jax.device_put(rng.normal(size=n).astype(np.float32), sh)

        fn = jax.jit(
            lambda b, i, w_, wy_: histogram_in_jit(
                b, i, (w_, wy_, w_), N_NODES, N_BINS, mesh=mesh
            )
        )
        def timed(f, *a, reps=5):
            """Per-rep wall times (median/min/max downstream, not a mean)."""
            jax.block_until_ready(f(*a))  # warm
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(f(*a))
                ts.append(time.perf_counter() - t0)
            return ts

        ts = timed(fn, bins, nid, w, wy)

        # local-only variant (no psum) isolates the reduction share
        from h2o3_tpu.ops.histogram import _select_local

        local = _select_local()
        loc_fn = jax.jit(
            shard_map(
                lambda b, i, w_, wy_: local(
                    b, i, jnp.stack([w_, wy_, w_], 1), N_NODES, N_BINS),
                mesh=mesh,
                in_specs=(P("rows"),) * 4,
                out_specs=P("rows"),
                check_vma=False,
            )
        )
        ts_local = timed(loc_fn, bins, nid, w, wy)

        med = lambda xs: sorted(xs)[len(xs) // 2]
        # run-order-matched pairs: rep i of the full pass against rep i of
        # the local pass, so each share reflects one machine state. Sorting
        # the two lists independently pairs fastest-with-fastest, which
        # understates the band whenever noise hits the two passes on
        # different reps.
        shares = [
            max(t - tl, 0.0) / t for t, tl in zip(ts, ts_local) if t > 0
        ]
        results.append({
            "mesh_shards": k,
            "rows_total": n,
            "rows_per_shard": ROWS_PER_SHARD,
            "hist_s_median": round(med(ts), 4),
            "hist_s_minmax": [round(min(ts), 4), round(max(ts), 4)],
            "hist_local_s_median": round(med(ts_local), 4),
            "hist_local_s_minmax": [
                round(min(ts_local), 4), round(max(ts_local), 4)
            ],
            "psum_share_median": round(med(shares), 4) if shares else None,
            "psum_share_minmax": [round(min(shares), 4), round(max(shares), 4)]
            if shares else None,
        })
        print(results[-1], flush=True)

    payload = {
        "workload": f"histogram pass, {N_COLS} cols x {N_BINS} bins x {N_NODES} nodes, "
                    f"{ROWS_PER_SHARD} rows/shard (weak scaling)",
        "backend": "cpu x 8 virtual devices (XLA_FLAGS force_host_platform_device_count)",
        "note": "virtual devices share this box's physical cores, so wall "
                "time grows ~linearly with shards BY CONSTRUCTION and no "
                "efficiency number is reported from this box (VERDICT r4 "
                "weak #3). The scaling-relevant measurement is psum_share "
                "— the fraction the cross-shard reduction adds over the "
                "local pass, computed per run-order-matched rep pair (rep i "
                "full vs rep i local; independent sorting would pair "
                "fastest-with-fastest and understate the band) — reported "
                "as median with min-max over 5 reps. "
                "On real chips each shard has its own compute, leaving "
                "psum as the only scaling cost. The mesh_shards=1 row has "
                "NO reduction at all: its delta is the replicated-output "
                "layout/transpose cost and bounds the measurement noise.",
        "results": results,
    }
    out = ROOT / "WEAKSCALING_r05.json"
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
