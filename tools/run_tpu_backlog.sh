#!/bin/sh
# TPU measurement backlog — run the moment the axon tunnel is back up.
#   0. memory diagnosis of the 10M-row RESOURCE_EXHAUSTED (tpu_mem_analysis)
#   1. bench.py (subprocess-per-phase; six backend inits — the parent stops
#      launching phases at H2O3_TPU_BENCH_DEADLINE_S, default 3000 s)
#   2. adaptivity A/B: default is now OFF (measured 5% slower on v5e,
#      BENCH_builder_20260731T0101Z*); the control run measures it ON,
#      headline only.
#   3. Pallas tile sweep (tools/bench_kernel_sweep.py) for the next kernel
#      iteration.
set -x
cd "$(dirname "$0")/.."

stamp=$(date -u +%Y%m%dT%H%M%SZ)

timeout 1800 python tools/tpu_mem_analysis.py --train \
  | tee "MEMDIAG_${stamp}.txt"

timeout 3600 python bench.py | tee "BENCH_builder_${stamp}.json"

H2O3_TPU_BIN_ADAPT=1 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_adapt.json"  # headline only (deadline=1s)

timeout 2400 python tools/bench_kernel_sweep.py \
  | tee "KERNEL_SWEEP_${stamp}.jsonl"

git add "MEMDIAG_${stamp}.txt" "BENCH_builder_${stamp}.json" \
        "BENCH_builder_${stamp}_adapt.json" "KERNEL_SWEEP_${stamp}.jsonl"
git commit -m "TPU measurement backlog: mem diagnosis, bench (adapt A/B), kernel tile sweep"
