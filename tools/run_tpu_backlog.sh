#!/bin/sh
# TPU measurement backlog — run the moment the axon tunnel is back up.
# Each step COMMITS its artifact immediately: the last two tunnel windows
# lasted ~2.5 h and wedged without warning, and an end-of-script commit
# would lose everything already measured.
#   0. memory diagnosis of the 10M-row RESOURCE_EXHAUSTED (tpu_mem_analysis)
#   1. bench.py (subprocess-per-phase; the parent stops launching phases at
#      H2O3_TPU_BENCH_DEADLINE_S, default 3000 s)
#   2. adaptivity A/B: default is now OFF (measured 5% slower on v5e,
#      BENCH_builder_20260731T0101Z*); the control run measures it ON,
#      headline only.
#   3. Pallas tile sweep (tools/bench_kernel_sweep.py) for the next kernel
#      iteration.
#   4. column-sharded split pipeline A/B (ISSUE 5): default is now SHARDED
#      (measured 6.2x less split-phase traffic + ~17% faster trees on the
#      8-device CPU proxy); the control run measures the replicated path,
#      headline only, plus the dedicated sweep A/B with byte tallies.
#   5. fused histogram->split Pallas pipeline A/B (ISSUE 6): default is now
#      FUSED on TPU (H2O3_TPU_SPLIT_FUSE=auto; 3x less modeled hist+split
#      HBM traffic on the CPU proxy); the control run measures the unfused
#      path, headline only, plus the dedicated sweep A/B with HBM tallies.
#      The tile sweep (step 3) now varies tiles via H2O3_TPU_PALLAS_TILES.
#   6. serving load A/B (ISSUE 7): open-loop Poisson sweep against the
#      batched /3/Predictions/rows route vs the per-request control
#      (H2O3_TPU_SCORE_BATCH_WINDOW_MS=0); artifact carries p50/p99, shed
#      rate, batch-occupancy histogram and the byte-parity probe.
#      tools/latest_bench_ok.py gates on the artifact's sanity.
#   8. recovery drill (ISSUE 10): kill a worker mid-bench-GBM (die: fault
#      at a collective boundary, right after an interval snapshot) and
#      assert the supervised loop auto-resumes with the PR-2 1e-6 pin and
#      NO operator action; the artifact logs the recovery_seconds histogram
#      + restart counts + generation ticks (same drill for GLM and AutoML).
#   7. quantized collective lane A/B (ISSUE 9): H2O3_TPU_COLLECTIVE_QUANT=1
#      vs =0 — per-phase modeled bytes with the {lane} split, measured
#      reduce seconds through the active lane, GBM AUC + GLM coefficient
#      deltas (CPU-proxy numbers in QUANT_AB_*_cpu8proxy.jsonl: 3.94x fewer
#      hist_reduce bytes, AUC delta <1e-3). The wire-byte win is a DCN
#      claim — THIS window's measured seconds on real interconnect are the
#      number that decides whether the lane defaults on for pods. Plus a
#      QUANT=1 headline run and the QUANT=0 headline control.
#      tools/latest_bench_ok.py gates on the artifact's sanity.
set -x
cd "$(dirname "$0")/.."

stamp=$(date -u +%Y%m%dT%H%M%SZ)

save() {  # save FILE MSG — commit one artifact if it has content
  if [ -s "$1" ]; then
    git add "$1" && git commit -m "$2" -- "$1"
  fi
}

timeout 1800 python tools/tpu_mem_analysis.py --train \
  | tee "MEMDIAG_${stamp}.txt"
save "MEMDIAG_${stamp}.txt" "TPU memory diagnosis for the 10M-row OOM"

# bench prints its ONE json line only at the very end: the wrapper timeout
# must exceed the worst case (launch deadline + the last phase's budget =
# 2400 + 1800) or a long automl pass kills the run with nothing written
H2O3_TPU_BENCH_DEADLINE_S=2400 timeout 5400 python bench.py | tee "BENCH_builder_${stamp}.json"
save "BENCH_builder_${stamp}.json" "TPU bench artifact (all phases, subprocess-isolated)"

H2O3_TPU_BIN_ADAPT=1 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_adapt.json"  # headline only (deadline=1s)
save "BENCH_builder_${stamp}_adapt.json" "TPU bench adaptivity A/B control (headline only)"

H2O3_TPU_BENCH_NBINS=127 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_nbins127.json"  # global bin-count A/B
save "BENCH_builder_${stamp}_nbins127.json" "TPU bench 127-bin A/B (headline only)"

H2O3_TPU_HIST=matmul H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_matmul.json"  # Pallas kernel vs plain-XLA A/B
save "BENCH_builder_${stamp}_matmul.json" "TPU bench plain-XLA histogram control (headline only)"

H2O3_TPU_SPLIT_SHARD=0 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_replsplit.json"  # replicated-split control
save "BENCH_builder_${stamp}_replsplit.json" "TPU bench replicated-split control (headline only)"

H2O3_TPU_SPLIT_FUSE=0 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_unfused.json"  # fused-split-pipeline control
save "BENCH_builder_${stamp}_unfused.json" "TPU bench unfused split-pipeline control (headline only)"

timeout 1200 python tools/bench_kernel_sweep.py --fused-ab --rows 1000000 \
  | tee "FUSED_AB_${stamp}.jsonl"  # fused-vs-unfused Pallas pipeline, HBM tallies
save "FUSED_AB_${stamp}.jsonl" "Fused-vs-unfused histogram->split pipeline A/B (1M rows)"

timeout 1200 python tools/bench_kernel_sweep.py --split-ab --rows 1000000 \
  | tee "SPLIT_AB_${stamp}.jsonl"  # sharded-vs-replicated split, byte tallies
save "SPLIT_AB_${stamp}.jsonl" "Split-pipeline sharded-vs-replicated A/B (1M rows)"

timeout 2400 python tools/bench_kernel_sweep.py \
  | tee "KERNEL_SWEEP_${stamp}.jsonl"
save "KERNEL_SWEEP_${stamp}.jsonl" "Pallas histogram kernel tile sweep"

# fallback-matrix closure A/B (ISSUE 15): monotone GBM, multinomial GLM,
# dropout DL — each NOW-fused lane vs the forced fallback it replaces, with
# the parity pins and dispatch/wall ratios. The real-TPU numbers decide how
# much of the CPU-proxy dispatch win survives on hardware where the kernels
# run native instead of interpreted.
timeout 1800 python tools/bench_kernel_sweep.py --fallback-ab --rows 100000 \
  | tee "FALLBACK_AB_${stamp}.jsonl"
save "FALLBACK_AB_${stamp}.jsonl" "Fallback-matrix closure A/B (mono GBM / multinomial GLM / dropout DL, fused vs forced fallback)"

# tree-kernel wave-2 A/B (ISSUE 16): GOSS / EFB / u8-code cache / int16
# hist lanes / lossguide, each knob-on vs knob-off with the parity pins and
# bit-identical controls. The CPU-proxy artifact (WAVE2_AB_*_cpu8proxy)
# pins correctness; the real-TPU run here decides the wall-clock story —
# GOSS and int16 only pay off where histogram bandwidth is the bottleneck.
timeout 1800 python tools/bench_kernel_sweep.py --wave2-ab --rows 1000000 \
  | tee "WAVE2_AB_${stamp}.jsonl"
save "WAVE2_AB_${stamp}.jsonl" "Tree-kernel wave-2 A/B (GOSS / EFB / u8 cache / int16 lanes / lossguide, 1M rows)"

# compiled-munging-plane A/B (ISSUE 20): fused vs eager group-by / join /
# sort + the expression-chain dispatch pin at 10M rows. The CPU-proxy
# artifact (MUNGE_AB_*_cpu8proxy.jsonl) pins parity and the dispatch cut;
# the TPU number that matters here is the join exchange — all_to_all over
# real ICI vs the CPU proxy's shared-memory copy decides whether the radix
# lane stays default-on for multi-host meshes.
timeout 1800 python tools/bench_kernel_sweep.py --munge-ab --rows 10000000 \
  | tee "MUNGE_AB_${stamp}.jsonl"
save "MUNGE_AB_${stamp}.jsonl" "Compiled munging plane A/B (group-by / join / sort / expr chain, 10M rows)"

# munging headline control: the whole bench with the plane disabled —
# cat_1m's group-by stage and join_10m pin the eager walls
H2O3_TPU_MUNGE_FUSE=0 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_mungeoff.json"
save "BENCH_builder_${stamp}_mungeoff.json" "TPU bench MUNGE_FUSE=0 control (headline only)"

# wave-2 bench headlines: the full-pipeline trees/sec under GOSS and under
# the int16 lanes (one control each; EFB and the u8 cache show up in the
# A/B's own counters, and the dense bench frame has nothing to bundle)
H2O3_TPU_TREE_GOSS=0.2,0.1 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_goss.json"
save "BENCH_builder_${stamp}_goss.json" "TPU bench GOSS a=0.2,b=0.1 headline (headline only)"
H2O3_TPU_HIST_I16=1 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_i16.json"
save "BENCH_builder_${stamp}_i16.json" "TPU bench int16 histogram-lane headline (headline only)"

# tile-autotuner first-build sweep (ISSUE 15 / ROADMAP 4b): run the bench
# headline under H2O3_TPU_PALLAS_TILES=auto on a COLD tile store — the
# first build sweeps once per shape bucket and persists the winners next to
# the compile cache; the second run must log zero new sweeps
# (pallas_tile_sweeps_total) and its headline is the self-tuned number to
# compare against the hand-swept KERNEL_SWEEP best.
rm -f "$(python - <<'PYEOF'
from h2o3_tpu.ops.hist_pallas import _tile_cache_path
print(_tile_cache_path())
PYEOF
)" 2>/dev/null || true
H2O3_TPU_PALLAS_TILES=auto H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_tilesauto.json"
save "BENCH_builder_${stamp}_tilesauto.json" "TPU bench headline under the tile autotuner, cold store (headline only)"
H2O3_TPU_PALLAS_TILES=auto H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_tilesauto2.json"
save "BENCH_builder_${stamp}_tilesauto2.json" "TPU bench headline under the tile autotuner, warm store — must report zero new sweeps (headline only)"

# serving load A/B (ISSUE 7): batched coalescing tier vs per-request control
# on the real accelerator. The harness spawns one server subprocess per mode
# and writes its own stamped artifact; stdout is the artifact JSON line.
timeout 1800 python tools/load_test.py --mode both --duration 8 \
  --out "LOADTEST_${stamp}.json" | tail -1 > /dev/null
save "LOADTEST_${stamp}.json" "Serving load A/B: batched rows route vs per-request control"

# whole-program GLM IRLS + DL epoch-chunk A/Bs (ISSUE 8): fused-vs-unfused
# hot-loop iterations/sec + dispatch counts + Gram/gradient collective byte
# tallies on the real accelerator (CPU-proxy numbers in the committed
# GLMDL_AB_*_cpu8proxy.jsonl: GLM 1.62x iters/sec, DL 2.9x epochs/sec).
timeout 1200 python tools/bench_kernel_sweep.py --glm-ab --rows 1000000 \
  | tee "GLMDL_AB_${stamp}_glm.jsonl"
save "GLMDL_AB_${stamp}_glm.jsonl" "Whole-program GLM IRLS fused-vs-unfused A/B (1M rows)"

timeout 1200 python tools/bench_kernel_sweep.py --dl-ab --rows 100000 \
  | tee "GLMDL_AB_${stamp}_dl.jsonl"
save "GLMDL_AB_${stamp}_dl.jsonl" "DL epoch-chunk + sharded-grad A/B (100k rows)"

# bench headline controls for the fused GLM/DL lanes: full phase run above
# measured the fused defaults; these pin the pre-fusion paths
H2O3_TPU_GLM_FUSE=0 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_glmunfused.json"  # per-iteration GLM control
save "BENCH_builder_${stamp}_glmunfused.json" "TPU bench unfused-GLM control (headline only)"

H2O3_TPU_DL_EPOCH_CHUNK=1 H2O3_TPU_DL_GRAD_SHARD=0 H2O3_TPU_BENCH_DEADLINE_S=1 \
  timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_dlperepoch.json"  # per-epoch DL control
save "BENCH_builder_${stamp}_dlperepoch.json" "TPU bench per-epoch DL control (headline only)"

# quantized collective lane A/B (ISSUE 9): modeled bytes + measured reduce
# seconds + accuracy deltas, quant vs exact, on the real interconnect
timeout 1200 python tools/bench_kernel_sweep.py --quant-ab \
  | tee "QUANT_AB_${stamp}.jsonl"
save "QUANT_AB_${stamp}.jsonl" "Quantized-collective-lane A/B (bytes, measured seconds, accuracy)"

# bench headline under the quantized lane, with the exact-lane control:
# H2O3_TPU_COLLECTIVE_QUANT=auto is off for single-process meshes, so both
# sides pin the knob explicitly
H2O3_TPU_COLLECTIVE_QUANT=1 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_quant.json"
save "BENCH_builder_${stamp}_quant.json" "TPU bench quantized-collective headline (headline only)"

H2O3_TPU_COLLECTIVE_QUANT=0 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_quant0.json"  # exact-lane headline control
save "BENCH_builder_${stamp}_quant0.json" "TPU bench exact-collective control (headline only)"

# self-healing recovery drill (ISSUE 10): worker death mid-GBM/GLM/AutoML
# with checkpoints enabled — asserts supervised auto-resume completes
# (1e-6 pin, no operator) and logs the recovery_seconds histogram into the
# artifact. On TPU the interesting number is the reform+recompile cost on
# real hardware (the CPU-proxy artifact is committed alongside the PR).
timeout 1800 python tools/recovery_drill.py \
  --out "RECOVERY_DRILL_${stamp}.json" > /dev/null
save "RECOVERY_DRILL_${stamp}.json" "Recovery drill: worker death mid-train, supervised auto-resume + recovery_seconds"

# overload-survival drill (ISSUE 19): admission storm at 4x capacity
# (shed honesty: 429/503 + computed Retry-After, zero server deaths,
# reservations back to zero), induced RESOURCE_EXHAUSTED auto-degrading to
# the streamed lane within the 1e-6 pin, and a wedged dispatch tripping
# the hang watchdog into a supervised snapshot resume. On TPU the OOM leg
# uses the REAL allocator signature (not just the synthetic fault text)
# and the interesting numbers are trip latency vs real compile baselines.
# tools/latest_bench_ok.py gates on the artifact's pins.
timeout 1800 python tools/overload_drill.py \
  --out "OVERLOAD_DRILL_${stamp}.json" > /dev/null
save "OVERLOAD_DRILL_${stamp}.json" "Overload drill: admission storm + OOM degrade + hang watchdog resume"

# out-of-core streaming A/B (ISSUE 11): streamed vs resident GBM at rows
# >= 10x a forced HBM window — wall time, AUC, peak frame device bytes
# (the fixed-footprint claim) + the COMPRESS=0 kill-switch control inside
# the harness; tools/latest_bench_ok.py gates on the summary's pins. On
# TPU the interesting numbers are real transfer overlap (PCIe/ICI
# host->HBM) vs the CPU proxy's memcpy, and where the streamed wall-clock
# ratio lands once transfers are truly asynchronous.
timeout 1800 python tools/bench_kernel_sweep.py --oocore-ab --rows 1000000 \
  | tee "OOCORE_AB_${stamp}.jsonl"
save "OOCORE_AB_${stamp}.jsonl" "Out-of-core streamed-vs-resident A/B (1M rows, 10x window)"

# refreshed capacity model: largest trainable rows per bracket, resident
# f32 vs compressed u8 vs streamed (analytic; commit alongside the A/B)
timeout 600 python tools/tpu_mem_analysis.py --oocore \
  --out "OOCORE_MEM_${stamp}.json" > /dev/null
save "OOCORE_MEM_${stamp}.json" "Out-of-core capacity model (compressed frames + HBM window)"

H2O3_TPU_FRAME_COMPRESS=0 H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_nocompress.json"  # out-of-core plane kill-switch control
save "BENCH_builder_${stamp}_nocompress.json" "TPU bench FRAME_COMPRESS=0 control (headline only)"

# fleet serving A/B (ISSUE 12): Zipf traffic over 16 models at 10x HBM
# oversubscription through the serving registry + residency LRU, vs the
# all-resident control — sustained QPS ratio (>= 0.5x required),
# peak-resident-bytes-under-budget pin, eviction/page-in counters, and the
# per-model byte-parity probe across page-out/page-in and across modes.
# Each step now also carries the ISSUE-18 span-sourced latency breakdown
# (queue-wait / dispatch / page-in legs) scraped from the tracing plane.
# On TPU the interesting number is the real page-in cost (PCIe/ICI
# host->HBM re-upload) vs the CPU proxy's memcpy — it decides how tight
# H2O3_TPU_SERVE_HBM_BYTES can run before the paging tax eats the tail.
timeout 1800 python tools/load_test.py --fleet --models 16 --oversub 10 \
  --qps 25,50,100,200,400,800 --duration 6 \
  --out "FLEET_${stamp}.json" | tail -1 > /dev/null
save "FLEET_${stamp}.json" "Fleet serving A/B: 10x HBM oversubscription vs all-resident"

# HBM attribution + flight-recorder capture (ISSUE 13): re-run the
# headline GBM config under a jax.profiler xplane trace with the devmem
# ledger polling real memory_stats, then dump the dispatch ring + the
# per-owner attribution table. This is the first window that lands
# MEASURED device-byte/device-time artifacts (not the CPU-proxy's modeled
# numbers): the xplane dump cross-references the ring by timestamp
# (profiler_start/profiler_end events bracket the capture), and the
# unattributed series is the XLA program/temp share the 10M-row OOM
# forensics needs. The stage attribution (profile_train_stages) rides
# along so dispatch_device_seconds{site} can be sanity-checked against
# wrapped-stage wall time.
timeout 1200 python - "FLIGHTREC_${stamp}.json" << 'PYEOF'
import json, sys
import bench
import h2o3_tpu
from h2o3_tpu.utils import devmem, flightrec, telemetry

h2o3_tpu.init(log_level="WARN")
fr = h2o3_tpu.upload_file(bench.make_data())
from h2o3_tpu.models.tree import GBM
kw = dict(ntrees=20, max_depth=6, learn_rate=0.1, min_rows=10.0,
          score_tree_interval=1000, seed=42)
GBM(**kw).train(y="label", training_frame=fr)  # warm compile
devmem.reset_peaks()
with telemetry.profiler("/tmp/h2o3_xplane"):
    GBM(**kw).train(y="label", training_frame=fr)
devmem.poll(force=True)
out = {"phase": "flightrec_capture", "devmem": devmem.status(),
       "ring": flightrec.ring_status(),
       "events": flightrec.events(),
       "xplane_dir": "/tmp/h2o3_xplane"}
with open(sys.argv[1], "w") as f:
    json.dump(out, f)
print("flightrec capture:", out["ring"], flush=True)
PYEOF
save "FLIGHTREC_${stamp}.json" "HBM attribution + flight-recorder capture under a profiler trace"

timeout 900 python tools/profile_train_stages.py \
  | tee "STAGES_${stamp}.json"
save "STAGES_${stamp}.json" "Stage wall-time attribution (cross-check for dispatch_device_seconds)"

# job-scoped trace capture (ISSUE 18): the headline GBM as a TRACED job —
# every dispatch carries trace/span/parent ids, the per-job ledger
# accumulates device-seconds/collective-bytes/window-bytes under the job
# key, and the export is Perfetto-loadable trace JSON cross-referenced
# with the xplane window (telemetry.profiler stamps the same ring).
# tools/latest_bench_ok.py gates on the artifact: a span per dispatched
# site and ledger totals finite and bounded by the measured wall.
timeout 1200 python - "TRACE_${stamp}.json" << 'PYEOF'
import json, sys, time
import bench
import h2o3_tpu
from h2o3_tpu.utils import flightrec, jobacct, telemetry

h2o3_tpu.init(log_level="WARN")
fr = h2o3_tpu.upload_file(bench.make_data())
from h2o3_tpu.models.tree import GBM
kw = dict(ntrees=20, max_depth=6, learn_rate=0.1, min_rows=10.0,
          score_tree_interval=1000, seed=42)
GBM(**kw).train(y="label", training_frame=fr)  # warm compile
flightrec.reset()
jobacct.reset()
t0 = time.perf_counter()
with telemetry.profiler("/tmp/h2o3_xplane_traced"):
    GBM(**kw).train(y="label", training_frame=fr)
wall = time.perf_counter() - t0
jobs = jobacct.all_jobs()
job = (max(jobs, key=lambda k: jobs[k].get("device_seconds") or 0)
       if jobs else None)
out = {"schema": "trace_capture/v1", "wall_s": round(wall, 3),
       "job": job, "ledger": jobs.get(job), "jobs": jobs,
       "trace": flightrec.trace_export(),
       "xplane_dir": "/tmp/h2o3_xplane_traced"}
with open(sys.argv[1], "w") as f:
    json.dump(out, f)
print("trace capture:", job, ledger and ledger.get("dispatches"),
      flush=True)
PYEOF
save "TRACE_${stamp}.json" "Traced headline GBM: span tree + per-job ledger + Perfetto export"

# ---------------------------------------------------------------------------
# v5e-16 POD BRACKET (ISSUE 14): the multihost runs proper. Everything above
# measures one process; these need the 4-host pod brought up via
# deploy/k8s.yaml (or 4 x `python -m h2o3_tpu.launch` with
# H2O3_TPU_COORDINATOR/H2O3_TPU_NUM_PROCESSES set). They are written to run
# ON RANK 0 of a formed pod; single-process runs of the same commands are
# still valid (degenerate pod) and keep the artifacts comparable.

# 2-D mesh A/B on the pod: 1-D vs rows×cols (stage-1 exact reduce over ICI,
# quantized stage over DCN — the placement claim arXiv:2110.10548 makes).
# On the pod this is the number that decides H2O3_TPU_MESH_ROWS=auto's
# default: the CPU-proxy artifact (MESH2D_AB_*_cpu8proxy.jsonl) only pins
# no-regression, because its one-host topology has no cheap/expensive
# split for the placement to exploit.
timeout 1200 python tools/bench_kernel_sweep.py --mesh2d-ab --rows 1000000 \
  | tee "MESH2D_AB_${stamp}.jsonl"
save "MESH2D_AB_${stamp}.jsonl" "1-D vs 2-D pod-mesh A/B (1M rows: per-phase bytes + tree wall)"

# pod-mesh bench headline: the full pipeline under MESH_ROWS=auto (2-D on
# the pod), with the 1-D control
H2O3_TPU_MESH_ROWS=auto H2O3_TPU_BENCH_DEADLINE_S=1 timeout 1800 python bench.py \
  | tee "BENCH_builder_${stamp}_mesh2d.json"
save "BENCH_builder_${stamp}_mesh2d.json" "TPU bench 2-D pod-mesh headline (headline only)"

# sharded-ingest timing: per-host byte-range parses vs the single-host
# parse on the pod's shared filesystem — wall time for the 1M-row CSV and
# the byte-parity pin (the single-process lane re-checks it in-tree; the
# pod number is the scaling claim: ingest wall should fall ~linearly with
# hosts until storage saturates)
timeout 1200 python - << 'PYEOF'
import json, time
import h2o3_tpu, bench
from h2o3_tpu.frame.parse import parse, parse_sharded

h2o3_tpu.init(log_level="WARN")
csv = bench.make_csv() if hasattr(bench, "make_csv") else None
if csv is None:
    import numpy as np, pandas as pd, tempfile
    rng = np.random.default_rng(0)
    df = pd.DataFrame(rng.normal(size=(1_000_000, 28)),
                      columns=[f"x{i}" for i in range(28)])
    csv = tempfile.mktemp(suffix=".csv"); df.to_csv(csv, index=False)
t0 = time.perf_counter(); a = parse({"source_frames": [csv]}); t_one = time.perf_counter() - t0
t0 = time.perf_counter(); b = parse_sharded({"source_frames": [csv]}); t_shard = time.perf_counter() - t0
import numpy as np
eq = all(np.asarray(a.vec(c).to_numpy(), np.float32).tobytes()
         == np.asarray(b.vec(c).to_numpy(), np.float32).tobytes()
         for c in a.names[:4])
print(json.dumps({"phase": "ingest_ab", "rows": a.nrow,
                  "single_host_s": round(t_one, 3),
                  "sharded_s": round(t_shard, 3),
                  "byte_equal_probe": bool(eq)}), flush=True)
PYEOF

# induced-preemption recovery drill on the pod: kill ONE RANK of the formed
# pod mid-GBM (a real member death, not the in-process die: fault) — the
# coordination service fail-stops every rank, the k8s restart loop
# (H2O3_TPU_POD_EXIT_DEGRADED) brings the pod back, and the supervisor
# resumes from the interval snapshot. recovery_seconds (metrics + flight
# recorder) is the headline: detection (heartbeat) + restart + re-formation
# + recompile + resume on real hardware.
timeout 2400 python tools/recovery_drill.py \
  --out "POD_RECOVERY_${stamp}.json" > /dev/null
save "POD_RECOVERY_${stamp}.json" "Pod preemption drill: member death -> restart loop -> supervised resume (recovery_seconds)"

# elastic scale-down drill (ISSUE 17): kill mid-GBM/GLM/DL with a
# reshape:RxC fault so the v5e-16 formation "comes back" smaller /
# re-factored, and prove the checkpointed job resumes on the CHANGED
# topology (16->8 scale-down, 2-D re-factorization) within the 1e-6
# parity pin. On real hardware the headline is recovery_seconds across a
# shape change: reform + full retrace for the new mesh + re-shard of the
# carried state (the CPU-proxy ELASTIC_DRILL artifact is committed
# alongside the PR; tools/latest_bench_ok.py gates on its pins).
timeout 2400 python tools/recovery_drill.py --elastic \
  --out "ELASTIC_DRILL_${stamp}.json" > /dev/null
save "ELASTIC_DRILL_${stamp}.json" "Elastic drill: kill mid-train, resume on a changed topology (shape matrix + recovery_seconds)"
