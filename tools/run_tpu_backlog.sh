#!/bin/sh
# TPU measurement backlog — run the moment the axon tunnel is back up.
# Captures everything round 4 built but could not measure (the tunnel went
# down ~15:00Z on 2026-07-30 and stayed down):
#   1. bench.py with bin adaptivity + packed transfers + depth-20 live
#      (headline + scale_10m + join_10m + glm_1m), artifact committed.
#   2. adaptivity A/B (H2O3_TPU_BIN_ADAPT=0 control run).
#   3. Pallas tile sweep (tools/bench_kernel_sweep.py) for the next kernel
#      iteration.
set -x
cd "$(dirname "$0")/.."

stamp=$(date -u +%Y%m%dT%H%M%SZ)
timeout 1200 python bench.py | tee "BENCH_builder_${stamp}.json"

H2O3_TPU_BIN_ADAPT=0 timeout 1200 python bench.py \
  | tee "BENCH_builder_${stamp}_noadapt.json"

timeout 2400 python tools/bench_kernel_sweep.py \
  | tee "KERNEL_SWEEP_${stamp}.jsonl"

git add "BENCH_builder_${stamp}.json" "BENCH_builder_${stamp}_noadapt.json" \
        "KERNEL_SWEEP_${stamp}.jsonl"
git commit -m "TPU measurement backlog: bench (adapt on/off) + kernel tile sweep"
