"""Measure the CPU GBM reference for bench.py's ``vs_baseline`` denominator.

The north star (BASELINE.md) is a *ratio* — ≥10x a 16-node CPU cluster,
AUC-matched — but no CPU reference had ever been measured through round 4,
so ``vs_baseline`` was literally ``value / 1.0``. This script runs sklearn's
``HistGradientBoostingClassifier`` (the documented stand-in for upstream's
CPU histogram GBM; upstream `hex/tree/gbm` is the same histogram-GBM family
[UNVERIFIED: reference mount empty all project life]) on the EXACT headline
workload — same generator, rows, cols, tree count, depth, bin count, leaf
minimum, learning rate — and prints one JSON line with trees/sec, AUC and
box specs. The measured number goes in BASELINE.md and bench.py's
``BASELINE_TREES_PER_SEC``; the cluster-equivalence arithmetic lives in
BASELINE.md next to the number.

Run: ``python tools/bench_cpu_baseline.py`` (CPU only; never touches jax).
"""

import json
import os
import sys
import time

# Pin to ONE thread before sklearn/OpenMP load: the number documented in
# BASELINE.md is a per-core reference, and on a multicore box an unpinned
# HistGradientBoosting fit would silently produce a multithreaded,
# incomparable denominator.
os.environ["OMP_NUM_THREADS"] = "1"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # reuse the exact headline data generator + constants

if bench.N_ROWS != 1_000_000:
    sys.exit(
        f"refusing to run: bench.N_ROWS={bench.N_ROWS} (H2O3_TPU_BENCH_SCALE "
        "is set?) — the denominator must be measured at full headline scale"
    )


def main() -> None:
    from sklearn.ensemble import HistGradientBoostingClassifier
    from sklearn.metrics import roc_auc_score

    df = bench.make_data()
    X = df[[c for c in df.columns if c != "label"]].to_numpy()
    y = (df["label"] == "s").to_numpy()

    def make_clf():
        return HistGradientBoostingClassifier(
            max_iter=bench.N_TREES,
            max_depth=bench.DEPTH,
            max_leaf_nodes=None,   # sklearn's default 31-leaf cap would build
                                   # SMALLER trees than the depth-6 (<=64 leaf)
                                   # TPU headline; depth is the only stop
            learning_rate=0.1,
            max_bins=255,          # same static-quantile resolution as the TPU path
            min_samples_leaf=10,   # headline min_rows
            early_stopping=False,  # the TPU headline builds all 20 trees
            validation_fraction=None,
        )

    # warmup on a slice so one-time import/alloc overhead stays out of the
    # timed fit (the TPU headline also excludes compile via a warmup train)
    HistGradientBoostingClassifier(
        max_iter=2, max_depth=bench.DEPTH, early_stopping=False
    ).fit(X[:50_000], y[:50_000])

    # The documented denominator is the MEDIAN of 4 reps (the box is shared
    # and single-rep spread was measured at ~9%); each rep fits fresh.
    reps = []
    for _ in range(4):
        clf = make_clf()
        t0 = time.time()
        clf.fit(X, y)
        reps.append(time.time() - t0)
    dt = sorted(reps)[1:3]
    dt = (dt[0] + dt[1]) / 2  # median of 4
    auc = float(roc_auc_score(y, clf.predict_proba(X)[:, 1]))

    ncpu = os.cpu_count()
    with open("/proc/cpuinfo") as f:
        model = next(
            (ln.split(":", 1)[1].strip() for ln in f if ln.startswith("model name")),
            "unknown",
        )
    print(
        json.dumps(
            {
                "metric": (
                    f"CPU reference: sklearn HistGradientBoosting trees/sec "
                    f"({bench.N_ROWS // 1_000_000}M rows x {bench.N_COLS} cols, "
                    f"depth {bench.DEPTH}, 255 bins, AUC={auc:.4f})"
                ),
                "value": round(bench.N_TREES / dt, 4),
                "unit": "trees/sec",
                "seconds": round(dt, 2),
                "rep_seconds": [round(r, 2) for r in reps],
                "protocol": "median of 4 fresh fits, warm process",
                "auc": round(auc, 4),
                "n_rows": bench.N_ROWS,
                "n_threads": 1,  # enforced via OMP_NUM_THREADS above
                "n_cpus_on_box": ncpu,
                "cpu_model": model,
                "sklearn_version": __import__("sklearn").__version__,
            }
        )
    )


if __name__ == "__main__":
    main()
