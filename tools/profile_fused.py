"""Profile the FUSED tree program (the path that actually runs) and report
per-phase device-time shares from a jax profiler trace.

VERDICT r4 weak #2: the bench breakdown used to time standalone per-phase
programs and reconstruct a per-tree estimate that disagreed with the fused
headline by 8x — useless for steering optimization. This tool instead:

1. compiles the real training program with ``--xla_dump_to`` so the
   optimized HLO text records, per instruction, the ``op_name`` metadata
   that carries our ``jax.named_scope`` phase tags (ph_hist / ph_split /
   ph_part / ph_grad — see shared_tree.py / ops/histogram.py);
2. runs one full (already compiled) train under ``jax.profiler.trace``;
3. joins the trace's per-op device events (``hlo_op`` stat) against the
   dump's op->phase map and aggregates device nanoseconds per phase.

The result is a breakdown of the program that RAN, summing to its measured
device time, with host share = wall - device. Works on CPU and TPU backends
(phase attribution inside fusions follows XLA's representative-op metadata,
so shares are approximate at fusion boundaries but sum exactly).

Standalone: ``python tools/profile_fused.py`` (env: H2O3_TPU_BENCH_SCALE).
Library: ``bench.py`` calls :func:`trace_phases` for the headline payload.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

PHASES = ("ph_hist", "ph_split", "ph_part", "ph_grad")

_DUMP_ENV = "H2O3_TPU_PROFILE_DUMP_DIR"


def ensure_dump_env(dump_dir: str) -> str:
    """Arrange for XLA to dump optimized HLO text; return the EFFECTIVE dump
    dir. MUST run before the first jax compilation in the process (XLA parses
    XLA_FLAGS once). If the operator already set --xla_dump_to, that dir is
    reused (ours would silently receive nothing)."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_dump_to=(\S+)", flags)
    if m:
        dump_dir = m.group(1)
        if "--xla_dump_hlo_as_text" not in flags:
            os.environ["XLA_FLAGS"] = f"{flags} --xla_dump_hlo_as_text"
    else:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_dump_to={dump_dir} --xla_dump_hlo_as_text".strip()
        )
    os.makedirs(dump_dir, exist_ok=True)
    return dump_dir


def prepare_dump_dir() -> str:
    """One-stop pre-jax setup: pick/create the dump dir, record it in
    ``_DUMP_ENV``, wire XLA_FLAGS. Used by both main() and bench.py's
    headline child — keep the recipe in exactly one place."""
    import tempfile

    dump_dir = os.environ.get(_DUMP_ENV) or tempfile.mkdtemp(
        prefix="h2o3_hlo_dump_"
    )
    dump_dir = ensure_dump_env(dump_dir)
    os.environ[_DUMP_ENV] = dump_dir
    return dump_dir


def phase_map_from_dump(dump_dir: str) -> dict[tuple[str, str], str]:
    """(hlo_module, hlo_op) -> phase, parsed from after-optimizations dumps."""
    out: dict[tuple[str, str], str] = {}
    for path in glob.glob(os.path.join(dump_dir, "*after_optimizations*.txt")):
        module = None
        with open(path) as f:
            for line in f:
                if module is None:
                    m = re.match(r"HloModule (\S+?),", line)
                    if m:
                        module = m.group(1)
                    continue
                m = re.match(r"\s+(?:ROOT )?%?([\w.\-]+) = .*?metadata={[^}]*op_name=\"([^\"]+)\"", line)
                if not m:
                    continue
                name, op_name = m.groups()
                for ph in PHASES:
                    if ph in op_name:
                        out[(module, name)] = ph
                        break
    return out


def aggregate_trace(trace_dir: str, phase_map: dict) -> dict:
    """Aggregate device-event nanoseconds per phase from an xplane trace."""
    import jax.profiler as jp

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        return {"error": "no xplane.pb produced by the trace"}
    # aggregate PER DEVICE, then average: an SPMD mesh runs the same program
    # on every device, and summing across devices would overstate device
    # time by the mesh size (observed 8x on the virtual CPU mesh)
    per_dev: dict = {}
    modules: dict[str, float] = {}
    n_device_events = 0
    pd = jp.ProfileData.from_file(max(paths, key=os.path.getmtime))
    for plane in pd.planes:
        for line in plane.lines:
            for ev in line.events:
                stats = dict(ev.stats)
                op = stats.get("hlo_op")
                if op is None or ev.name.startswith("end:"):
                    continue
                module = str(stats.get("hlo_module", ""))
                dur = float(ev.duration_ns)
                ordinal = stats.get("device_ordinal", plane.name)
                agg = per_dev.setdefault(
                    ordinal, {ph: 0.0 for ph in (*PHASES, "other", "_total")}
                )
                n_device_events += 1
                agg["_total"] += dur
                modules[module] = modules.get(module, 0.0) + dur
                agg[phase_map.get((module, str(op)), "other")] += dur
    if n_device_events == 0:
        return {"error": "trace has no device events (plugin profiler gap?)"}
    n_dev = len(per_dev)
    mean = {
        k: sum(d[k] for d in per_dev.values()) / n_dev
        for k in (*PHASES, "other", "_total")
    }
    top_modules = sorted(modules.items(), key=lambda kv: -kv[1])[:5]
    return {
        "phases_s": {
            k: round(mean[k] / 1e9, 4) for k in (*PHASES, "other")
        },
        "device_total_s": round(mean["_total"] / 1e9, 4),
        "n_devices": n_dev,
        "n_device_events": n_device_events,
        "top_modules_s": {
            k: round(v / n_dev / 1e9, 4) for k, v in top_modules
        },
    }


def trace_phases(run_once, dump_dir: str) -> dict:
    """Trace one execution of ``run_once`` (already compiled) and return the
    per-phase breakdown dict. Never raises — errors come back in the dict."""
    import shutil
    import tempfile

    import jax

    trace_dir = tempfile.mkdtemp(prefix="h2o3_trace_")
    try:
        with jax.profiler.trace(trace_dir):
            t0 = time.time()
            run_once()
            wall = time.time() - t0
        out = aggregate_trace(trace_dir, phase_map_from_dump(dump_dir))
        out["wall_s"] = round(wall, 4)
        if "device_total_s" in out:
            out["host_s"] = round(max(wall - out["device_total_s"], 0.0), 4)
        return out
    except Exception as e:  # noqa: BLE001 — diagnostics must never sink a bench
        return {"error": repr(e)}
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)


def cleanup_dump_dir() -> None:
    """Best-effort removal of the dump dir once the breakdown is extracted —
    dumps are tens of MB per bench run and /tmp outlives us on a TPU VM.
    Skipped when the operator supplied their own --xla_dump_to."""
    import shutil

    d = os.environ.get(_DUMP_ENV, "")
    if "h2o3_hlo_dump_" in os.path.basename(d.rstrip("/")):
        shutil.rmtree(d, ignore_errors=True)


def main() -> None:
    import tempfile

    dump_dir = os.environ.get(_DUMP_ENV)
    if not dump_dir:
        # re-exec with the dump env so XLA_FLAGS is set before jax loads
        dump_dir = tempfile.mkdtemp(prefix="h2o3_hlo_dump_")
        env = dict(os.environ, **{_DUMP_ENV: dump_dir})
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
    dump_dir = prepare_dump_dir()

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench
    import h2o3_tpu
    from h2o3_tpu.models.tree import GBM

    h2o3_tpu.init(log_level="WARN")
    fr = h2o3_tpu.upload_file(bench.make_data())
    kw = dict(
        ntrees=bench.N_TREES, max_depth=bench.DEPTH, learn_rate=0.1,
        min_rows=10.0, score_tree_interval=1000, seed=42,
    )
    GBM(**kw).train(y="label", training_frame=fr)  # compile (dumps HLO)
    out = trace_phases(
        lambda: GBM(**kw).train(y="label", training_frame=fr), dump_dir
    )
    cleanup_dump_dir()
    out["n_trees"] = bench.N_TREES
    out["n_rows"] = bench.N_ROWS
    print(json.dumps(out))


if __name__ == "__main__":
    main()
