#!/usr/bin/env python
"""Self-healing recovery drill (ISSUE 10): kill a worker mid-GBM and prove
the supervised recovery loop — detection → reform → resume — completes with
NO operator action and reproduces the uninterrupted run.

What it does, per algo (gbm / glm / automl):

1. builds the uninterrupted reference model;
2. re-runs with ``export_checkpoints_dir`` under
   :func:`h2o3_tpu.cluster.recovery.run_supervised` with a one-shot
   ``die:<algo>`` fault armed — the worker "dies" at a collective boundary
   right after an interval snapshot, exactly what a preempted v5e host does;
3. asserts the healed run's metrics land within the PR-2 1e-6 resume pin of
   the reference and the cloud ended healthy with the generation ticked;
4. emits one JSON artifact line with the metric deltas, restart counts, and
   the ``recovery_seconds`` histogram snapshot from the registry.

Queued in tools/run_tpu_backlog.sh for the next tunnel window; runs on the
CPU proxy too (that is what CI exercises via tests/test_recovery.py — this
tool is the measured-artifact version of the same drill).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU proxy runs drill the same 8-device sharded mesh the bench artifacts
# use (real accelerators keep their native device count)
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu" and \
        "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def _frame(n=4000, seed=3):
    import numpy as np
    import pandas as pd

    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return Frame.from_pandas(df)


def _drill_gbm(fr, ckdir):
    import numpy as np

    from h2o3_tpu.cluster import recovery
    from h2o3_tpu.models import GBM
    from h2o3_tpu.utils import faults

    kw = dict(ntrees=16, max_depth=4, seed=11, learn_rate=0.2,
              score_tree_interval=4)
    full = GBM(**kw).train(y="y", training_frame=fr)

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(**kw2).train(y="y", training_frame=fr)

    t0 = time.perf_counter()
    with faults.inject(die={"gbm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="gbm drill")
    wall = time.perf_counter() - t0
    delta = abs(healed.training_metrics.logloss - full.training_metrics.logloss)
    assert delta <= 1e-6, f"gbm resume pin violated: {delta}"
    assert healed.output["ntrees_actual"] == kw["ntrees"]
    pa = full.predict(fr).vec("p").to_numpy()
    pb = healed.predict(fr).vec("p").to_numpy()
    return {"logloss_delta": delta, "wall_s": wall,
            "pred_max_delta": float(np.max(np.abs(pa - pb)))}


def _drill_glm(fr, ckdir):
    import numpy as np

    from h2o3_tpu.cluster import recovery
    from h2o3_tpu.models import GLM
    from h2o3_tpu.utils import faults

    kw = dict(family="binomial", max_iterations=25, seed=1)
    full = GLM(**kw).train(y="y", training_frame=fr)

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GLM(**kw2).train(y="y", training_frame=fr)

    t0 = time.perf_counter()
    with faults.inject(die={"glm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="glm",
                                         description="glm drill")
    wall = time.perf_counter() - t0
    beta_delta = float(np.max(np.abs(
        np.asarray(healed.output["beta_std"]) - np.asarray(full.output["beta_std"]))))
    delta = abs(healed.training_metrics.logloss - full.training_metrics.logloss)
    assert delta <= 1e-6, f"glm resume pin violated: {delta}"
    return {"logloss_delta": delta, "beta_max_delta": beta_delta,
            "wall_s": wall}


def _drill_automl(fr, ckdir):
    from h2o3_tpu.cluster import recovery
    from h2o3_tpu.automl import AutoML
    from h2o3_tpu.utils import faults

    spec = dict(max_models=3, nfolds=2, seed=11, max_runtime_secs=0.0,
                include_algos=["GBM", "GLM"], project_name="drill")

    def lb(aml):
        return sorted(
            (r["model_id"].split("_")[0], round(float(r["auc"]), 10))
            for r in aml.leaderboard.as_table())

    full = AutoML(**spec)
    full.train(y="y", training_frame=fr)
    assert full.leaderboard.models, "drill spec built no models"

    def _launch(_ckpt):
        aml = AutoML(export_checkpoints_dir=ckdir, **spec)
        aml.train(y="y", training_frame=fr)
        return aml

    t0 = time.perf_counter()
    with faults.inject(die={"automl"}):
        healed = recovery.run_supervised(_launch, description="automl drill")
    wall = time.perf_counter() - t0
    assert lb(healed) == lb(full), "automl resume leaderboard diverged"
    recovered = sum(1 for e in healed.event_log if e["stage"] == "recover")
    assert recovered >= 1, "resume recovered no steps — the drill was vacuous"
    return {"leaderboard_equal": True, "steps_recovered": recovered,
            "wall_s": wall}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="artifact path (default: "
                    "RECOVERY_DRILL_<stamp>.json in the repo root)")
    ap.add_argument("--algos", default="gbm,glm,automl")
    args = ap.parse_args(argv)

    os.environ.setdefault("H2O3_TPU_RECOVERY", "1")
    os.environ.setdefault("H2O3_TPU_RECOVERY_BACKOFF", "0.05")

    import tempfile

    import jax

    import h2o3_tpu
    from h2o3_tpu.cluster import cloud
    from h2o3_tpu.utils import metrics as mx

    h2o3_tpu.init()
    fr = _frame()
    drills = {"gbm": _drill_gbm, "glm": _drill_glm, "automl": _drill_automl}
    gen0 = cloud.generation()
    results = {}
    for algo in args.algos.split(","):
        algo = algo.strip()
        with tempfile.TemporaryDirectory(prefix=f"drill_{algo}_") as ckdir:
            results[algo] = drills[algo](fr, ckdir)
        assert cloud.degraded_reason() is None, "cloud left degraded"

    # the recovery_seconds histogram snapshot: detection → resume dispatch
    snap = mx.REGISTRY.snapshot()
    fam = {name: snap.get(name) for name in (
        "recovery_seconds", "recovery_attempts_total",
        "cloud_generation", "cloud_health_transitions_total")}
    artifact = {
        "kind": "recovery_drill",
        "stamp": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        "backend": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "generations_ticked": cloud.generation() - gen0,
        "results": results,
        "recovery_metrics": fam,
        "ok": True,
    }
    out = args.out or f"RECOVERY_DRILL_{artifact['stamp']}.json"
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    sys.exit(main())
