#!/usr/bin/env python
"""Self-healing recovery drill (ISSUE 10): kill a worker mid-GBM and prove
the supervised recovery loop — detection → reform → resume — completes with
NO operator action and reproduces the uninterrupted run.

What it does, per algo (gbm / glm / dl / automl):

1. builds the uninterrupted reference model;
2. re-runs with ``export_checkpoints_dir`` under
   :func:`h2o3_tpu.cluster.recovery.run_supervised` with a one-shot
   ``die:<algo>`` fault armed — the worker "dies" at a collective boundary
   right after an interval snapshot, exactly what a preempted v5e host does;
3. asserts the healed run's metrics land within the PR-2 1e-6 resume pin of
   the reference and the cloud ended healthy with the generation ticked;
4. emits one JSON artifact line with the metric deltas, restart counts, and
   the ``recovery_seconds`` histogram snapshot from the registry.

``--elastic`` (ISSUE 17) is the topology-chaos variant: the kill is a
``reshape:RxC`` fault, so the formation "comes back different" and the
snapshot must resume on a CHANGED mesh shape. Each algo is killed on a
different transition of the shape-change matrix (8->4 scale-down, 4->8
scale-up, 2x4->4x2 transpose, 1-D->2-D) with the same 1e-6 final-metric
pin plus splits/coefs parity; emits ``ELASTIC_DRILL_<stamp>.json``.

Queued in tools/run_tpu_backlog.sh for the next tunnel window; runs on the
CPU proxy too (that is what CI exercises via tests/test_recovery.py — this
tool is the measured-artifact version of the same drill).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU proxy runs drill the same 8-device sharded mesh the bench artifacts
# use (real accelerators keep their native device count)
if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu" and \
        "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8").strip()


def _frame(n=4000, seed=3):
    import numpy as np
    import pandas as pd

    from h2o3_tpu.frame.frame import Frame

    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "c": rng.choice(["x", "y", "z"], n),
    })
    eta = df["a"] * 1.5 + (df["c"] == "x") * 2 - df["b"]
    df["y"] = np.where(eta + rng.normal(size=n) > 0, "p", "n")
    return Frame.from_pandas(df)


def _drill_gbm(fr, ckdir):
    import numpy as np

    from h2o3_tpu.cluster import recovery
    from h2o3_tpu.models import GBM
    from h2o3_tpu.utils import faults

    kw = dict(ntrees=16, max_depth=4, seed=11, learn_rate=0.2,
              score_tree_interval=4)
    full = GBM(**kw).train(y="y", training_frame=fr)

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GBM(**kw2).train(y="y", training_frame=fr)

    t0 = time.perf_counter()
    with faults.inject(die={"gbm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="gbm",
                                         description="gbm drill")
    wall = time.perf_counter() - t0
    delta = abs(healed.training_metrics.logloss - full.training_metrics.logloss)
    assert delta <= 1e-6, f"gbm resume pin violated: {delta}"
    assert healed.output["ntrees_actual"] == kw["ntrees"]
    pa = full.predict(fr).vec("p").to_numpy()
    pb = healed.predict(fr).vec("p").to_numpy()
    return {"logloss_delta": delta, "wall_s": wall,
            "pred_max_delta": float(np.max(np.abs(pa - pb)))}


def _drill_glm(fr, ckdir):
    import numpy as np

    from h2o3_tpu.cluster import recovery
    from h2o3_tpu.models import GLM
    from h2o3_tpu.utils import faults

    kw = dict(family="binomial", max_iterations=25, seed=1)
    full = GLM(**kw).train(y="y", training_frame=fr)

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return GLM(**kw2).train(y="y", training_frame=fr)

    t0 = time.perf_counter()
    with faults.inject(die={"glm"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir, algo="glm",
                                         description="glm drill")
    wall = time.perf_counter() - t0
    beta_delta = float(np.max(np.abs(
        np.asarray(healed.output["beta_std"]) - np.asarray(full.output["beta_std"]))))
    delta = abs(healed.training_metrics.logloss - full.training_metrics.logloss)
    assert delta <= 1e-6, f"glm resume pin violated: {delta}"
    return {"logloss_delta": delta, "beta_max_delta": beta_delta,
            "wall_s": wall}


def _drill_dl(fr, ckdir):
    import numpy as np

    from h2o3_tpu.cluster import recovery
    from h2o3_tpu.models import DeepLearning
    from h2o3_tpu.utils import faults

    kw = dict(hidden=[8], seed=4, mini_batch_size=64, epochs=4)
    full = DeepLearning(**kw).train(y="y", training_frame=fr)

    def _launch(ckpt):
        kw2 = dict(kw, export_checkpoints_dir=ckdir)
        if ckpt:
            kw2["checkpoint"] = ckpt
        return DeepLearning(**kw2).train(y="y", training_frame=fr)

    t0 = time.perf_counter()
    with faults.inject(die={"deeplearning"}):
        healed = recovery.run_supervised(_launch, ckdir=ckdir,
                                         algo="deeplearning",
                                         description="dl drill")
    wall = time.perf_counter() - t0
    delta = abs(healed.training_metrics.logloss - full.training_metrics.logloss)
    assert delta <= 1e-6, f"dl resume pin violated: {delta}"
    assert healed.output["epochs_trained"] == kw["epochs"]
    pa = full.predict(fr).vec("p").to_numpy()
    pb = healed.predict(fr).vec("p").to_numpy()
    return {"logloss_delta": delta, "wall_s": wall,
            "pred_max_delta": float(np.max(np.abs(pa - pb)))}


def _drill_automl(fr, ckdir):
    from h2o3_tpu.cluster import recovery
    from h2o3_tpu.automl import AutoML
    from h2o3_tpu.utils import faults

    spec = dict(max_models=3, nfolds=2, seed=11, max_runtime_secs=0.0,
                include_algos=["GBM", "GLM"], project_name="drill")

    def lb(aml):
        return sorted(
            (r["model_id"].split("_")[0], round(float(r["auc"]), 10))
            for r in aml.leaderboard.as_table())

    full = AutoML(**spec)
    full.train(y="y", training_frame=fr)
    assert full.leaderboard.models, "drill spec built no models"

    def _launch(_ckpt):
        aml = AutoML(export_checkpoints_dir=ckdir, **spec)
        aml.train(y="y", training_frame=fr)
        return aml

    t0 = time.perf_counter()
    with faults.inject(die={"automl"}):
        healed = recovery.run_supervised(_launch, description="automl drill")
    wall = time.perf_counter() - t0
    assert lb(healed) == lb(full), "automl resume leaderboard diverged"
    recovered = sum(1 for e in healed.event_log if e["stage"] == "recover")
    assert recovered >= 1, "resume recovered no steps — the drill was vacuous"
    return {"leaderboard_equal": True, "steps_recovered": recovered,
            "wall_s": wall}


# ---------------------------------------------------------------------------
# elastic drills (ISSUE 17): kill mid-train with a reshape:RxC fault and
# resume the snapshot on a DIFFERENT mesh shape. Each algo is killed on a
# different transition so one artifact covers the whole shape-change matrix
# (scale-down, scale-up, 2-D transpose, 1-D <-> 2-D) on 8 devices.

ELASTIC_MATRIX = (
    ("gbm", (1, 8), (1, 4), "8->4"),
    ("glm", (1, 4), (1, 8), "4->8"),
    ("deeplearning", (2, 4), (4, 2), "2x4->4x2"),
    ("gbm", (1, 8), (2, 4), "1d->2d"),
)


def _elastic_case(algo, start, end, fr):
    """Reference run on ``start``; killed run re-forms onto ``end`` mid-train
    and resumes its snapshot there. Returns the parity record (pins at the
    PR-2 1e-6 resume contract — docs/RECOVERY.md 'Elastic resume')."""
    import tempfile

    import numpy as np

    from h2o3_tpu.cluster import cloud, recovery
    from h2o3_tpu.models import GBM, GLM, DeepLearning
    from h2o3_tpu.parallel import mesh
    from h2o3_tpu.utils import faults

    cls, kw = {
        "gbm": (GBM, dict(ntrees=16, max_depth=4, seed=11, learn_rate=0.2,
                          score_tree_interval=4)),
        "glm": (GLM, dict(family="binomial", max_iterations=25, seed=1)),
        "deeplearning": (DeepLearning, dict(hidden=[8], seed=4,
                                            mini_batch_size=64, epochs=4)),
    }[algo]

    mesh.reform_mesh(start)
    full = cls(**kw).train(y="y", training_frame=fr)
    ref_ll = full.training_metrics.logloss
    ref_pred = full.predict(fr).vec("p").to_numpy().copy()

    with tempfile.TemporaryDirectory(prefix=f"elastic_{algo}_") as ckdir:
        def _launch(ckpt):
            kw2 = dict(kw, export_checkpoints_dir=ckdir)
            if ckpt:
                kw2["checkpoint"] = ckpt
            return cls(**kw2).train(y="y", training_frame=fr)

        t0 = time.perf_counter()
        with faults.inject(reshape=end):
            healed = recovery.run_supervised(
                _launch, ckdir=ckdir, algo=algo,
                description=f"elastic {algo} {start}->{end}")
        wall = time.perf_counter() - t0

    got = dict(mesh.get_mesh().shape)
    assert got.get("rows", 1) * got.get("cols", 1) == end[0] * end[1], \
        f"resume did not land on {end}: mesh is {got}"
    assert cloud.degraded_reason() is None, "cloud left degraded"

    delta = abs(healed.training_metrics.logloss - ref_ll)
    assert delta <= 1e-6, f"{algo} elastic resume pin violated: {delta}"
    rec = {"algo": algo, "from": f"{start[0]}x{start[1]}",
           "to": f"{end[0]}x{end[1]}", "logloss_delta": delta,
           "recovery_seconds": wall}
    # splits/coefs parity: trees predict identically (split-for-split), GLM
    # coefficients match, DL predictions match — all within f32 resolution
    pred = healed.predict(fr).vec("p").to_numpy()
    rec["pred_max_delta"] = float(np.max(np.abs(ref_pred - pred)))
    assert rec["pred_max_delta"] <= 1e-5, \
        f"{algo} elastic pred parity violated: {rec['pred_max_delta']}"
    if algo == "gbm":
        assert healed.output["ntrees_actual"] == kw["ntrees"]
    elif algo == "deeplearning":
        assert healed.output["epochs_trained"] == kw["epochs"]
    elif algo == "glm":
        rec["beta_max_delta"] = float(np.max(np.abs(
            np.asarray(healed.output["beta_std"])
            - np.asarray(full.output["beta_std"]))))
        assert rec["beta_max_delta"] <= 1e-5, \
            f"glm elastic coef parity violated: {rec['beta_max_delta']}"
    return rec


def _run_elastic(out_path):
    import jax

    import h2o3_tpu
    from h2o3_tpu.cluster import cloud
    from h2o3_tpu.parallel import mesh
    from h2o3_tpu.utils import metrics as mx

    h2o3_tpu.init()
    if len(jax.devices()) < 8:
        print(f"elastic drill needs >= 8 devices (have {len(jax.devices())})",
              file=sys.stderr)
        return 2
    fr = _frame()
    gen0 = cloud.generation()
    results = []
    try:
        for algo, start, end, label in ELASTIC_MATRIX:
            rec = _elastic_case(algo, start, end, fr)
            rec["transition"] = label
            results.append(rec)
            print(f"elastic {label} ({algo}): logloss_delta="
                  f"{rec['logloss_delta']:.2e} "
                  f"recovery_seconds={rec['recovery_seconds']:.2f}")
    finally:
        mesh.reform_mesh()  # re-plan onto every live device for whoever's next

    snap = mx.REGISTRY.snapshot()
    fam = {name: snap.get(name) for name in (
        "recovery_seconds", "recovery_attempts_total", "cloud_generation")}
    artifact = {
        "kind": "elastic_drill",
        "stamp": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        "backend": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "generations_ticked": cloud.generation() - gen0,
        "results": results,
        "recovery_seconds": max(r["recovery_seconds"] for r in results),
        "recovery_metrics": fam,
        "ok": True,
    }
    out = out_path or f"ELASTIC_DRILL_{artifact['stamp']}.json"
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps(artifact))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None, help="artifact path (default: "
                    "RECOVERY_DRILL_<stamp>.json in the repo root)")
    ap.add_argument("--algos", default="gbm,glm,automl")
    ap.add_argument("--elastic", action="store_true",
                    help="topology-chaos mode (ISSUE 17): each algo is "
                    "killed mid-train by a reshape:RxC fault and resumes "
                    "its snapshot on a DIFFERENT mesh shape; emits "
                    "ELASTIC_DRILL_<stamp>.json")
    args = ap.parse_args(argv)

    os.environ.setdefault("H2O3_TPU_RECOVERY", "1")
    os.environ.setdefault("H2O3_TPU_RECOVERY_BACKOFF", "0.05")

    if args.elastic:
        return _run_elastic(args.out)

    import tempfile

    import jax

    import h2o3_tpu
    from h2o3_tpu.cluster import cloud
    from h2o3_tpu.utils import metrics as mx

    h2o3_tpu.init()
    fr = _frame()
    drills = {"gbm": _drill_gbm, "glm": _drill_glm, "dl": _drill_dl,
              "automl": _drill_automl}
    gen0 = cloud.generation()
    results = {}
    for algo in args.algos.split(","):
        algo = algo.strip()
        with tempfile.TemporaryDirectory(prefix=f"drill_{algo}_") as ckdir:
            results[algo] = drills[algo](fr, ckdir)
        assert cloud.degraded_reason() is None, "cloud left degraded"

    # the recovery_seconds histogram snapshot: detection → resume dispatch
    snap = mx.REGISTRY.snapshot()
    fam = {name: snap.get(name) for name in (
        "recovery_seconds", "recovery_attempts_total",
        "cloud_generation", "cloud_health_transitions_total")}
    artifact = {
        "kind": "recovery_drill",
        "stamp": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        "backend": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "generations_ticked": cloud.generation() - gen0,
        "results": results,
        "recovery_metrics": fam,
        "ok": True,
    }
    out = args.out or f"RECOVERY_DRILL_{artifact['stamp']}.json"
    with open(out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    sys.exit(main())
