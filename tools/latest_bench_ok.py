"""Exit 0 iff the newest BENCH_builder_*.json captured a real headline value
AND at least one post-headline phase.

Used by tunnel_watch.sh as the 'did the backlog actually measure anything'
signal — the backlog script's own exit code cannot carry it (tee pipelines,
error-JSON-by-design). Requiring a post-headline phase matters: round 4's
failure mode was exactly 'headline measured, every scale phase dead in a
RESOURCE_EXHAUSTED cascade', and standing down on a headline alone would
forfeit the later windows this round exists to use.
"""

import glob
import json
import os
import sys

# keep in sync with bench.py _PHASES (minus headline)
POST_HEADLINE = (
    "scale_10m", "cat_1m", "join_10m", "glm_1m", "hash_1m", "dl_100k",
    "automl_50k",
)

RECENT_S = 6 * 3600  # this window's artifacts only — stale full runs from
                     # an earlier round must not stand the watcher down


def _stamp_age_s(path: str, now: float) -> float | None:
    """Age from the UTC stamp IN THE FILENAME (BENCH_builder_<stamp>*.json).

    mtime is useless here: these artifacts are git-committed and a fresh
    checkout re-stamps them to checkout time, which would let a previous
    round's success stand the watcher down. Old-style names without a
    stamp are by definition not from this window."""
    import re
    from datetime import datetime, timezone

    m = re.search(r"(\d{8}T\d{6})Z", os.path.basename(path))
    if not m:
        return None
    t = datetime.strptime(m.group(1), "%Y%m%dT%H%M%S").replace(
        tzinfo=timezone.utc
    )
    return now - t.timestamp()


def main() -> int:
    import time

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    now = time.time()
    # ANY qualifying artifact from this window counts: the backlog writes
    # headline-only A/B controls (_adapt/_nbins127/_matmul) AFTER the full
    # run, so "the newest file" is usually a control and judging only it
    # would loop the watcher forever on a fully successful window
    recent = []
    try:
        candidates = glob.glob(os.path.join(here, "BENCH_builder_*.json"))
    except OSError as e:  # unreadable repo dir: clean message, not traceback
        print(f"cannot list bench artifacts under {here}: {e}")
        return 1
    for p in candidates:
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    recent = [p for _, p in sorted(recent)]
    if not recent:
        print("no recent BENCH_builder artifacts")
        return 1
    for path in recent:
        headline_ok = phases_ok = registry_ok = False
        psum_note = ""
        note = ""
        try:
            with open(path) as f:
                d = json.loads(f.readline())
            if isinstance(d, dict):
                headline_ok = float(d.get("value") or 0) > 0
                phases_ok = any(
                    isinstance(d.get(p), dict) for p in POST_HEADLINE
                )
                # the registry-snapshot block: bench counters sourced from
                # the live /3/Metrics registry — an artifact without it was
                # produced by a pre-observability bench and cannot be
                # cross-checked against the endpoint
                reg = d.get("metrics_registry")
                registry_ok = isinstance(reg, dict) and len(reg) > 0
                # psum_bytes_per_tree (split-pipeline traffic, ISSUE 5) is
                # OPTIONAL — older artifacts predate it — but when present
                # it must be a sane number: a negative/NaN/garbage value
                # means the byte tally broke and the A/B replay would be
                # comparing noise, so the artifact does not count
                if "psum_bytes_per_tree" in d:
                    try:
                        v = float(d["psum_bytes_per_tree"])
                        sane = v >= 0 and v == v and v != float("inf")
                    except (TypeError, ValueError):
                        sane = False
                    psum_note = (
                        f" psum-bytes/tree={d['psum_bytes_per_tree']}"
                        if sane else " psum-bytes/tree=INSANE"
                    )
                    if not sane:
                        headline_ok = False
                # hist_hbm_bytes_per_tree (fused split pipeline, ISSUE 6) is
                # OPTIONAL like psum above, but when present it must be a
                # sane non-negative finite number or the fused-vs-unfused
                # A/B would be comparing noise
                if "hist_hbm_bytes_per_tree" in d:
                    try:
                        v = float(d["hist_hbm_bytes_per_tree"])
                        sane = v >= 0 and v == v and v != float("inf")
                    except (TypeError, ValueError):
                        sane = False
                    psum_note += (
                        f" hist-hbm-bytes/tree={d['hist_hbm_bytes_per_tree']}"
                        if sane else " hist-hbm-bytes/tree=INSANE"
                    )
                    if not sane:
                        headline_ok = False
        except OSError as e:  # vanished/unreadable between glob and open
            note = f" (unreadable: {e.strerror or e})"
        except Exception as e:  # torn/empty/garbage JSON is a MISSING, not a crash
            note = f" (unparseable: {type(e).__name__})"
        print(
            f"{os.path.basename(path)}: "
            f"headline={'ok' if headline_ok else 'MISSING'}"
            f" post-headline-phases={'ok' if phases_ok else 'MISSING'}"
            f" registry-snapshot={'ok' if registry_ok else 'MISSING'}"
            f"{psum_note}{note}"
        )
        if headline_ok and phases_ok and registry_ok:
            return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
