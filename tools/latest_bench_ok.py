"""Exit 0 iff the newest BENCH_builder_*.json captured a real headline value
AND at least one post-headline phase.

Used by tunnel_watch.sh as the 'did the backlog actually measure anything'
signal — the backlog script's own exit code cannot carry it (tee pipelines,
error-JSON-by-design). Requiring a post-headline phase matters: round 4's
failure mode was exactly 'headline measured, every scale phase dead in a
RESOURCE_EXHAUSTED cascade', and standing down on a headline alone would
forfeit the later windows this round exists to use.
"""

import glob
import json
import os
import sys

# keep in sync with bench.py _PHASES (minus headline)
POST_HEADLINE = (
    "scale_10m", "cat_1m", "join_10m", "glm_1m", "hash_1m", "dl_100k",
    "automl_50k",
)

def main() -> int:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = glob.glob(os.path.join(here, "BENCH_builder_*.json"))
    if not paths:
        return 1
    newest = max(paths, key=os.path.getmtime)
    headline_ok = phases_ok = False
    try:
        with open(newest) as f:
            d = json.loads(f.readline())
        if isinstance(d, dict):
            headline_ok = float(d.get("value") or 0) > 0
            phases_ok = any(isinstance(d.get(p), dict) for p in POST_HEADLINE)
    except Exception:
        pass
    print(
        f"{os.path.basename(newest)}: headline={'ok' if headline_ok else 'MISSING'}"
        f" post-headline-phases={'ok' if phases_ok else 'MISSING'}"
    )
    return 0 if (headline_ok and phases_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
