"""Exit 0 iff the newest BENCH_builder_*.json captured a real headline value
AND at least one post-headline phase.

Used by tunnel_watch.sh as the 'did the backlog actually measure anything'
signal — the backlog script's own exit code cannot carry it (tee pipelines,
error-JSON-by-design). Requiring a post-headline phase matters: round 4's
failure mode was exactly 'headline measured, every scale phase dead in a
RESOURCE_EXHAUSTED cascade', and standing down on a headline alone would
forfeit the later windows this round exists to use.
"""

import glob
import json
import os
import sys

# keep in sync with bench.py _PHASES (minus headline)
POST_HEADLINE = (
    "scale_10m", "cat_1m", "join_10m", "glm_1m", "hash_1m", "dl_100k",
    "automl_50k",
)

RECENT_S = 6 * 3600  # this window's artifacts only — stale full runs from
                     # an earlier round must not stand the watcher down


def _stamp_age_s(path: str, now: float) -> float | None:
    """Age from the UTC stamp IN THE FILENAME (BENCH_builder_<stamp>*.json).

    mtime is useless here: these artifacts are git-committed and a fresh
    checkout re-stamps them to checkout time, which would let a previous
    round's success stand the watcher down. Old-style names without a
    stamp are by definition not from this window."""
    import re
    from datetime import datetime, timezone

    m = re.search(r"(\d{8}T\d{6})Z", os.path.basename(path))
    if not m:
        return None
    t = datetime.strptime(m.group(1), "%Y%m%dT%H%M%S").replace(
        tzinfo=timezone.utc
    )
    return now - t.timestamp()


def _loadtest_ok(here: str, now: float):
    """Sanity-check the newest recent LOADTEST_*.json (tools/load_test.py,
    the serving-tier A/B). Returns None when no recent artifact exists (no
    opinion), else True/False. Checks: non-empty steps each carrying a p99,
    non-zero achieved throughput somewhere, and shed rate <= 1% on every
    step offered at or below half the mode's sustained capacity — a tier
    shedding sub-capacity traffic is broken, not overloaded."""
    recent = []
    for p in glob.glob(os.path.join(here, "LOADTEST_*.json")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        with open(path) as f:
            d = json.loads(f.readline())
        steps = d.get("steps") or []
        summary = d.get("summary") or {}
        if not steps:
            print(f"{name}: NO steps")
            return False
        if not all(s.get("p99_ms") is not None or s.get("ok", 0) == 0
                   for s in steps):
            print(f"{name}: step missing p99")
            return False
        if not any(float(s.get("achieved_qps") or 0) > 0 for s in steps):
            print(f"{name}: zero throughput everywhere")
            return False
        for s in steps:
            cap = summary.get(f"{s.get('mode')}_sustained_qps") or 0
            if cap and s["offered_qps"] <= 0.5 * cap and s["shed_rate"] > 0.01:
                print(f"{name}: shed at sub-capacity load "
                      f"({s['mode']} offered={s['offered_qps']} "
                      f"shed_rate={s['shed_rate']})")
                return False
        parity = summary.get("parity_byte_equal")
        if parity is False:
            print(f"{name}: batched/control predictions DIVERGED")
            return False
        # span-sourced latency breakdown (ISSUE 18) is OPTIONAL — older
        # artifacts predate it — but when a step carries one, every leg
        # that counted requests must carry a finite non-negative mean, or
        # the breakdown the batch-window tuning relies on is garbage
        for s in steps:
            for leg, st in (s.get("latency_breakdown") or {}).items():
                if not st.get("count"):
                    continue
                try:
                    v = float(st.get("mean_ms"))
                    sane = v >= 0 and v == v and v != float("inf")
                except (TypeError, ValueError):
                    sane = False
                if not sane:
                    print(f"{name}: breakdown leg {leg} mean_ms INSANE "
                          f"({st.get('mean_ms')!r})")
                    return False
        print(f"{name}: steps=ok p99=ok throughput=ok"
              f" speedup={summary.get('speedup')}"
              f" parity={'ok' if parity else 'n/a'}")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _quant_ab_ok(here: str, now: float):
    """Sanity-check the newest recent QUANT_AB_*.jsonl (bench_kernel_sweep
    --quant-ab, the quantized-collective-lane A/B). Returns None when no
    recent artifact exists (no opinion), else True/False. Checks the
    acceptance pins: modeled hist_reduce bytes ratio >= 2 (the lane's
    reason to exist), GBM AUC delta <= 1e-3 and a finite small GLM
    coefficient delta (accuracy envelopes) — a summary violating them
    means the lane regressed and the window's numbers are noise."""
    recent = []
    for p in glob.glob(os.path.join(here, "QUANT_AB_*.jsonl")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        summary = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "quant_ab" in d:
                    summary = d["quant_ab"]
        if not summary:
            print(f"{name}: NO quant_ab summary line")
            return False
        ratio = float(summary.get("hist_bytes_ratio_exact_over_quant") or 0)
        auc_d = float(summary.get("gbm_auc_delta", float("nan")))
        coef_d = float(summary.get("glm_coef_max_delta", float("nan")))
        if not ratio >= 2.0:
            print(f"{name}: hist_reduce byte ratio {ratio} < 2x")
            return False
        if not auc_d <= 1e-3:
            print(f"{name}: GBM AUC delta {auc_d} > 1e-3")
            return False
        if not coef_d <= 1e-2:
            print(f"{name}: GLM coef delta {coef_d} > 1e-2")
            return False
        print(f"{name}: bytes-ratio={ratio} auc-delta={auc_d} "
              f"coef-delta={coef_d} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _oocore_ab_ok(here: str, now: float):
    """Sanity-check the newest recent OOCORE_AB_*.jsonl (bench_kernel_sweep
    --oocore-ab, the out-of-core streaming A/B). Returns None when no
    recent artifact exists (no opinion), else True/False. Checks the
    acceptance pins: the streamed mode really streamed at rows >= 10x the
    window with its peak frame device bytes bounded by the window (the
    fixed-footprint claim), the COMPRESS=0 control stayed resident (the
    kill switch works), and the AUC delta stays inside the f32
    block-summation envelope."""
    recent = []
    for p in glob.glob(os.path.join(here, "OOCORE_AB_*.jsonl")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        summary = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "oocore_ab" in d:
                    summary = d["oocore_ab"]
        if not summary:
            print(f"{name}: NO oocore_ab summary line")
            return False
        if not summary.get("streamed_engaged"):
            print(f"{name}: streamed mode never streamed")
            return False
        if not summary.get("compress0_stayed_resident"):
            print(f"{name}: COMPRESS=0 control STREAMED (kill switch broken)")
            return False
        if not summary.get("peak_within_window"):
            print(f"{name}: peak frame device bytes EXCEEDED the window")
            return False
        if not float(summary.get("rows_over_window") or 0) >= 10.0:
            print(f"{name}: rows_over_window "
                  f"{summary.get('rows_over_window')} < 10x")
            return False
        auc_d = float(summary.get("auc_delta", float("nan")))
        if not auc_d <= 5e-3:
            print(f"{name}: streamed AUC delta {auc_d} > 5e-3")
            return False
        print(f"{name}: streamed=ok peak-in-window=ok "
              f"rows/window={summary['rows_over_window']} "
              f"auc-delta={auc_d} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _fallback_ab_ok(here: str, now: float):
    """Sanity-check the newest recent FALLBACK_AB_*.jsonl
    (bench_kernel_sweep --fallback-ab, the ISSUE-15 fallback-matrix
    closure A/B). Returns None when no recent artifact exists (no
    opinion), else True/False. Checks the acceptance pins: mono GBM preds
    fused-vs-fallback within the block-sum envelope, multinomial GLM coef
    parity <= 2e-3, dropout-DL trajectory parity <= 1e-4 vs the same-masks
    ctl control, the multinomial dispatch drop >= 3x, and the fused lanes'
    wall no worse than the fallback they replace (1.10x proxy-noise
    allowance)."""
    recent = []
    for p in glob.glob(os.path.join(here, "FALLBACK_AB_*.jsonl")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        summary = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "fallback_ab" in d:
                    summary = d["fallback_ab"]
        if not summary:
            print(f"{name}: NO fallback_ab summary line")
            return False
        mono_d = float(summary.get("mono_pred_max_delta", float("nan")))
        glm_d = float(summary.get("glm_coef_max_delta", float("nan")))
        dl_d = float(summary.get("dl_ctl_pred_max_delta", float("nan")))
        if not mono_d <= 1e-4:
            print(f"{name}: mono pred delta {mono_d} > 1e-4")
            return False
        if not glm_d <= 2e-3:
            print(f"{name}: multinomial coef delta {glm_d} > 2e-3")
            return False
        if not dl_d <= 1e-4:
            print(f"{name}: dropout-DL ctl pred delta {dl_d} > 1e-4")
            return False
        gr = float(summary.get("glm_dispatch_ratio_fallback_over_fused")
                   or 0)
        if not gr >= 3.0:
            print(f"{name}: multinomial dispatch ratio {gr} < 3x")
            return False
        for k in ("mono_time_ratio_fused_over_fallback",
                  "glm_time_ratio_fused_over_fallback",
                  "dl_time_ratio_fused_over_fallback"):
            r = float(summary.get(k) or 0)
            if not 0 < r <= 1.10:
                print(f"{name}: {k}={r} outside (0, 1.10]")
                return False
        print(f"{name}: mono-delta={mono_d} glm-delta={glm_d} "
              f"dl-delta={dl_d} glm-dispatch-ratio={gr} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _wave2_ab_ok(here: str, now: float):
    """Sanity-check the newest recent WAVE2_AB_*.jsonl (bench_kernel_sweep
    --wave2-ab, the ISSUE-16 tree-kernel wave-2 A/B). Returns None when no
    recent artifact exists (no opinion), else True/False. Checks the
    acceptance pins: GOSS at a=0.2,b=0.1 streams >=2x fewer row stats per
    level at AUC delta <=1e-3, EFB shrinks the histogram C dimension
    >=1.5x with bit-equal split structure on the integer-exact parity
    frame, the u8-code cache cuts rebin HBM traffic >=2x across repeated
    builds, the int16 lane holds a 1.10x RMSE envelope, lossguide honors
    its leaf budget, and EVERY knob-off control is bit-identical."""
    recent = []
    for p in glob.glob(os.path.join(here, "WAVE2_AB_*.jsonl")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        summary = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "wave2_ab" in d:
                    summary = d["wave2_ab"]
        if not summary:
            print(f"{name}: NO wave2_ab summary line")
            return False
        goss_r = float(summary.get("goss_row_stats_ratio") or 0)
        if not goss_r >= 2.0:
            print(f"{name}: GOSS row-stats ratio {goss_r} < 2x")
            return False
        goss_d = float(summary.get("goss_auc_delta", float("nan")))
        if not goss_d <= 1e-3:
            print(f"{name}: GOSS AUC delta {goss_d} > 1e-3")
            return False
        efb_s = float(summary.get("efb_c_shrink") or 0)
        if not efb_s >= 1.5:
            print(f"{name}: EFB C shrink {efb_s} < 1.5x")
            return False
        u8_r = float(summary.get("u8_rebin_bytes_ratio") or 0)
        if not u8_r >= 2.0:
            print(f"{name}: u8 rebin-bytes ratio {u8_r} < 2x")
            return False
        i16_r = float(summary.get("i16_rmse_ratio", float("nan")))
        if not 0 < i16_r <= 1.10:
            print(f"{name}: i16 RMSE ratio {i16_r} outside (0, 1.10]")
            return False
        for k in ("efb_splits_bit_equal", "goss_off_bit_identical",
                  "u8_off_bit_identical", "i16_off_bit_identical",
                  "lossguide_leaves_bounded",
                  "lossguide_unbound_bit_identical"):
            if summary.get(k) is not True:
                print(f"{name}: {k}={summary.get(k)!r} (want true)")
                return False
        print(f"{name}: goss-ratio={goss_r} goss-auc-delta={goss_d} "
              f"efb-shrink={efb_s} u8-ratio={u8_r} i16-rmse={i16_r} "
              f"controls=bit-identical ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _munge_ab_ok(here: str, now: float):
    """Sanity-check the newest recent MUNGE_AB_*.jsonl (bench_kernel_sweep
    --munge-ab, the ISSUE-20 compiled-munging-plane A/B). Returns None
    when no recent artifact exists (no opinion), else True/False. Checks
    the acceptance pins: fused wall <= 0.5x eager for group-by AND join,
    sort no worse than ~1.1x, the 10-op expression chain's dispatch count
    cut >= 5x, and every parity pin green (joins/sort/chain bit-equal,
    group-by counts exact + float sums allclose)."""
    recent = []
    for p in glob.glob(os.path.join(here, "MUNGE_AB_*.jsonl")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        summary = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "munge_ab" in d:
                    summary = d["munge_ab"]
        if not summary:
            print(f"{name}: NO munge_ab summary line")
            return False
        gb_r = float(summary.get("groupby_wall_ratio_fused_over_eager",
                                 float("nan")))
        if not gb_r <= 0.5:
            print(f"{name}: group-by fused/eager wall {gb_r} > 0.5x")
            return False
        jn_r = float(summary.get("join_wall_ratio_fused_over_eager",
                                 float("nan")))
        if not jn_r <= 0.5:
            print(f"{name}: join fused/eager wall {jn_r} > 0.5x")
            return False
        so_r = float(summary.get("sort_wall_ratio_fused_over_eager",
                                 float("nan")))
        if not so_r <= 1.1:
            print(f"{name}: sort fused/eager wall {so_r} > 1.1x")
            return False
        disp_r = float(summary.get("chain_dispatch_ratio") or 0)
        if not disp_r >= 5.0:
            print(f"{name}: chain dispatch ratio {disp_r} < 5x")
            return False
        if summary.get("parity_ok") is not True:
            bad = [k for k in ("groupby_parity_ok", "join_bit_equal",
                               "sort_bit_equal", "chain_bit_equal")
                   if summary.get(k) is not True]
            print(f"{name}: parity pins failed: {bad}")
            return False
        print(f"{name}: groupby={gb_r}x join={jn_r}x sort={so_r}x "
              f"chain-dispatches=1/{disp_r} parity=ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _mesh2d_ab_ok(here: str, now: float):
    """Sanity-check the newest recent MESH2D_AB_*.jsonl (bench_kernel_sweep
    --mesh2d-ab, the 1-D vs 2-D pod-mesh A/B, ISSUE 14). Returns None when
    no recent artifact exists (no opinion), else True/False. Checks the
    acceptance pins: collective bytes recorded BY PHASE on every mesh shape
    (a zero phase means the 2-D tally broke), the winner gather shrank with
    the cols width, and 2x4 fused_tree_s held within 1.10x of the 1-D mesh
    — 'no worse' up to proxy noise: on the one-host CPU proxy the stage-1
    rows psum is pure emulation overhead with none of the ICI placement
    payoff, so a small regression is expected there and the real
    ICI-vs-DCN number is the queued v5e-16 pod bracket's."""
    recent = []
    for p in glob.glob(os.path.join(here, "MESH2D_AB_*.jsonl")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        summary = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if "mesh2d_ab" in d:
                    summary = d["mesh2d_ab"]
        if not summary:
            print(f"{name}: NO mesh2d_ab summary line")
            return False
        if not summary.get("phases_recorded_all_modes"):
            print(f"{name}: a mesh shape recorded ZERO bytes for a phase")
            return False
        ratio = float(summary.get("time_ratio_2x4_over_1d") or 0)
        if not 0 < ratio <= 1.10:
            print(f"{name}: 2x4 fused_tree_s ratio {ratio} outside (0, 1.10]")
            return False
        wg = float(summary.get("winner_gather_ratio_1d_over_2x4") or 0)
        if not wg >= 1.5:
            print(f"{name}: winner gather did not shrink with cols ({wg})")
            return False
        print(f"{name}: phases=ok 2x4-time-ratio={ratio} "
              f"winner-gather-ratio={wg} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _fleet_ok(here: str, now: float):
    """Sanity-check the newest recent FLEET_*.json (tools/load_test.py
    --fleet, the serving-plane oversubscription A/B). Returns None when no
    recent artifact exists (no opinion), else True/False. Checks the
    ISSUE-12 acceptance pins: resident model bytes stayed under
    H2O3_TPU_SERVE_HBM_BYTES at oversubscription, paging actually happened
    (evictions > 0), every model's scores were byte-stable across
    page-out/page-in AND across the resident control, and the oversub
    tier's sustained QPS held >= 0.5x the all-resident run."""
    recent = []
    for p in glob.glob(os.path.join(here, "FLEET_*.json")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        with open(path) as f:
            d = json.loads(f.readline())
        s = d.get("summary") or {}
        if not d.get("steps"):
            print(f"{name}: NO steps")
            return False
        if not s.get("peak_within_budget"):
            print(f"{name}: resident model bytes EXCEEDED the HBM budget "
                  f"(peak {s.get('oversub_hbm_peak_bytes')} > "
                  f"{s.get('hbm_budget_bytes')})")
            return False
        if not (s.get("oversub_evictions") or 0) > 0:
            print(f"{name}: oversubscription never paged (evictions=0)")
            return False
        if not (s.get("oversub_parity_stable")
                and s.get("parity_across_modes")):
            print(f"{name}: paging perturbed scores (parity_stable="
                  f"{s.get('oversub_parity_stable')}, across_modes="
                  f"{s.get('parity_across_modes')})")
            return False
        ratio = s.get("qps_ratio_vs_resident")
        if ratio is not None and ratio < 0.5:
            print(f"{name}: oversub sustained QPS ratio {ratio} < 0.5x "
                  "resident")
            return False
        print(f"{name}: peak-in-budget=ok evictions="
              f"{s.get('oversub_evictions')} parity=ok qps-ratio={ratio} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _elastic_drill_ok(here: str, now: float):
    """Sanity-check the newest recent ELASTIC_DRILL_*.json
    (tools/recovery_drill.py --elastic, the ISSUE-17 topology-chaos drill).
    Returns None when no recent artifact exists (no opinion), else
    True/False. Checks the elastic acceptance pins: every shape transition
    in the matrix completed with the 1e-6 final-metric parity, the resumes
    actually re-formed the cloud (generations ticked), and the
    recovery_seconds measurement is present."""
    recent = []
    for p in glob.glob(os.path.join(here, "ELASTIC_DRILL_*.json")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        with open(path) as f:
            d = json.load(f)  # indented JSON (same format as RECOVERY_DRILL)
        if not d.get("ok"):
            print(f"{name}: ok flag not set")
            return False
        results = d.get("results") or []
        if len(results) < 3:
            print(f"{name}: only {len(results)} transitions drilled "
                  "(want the full shape-change matrix)")
            return False
        algos = {r.get("algo") for r in results}
        if not {"gbm", "glm", "deeplearning"} <= algos:
            print(f"{name}: matrix missing algos (have {sorted(algos)})")
            return False
        for r in results:
            label = f"{r.get('algo')} {r.get('from')}->{r.get('to')}"
            if not (0 <= float(r.get("logloss_delta", 1)) <= 1e-6):
                print(f"{name}: {label} parity pin violated "
                      f"(logloss_delta={r.get('logloss_delta')})")
                return False
            if r.get("recovery_seconds") is None:
                print(f"{name}: {label} has no recovery_seconds")
                return False
        if not (d.get("generations_ticked") or 0) >= len(results):
            print(f"{name}: generations_ticked="
                  f"{d.get('generations_ticked')} < {len(results)} resumes "
                  "— the drill never actually re-formed")
            return False
        if d.get("recovery_seconds") is None:
            print(f"{name}: no headline recovery_seconds")
            return False
        print(f"{name}: {len(results)} transitions, parity<=1e-6, "
              f"generations={d.get('generations_ticked')} "
              f"recovery_seconds={d.get('recovery_seconds'):.2f} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _overload_drill_ok(here: str, now: float):
    """Sanity-check the newest recent OVERLOAD_DRILL_*.json
    (tools/overload_drill.py, the ISSUE-19 overload-survival drill).
    Returns None when no recent artifact exists (no opinion), else
    True/False. Checks the acceptance pins: the admission storm at 4x
    capacity landed some requests AND shed the rest with only 429/503 and
    an honest Retry-After >= 1 s while the server survived and the
    reservation ledger returned to zero (memory gate shed reason=memory);
    the induced OOM auto-degraded to a model within 1e-6 of the resident
    control with an incident naming the dispatch and NO generation tick;
    the induced hang tripped the watchdog past its budget, captured a
    hang incident, and the supervisor re-formed and resumed to the 1e-6
    pin."""
    recent = []
    for p in glob.glob(os.path.join(here, "OVERLOAD_DRILL_*.json")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        with open(path) as f:
            d = json.load(f)  # indented JSON, same format as the drills
        if not d.get("ok"):
            print(f"{name}: ok flag not set")
            return False
        r = d.get("results") or {}
        storm, oom, hang = r.get("storm"), r.get("oom"), r.get("hang")
        if not (storm and oom and hang):
            print(f"{name}: scenarios missing (have {sorted(r)})")
            return False
        if not (storm.get("ok", 0) >= 1 and storm.get("shed", 0) >= 1):
            print(f"{name}: storm did not both admit and shed "
                  f"(ok={storm.get('ok')} shed={storm.get('shed')})")
            return False
        if not set(storm.get("shed_statuses") or ()) <= {429, 503}:
            print(f"{name}: storm shed with non-backpressure statuses "
                  f"{storm.get('shed_statuses')}")
            return False
        if not float(storm.get("retry_after_min") or 0) >= 1:
            print(f"{name}: dishonest Retry-After "
                  f"({storm.get('retry_after_min')})")
            return False
        if not (storm.get("server_alive")
                and storm.get("reservations_after") == 0):
            print(f"{name}: storm killed the server or leaked reservations")
            return False
        if (storm.get("memory_shed") or {}).get("reason") != "memory":
            print(f"{name}: memory gate never shed reason=memory "
                  f"({storm.get('memory_shed')})")
            return False
        if not (0 <= float(oom.get("logloss_delta", 1)) <= 1e-6):
            print(f"{name}: oom degrade parity pin violated "
                  f"(logloss_delta={oom.get('logloss_delta')})")
            return False
        if oom.get("incident_trigger") != "oom" or not oom.get("incident"):
            print(f"{name}: oom incident missing/mistriggered")
            return False
        if oom.get("generation_ticked") != 0:
            print(f"{name}: oom degrade re-formed the cloud "
                  f"(generation_ticked={oom.get('generation_ticked')})")
            return False
        trips = hang.get("trips") or []
        if not trips or not all(
                float(t.get("budget_s") or 0) > 0
                and float(t.get("age_s") or 0) >= float(t["budget_s"])
                for t in trips):
            print(f"{name}: watchdog trips missing/under-budget ({trips})")
            return False
        if hang.get("incident_trigger") != "hang" or not hang.get("incident"):
            print(f"{name}: hang incident missing/mistriggered")
            return False
        if not (hang.get("generations_ticked") or 0) >= 1:
            print(f"{name}: hang never handed the job to the supervisor "
                  f"(generations_ticked={hang.get('generations_ticked')})")
            return False
        if not (0 <= float(hang.get("logloss_delta", 1)) <= 1e-6):
            print(f"{name}: hang resume parity pin violated "
                  f"(logloss_delta={hang.get('logloss_delta')})")
            return False
        print(f"{name}: storm ok={storm['ok']}/shed={storm['shed']} "
              f"oom-delta={oom['logloss_delta']:.1e} "
              f"hang-trips={len(trips)} "
              f"hang-delta={hang['logloss_delta']:.1e} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def _ledger_sane(led: dict) -> bool:
    """One per-job ledger's totals: finite non-negative numbers, counts
    non-negative ints. Shared by the TRACE gate and the BENCH jobs block."""
    try:
        for k in ("device_seconds", "queue_wait_seconds"):
            v = float(led.get(k, 0) or 0)
            if not (v >= 0 and v == v and v != float("inf")):
                return False
        for v in (led.get("dispatches") or {}).values():
            if not (isinstance(v, int) and v >= 0):
                return False
        for v in list((led.get("collective_bytes") or {}).values()) + [
                led.get("window_bytes", 0) or 0]:
            v = float(v)
            if not (v >= 0 and v == v and v != float("inf")):
                return False
    except (TypeError, ValueError):
        return False
    return True


def _trace_ok(here: str, now: float):
    """Sanity-check the newest recent TRACE_*.json (the run_tpu_backlog
    traced-headline-GBM capture, ISSUE 18). Returns None when no recent
    artifact exists (no opinion), else True/False. Checks the acceptance
    pins: the Perfetto export carries a span for EVERY site the job's
    ledger says it dispatched (a missing site means the trace plane lost a
    dispatch path), and the ledger totals are finite with device-seconds
    bounded by the measured wall-clock — attribution that exceeds the wall
    is double-counting, not measurement."""
    recent = []
    for p in glob.glob(os.path.join(here, "TRACE_*.json")):
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    if not recent:
        return None
    path = sorted(recent)[0][1]
    name = os.path.basename(path)
    try:
        with open(path) as f:
            d = json.load(f)
        led = d.get("ledger") or {}
        evs = (d.get("trace") or {}).get("traceEvents") or []
        if not evs:
            print(f"{name}: trace export has NO events")
            return False
        span_names = {e.get("name") for e in evs if e.get("ph") == "X"}
        missing = [site for site in (led.get("dispatches") or {})
                   if f"dispatch:{site}" not in span_names]
        if missing:
            print(f"{name}: ledger dispatched {missing} but the trace "
                  "has no spans for them")
            return False
        if not led.get("dispatches"):
            print(f"{name}: traced GBM job recorded ZERO dispatches")
            return False
        bad = [j for j, lj in (d.get("jobs") or {}).items()
               if not _ledger_sane(lj)]
        if bad:
            print(f"{name}: ledger totals INSANE for {bad}")
            return False
        wall = float(d.get("wall_s") or 0)
        ds = float(led.get("device_seconds") or 0)
        if not (wall > 0 and 0 <= ds <= wall):
            print(f"{name}: ledger device-seconds {ds} outside "
                  f"[0, wall={wall}]")
            return False
        print(f"{name}: spans-per-site=ok dispatches={led['dispatches']} "
              f"device_s={ds} wall_s={wall} ok")
        return True
    except OSError as e:
        print(f"{name}: unreadable ({e.strerror or e})")
        return False
    except Exception as e:  # torn/garbage JSON
        print(f"{name}: unparseable ({type(e).__name__})")
        return False


def main() -> int:
    import time

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    now = time.time()
    # serving-tier artifact gate: when a recent load-test artifact exists it
    # must be sane, or the window's serving A/B numbers are untrustworthy
    lt = _loadtest_ok(here, now)
    if lt is False:
        return 1
    # quantized-collective-lane gate (ISSUE 9): same contract — a recent
    # --quant-ab artifact must satisfy the acceptance pins or the window
    # stands
    qa = _quant_ab_ok(here, now)
    if qa is False:
        return 1
    # out-of-core streaming gate (ISSUE 11): a recent --oocore-ab artifact
    # must satisfy the fixed-footprint acceptance pins or the window stands
    oo = _oocore_ab_ok(here, now)
    if oo is False:
        return 1
    # fleet serving gate (ISSUE 12): a recent --fleet artifact must satisfy
    # the oversubscription acceptance pins or the window stands
    fl = _fleet_ok(here, now)
    if fl is False:
        return 1
    # 2-D pod-mesh gate (ISSUE 14): a recent --mesh2d-ab artifact must
    # satisfy the no-regression + per-phase-bytes pins or the window stands
    m2 = _mesh2d_ab_ok(here, now)
    if m2 is False:
        return 1
    # fallback-matrix closure gate (ISSUE 15): a recent --fallback-ab
    # artifact must satisfy the parity + dispatch + no-worse-wall pins
    fb = _fallback_ab_ok(here, now)
    if fb is False:
        return 1
    # tree-kernel wave-2 gate (ISSUE 16): a recent --wave2-ab artifact
    # must satisfy the sampling/bundling/quantization pins + bit-identical
    # knob-off controls or the window stands
    w2 = _wave2_ab_ok(here, now)
    if w2 is False:
        return 1
    # compiled-munging-plane gate (ISSUE 20): a recent --munge-ab artifact
    # must satisfy the wall-ratio + dispatch-cut + parity pins or the
    # window stands
    mu = _munge_ab_ok(here, now)
    if mu is False:
        return 1
    # elastic-recovery gate (ISSUE 17): a recent --elastic drill artifact
    # must satisfy the shape-change parity pins or the window stands
    el = _elastic_drill_ok(here, now)
    if el is False:
        return 1
    # job-scoped tracing gate (ISSUE 18): a recent traced-GBM capture must
    # carry a span per dispatched site and a wall-bounded ledger
    tr = _trace_ok(here, now)
    if tr is False:
        return 1
    # overload-survival gate (ISSUE 19): a recent overload drill must
    # satisfy the shed-honesty + OOM-degrade + hang-watchdog pins or the
    # window stands
    ov = _overload_drill_ok(here, now)
    if ov is False:
        return 1
    # ANY qualifying artifact from this window counts: the backlog writes
    # headline-only A/B controls (_adapt/_nbins127/_matmul) AFTER the full
    # run, so "the newest file" is usually a control and judging only it
    # would loop the watcher forever on a fully successful window
    recent = []
    try:
        candidates = glob.glob(os.path.join(here, "BENCH_builder_*.json"))
    except OSError as e:  # unreadable repo dir: clean message, not traceback
        print(f"cannot list bench artifacts under {here}: {e}")
        return 1
    for p in candidates:
        age = _stamp_age_s(p, now)
        if age is not None and 0 <= age < RECENT_S:
            recent.append((age, p))
    recent = [p for _, p in sorted(recent)]
    if not recent:
        print("no recent BENCH_builder artifacts")
        return 1
    for path in recent:
        headline_ok = phases_ok = registry_ok = False
        psum_note = ""
        note = ""
        try:
            with open(path) as f:
                d = json.loads(f.readline())
            if isinstance(d, dict):
                headline_ok = float(d.get("value") or 0) > 0
                phases_ok = any(
                    isinstance(d.get(p), dict) for p in POST_HEADLINE
                )
                # the registry-snapshot block: bench counters sourced from
                # the live /3/Metrics registry — an artifact without it was
                # produced by a pre-observability bench and cannot be
                # cross-checked against the endpoint
                reg = d.get("metrics_registry")
                registry_ok = isinstance(reg, dict) and len(reg) > 0
                # psum_bytes_per_tree (split-pipeline traffic, ISSUE 5) is
                # OPTIONAL — older artifacts predate it — but when present
                # it must be a sane number: a negative/NaN/garbage value
                # means the byte tally broke and the A/B replay would be
                # comparing noise, so the artifact does not count
                if "psum_bytes_per_tree" in d:
                    try:
                        v = float(d["psum_bytes_per_tree"])
                        sane = v >= 0 and v == v and v != float("inf")
                    except (TypeError, ValueError):
                        sane = False
                    psum_note = (
                        f" psum-bytes/tree={d['psum_bytes_per_tree']}"
                        if sane else " psum-bytes/tree=INSANE"
                    )
                    if not sane:
                        headline_ok = False
                # hist_hbm_bytes_per_tree (fused split pipeline, ISSUE 6) is
                # OPTIONAL like psum above, but when present it must be a
                # sane non-negative finite number or the fused-vs-unfused
                # A/B would be comparing noise
                if "hist_hbm_bytes_per_tree" in d:
                    try:
                        v = float(d["hist_hbm_bytes_per_tree"])
                        sane = v >= 0 and v == v and v != float("inf")
                    except (TypeError, ValueError):
                        sane = False
                    psum_note += (
                        f" hist-hbm-bytes/tree={d['hist_hbm_bytes_per_tree']}"
                        if sane else " hist-hbm-bytes/tree=INSANE"
                    )
                    if not sane:
                        headline_ok = False
                # tracked GLM/DL/AutoML summary keys (ISSUE 8) are OPTIONAL
                # — artifacts from partial runs lack them — but when
                # present they must be finite positives or the per-round
                # trend they exist to track is garbage
                for k in ("glm_iters_per_s", "dl_epoch_s",
                          "automl_total_s"):
                    if k not in d:
                        continue
                    try:
                        v = float(d[k])
                        sane = v > 0 and v == v and v != float("inf")
                    except (TypeError, ValueError):
                        sane = False
                    psum_note += (
                        f" {k}={d[k]}" if sane else f" {k}=INSANE"
                    )
                    if not sane:
                        headline_ok = False
                # devmem attribution block (ISSUE 13) is OPTIONAL — older
                # artifacts predate the ledger — but when present every
                # per-owner byte count must be a finite non-negative int
                # and each peak must be >= its live value, or the HBM
                # attribution the TPU-window A/Bs rely on is garbage
                if "devmem" in d:
                    dv = d["devmem"]
                    sane = isinstance(dv, dict)
                    if sane:
                        own = dv.get("owned_bytes", {})
                        pk = dv.get("peak_owned_bytes", {})
                        try:
                            for o, v in {**own, **pk}.items():
                                v = float(v)
                                if not (v >= 0 and v == v
                                        and v != float("inf")):
                                    sane = False
                            for o, v in own.items():
                                if float(pk.get(o, v)) < float(v):
                                    sane = False
                        except (TypeError, ValueError):
                            sane = False
                    psum_note += (" devmem=ok" if sane
                                  else " devmem=INSANE")
                    if not sane:
                        headline_ok = False
                # per-job ledger block (ISSUE 18) is OPTIONAL — older
                # artifacts predate jobacct — but when present every
                # job's totals must be finite non-negative numbers or
                # the device-time attribution is garbage
                if "jobs" in d:
                    jb = d["jobs"]
                    sane = isinstance(jb, dict) and all(
                        isinstance(lj, dict) and _ledger_sane(lj)
                        for lj in jb.values())
                    psum_note += (" jobs=ok" if sane else " jobs=INSANE")
                    if not sane:
                        headline_ok = False
        except OSError as e:  # vanished/unreadable between glob and open
            note = f" (unreadable: {e.strerror or e})"
        except Exception as e:  # torn/empty/garbage JSON is a MISSING, not a crash
            note = f" (unparseable: {type(e).__name__})"
        print(
            f"{os.path.basename(path)}: "
            f"headline={'ok' if headline_ok else 'MISSING'}"
            f" post-headline-phases={'ok' if phases_ok else 'MISSING'}"
            f" registry-snapshot={'ok' if registry_ok else 'MISSING'}"
            f"{psum_note}{note}"
        )
        if headline_ok and phases_ok and registry_ok:
            return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
