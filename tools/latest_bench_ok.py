"""Exit 0 iff the newest BENCH_builder_*.json captured a real headline value
AND at least one post-headline phase.

Used by tunnel_watch.sh as the 'did the backlog actually measure anything'
signal — the backlog script's own exit code cannot carry it (tee pipelines,
error-JSON-by-design). Requiring a post-headline phase matters: round 4's
failure mode was exactly 'headline measured, every scale phase dead in a
RESOURCE_EXHAUSTED cascade', and standing down on a headline alone would
forfeit the later windows this round exists to use.
"""

import glob
import json
import os
import sys

# keep in sync with bench.py _PHASES (minus headline)
POST_HEADLINE = (
    "scale_10m", "cat_1m", "join_10m", "glm_1m", "hash_1m", "dl_100k",
    "automl_50k",
)

RECENT_S = 6 * 3600  # this window's artifacts only — stale full runs from
                     # an earlier round must not stand the watcher down


def main() -> int:
    import time

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    now = time.time()
    # ANY qualifying artifact from this window counts: the backlog writes
    # headline-only A/B controls (_adapt/_nbins127/_matmul) AFTER the full
    # run, so "the newest file" is usually a control and judging only it
    # would loop the watcher forever on a fully successful window
    recent = [
        p for p in glob.glob(os.path.join(here, "BENCH_builder_*.json"))
        if now - os.path.getmtime(p) < RECENT_S
    ]
    if not recent:
        print("no recent BENCH_builder artifacts")
        return 1
    for path in sorted(recent, key=os.path.getmtime, reverse=True):
        headline_ok = phases_ok = False
        try:
            with open(path) as f:
                d = json.loads(f.readline())
            if isinstance(d, dict):
                headline_ok = float(d.get("value") or 0) > 0
                phases_ok = any(
                    isinstance(d.get(p), dict) for p in POST_HEADLINE
                )
        except Exception:
            pass
        print(
            f"{os.path.basename(path)}: "
            f"headline={'ok' if headline_ok else 'MISSING'}"
            f" post-headline-phases={'ok' if phases_ok else 'MISSING'}"
        )
        if headline_ok and phases_ok:
            return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
