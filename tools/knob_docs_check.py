#!/usr/bin/env python
"""Knob/docs drift gate: every ``H2O3_TPU_*`` knob registered in
``h2o3_tpu/config.py`` must be mentioned somewhere under ``docs/`` — an
operator reading the runbooks has to be able to find every switch that
exists. Exits 1 listing the undocumented knobs; wired into tier-1 through
``tests/test_bench_infra.py`` so a new knob cannot merge undocumented.

Usage::

    python tools/knob_docs_check.py [--extra KNOB ...]

``--extra`` injects fabricated knob names (the self-test hook: the wiring
test proves the gate actually fails on an undocumented knob).
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--extra", action="append", default=[],
                    help="pretend this knob is registered too (self-test)")
    args = ap.parse_args(argv)

    sys.path.insert(0, ROOT)
    from h2o3_tpu import config

    docs = ""
    for path in sorted(glob.glob(os.path.join(ROOT, "docs", "*.md"))):
        with open(path, encoding="utf-8") as f:
            docs += f.read()
    if not docs:
        print("knob_docs_check: no docs/*.md found")
        return 1

    knobs = sorted(set(config._KNOBS) | set(args.extra))
    missing = [k for k in knobs if k not in docs]
    if missing:
        print("knob_docs_check: knobs registered in config.py but absent "
              "from docs/*.md:")
        for k in missing:
            print(f"  {k}")
        print("document them (the full table lives in docs/MIGRATION.md).")
        return 1
    print(f"knob_docs_check: all {len(knobs)} knobs documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
