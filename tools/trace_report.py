#!/usr/bin/env python
"""Render flight-recorder spans as Chrome/Perfetto trace JSON (ISSUE 18).

Three sources, same output shape as ``GET /3/FlightRecorder?format=trace``:

- an **incident bundle** (``incident_*.json``): renders the frozen ring's
  ``events`` list — the postmortem view of what the dead generation was
  dispatching, one lane per trace id, with the bundle's per-job ledgers
  summarized alongside;
- a **live server** (``--url http://host:54321``): fetches the rendered
  trace straight off the REST plane (registry spans included);
- the **local ring** of this process (no args) — mostly for smoke tests.

The trace JSON loads in ``chrome://tracing`` or https://ui.perfetto.dev.
``profiler_start``/``profiler_end`` ring events render the xplane capture
window on lane 0, so lining a trace up against a
``telemetry.profiler`` capture is a timestamp overlap, not guesswork.

Usage::

    python tools/trace_report.py /tmp/h2o3_incidents/incident_*.json
    python tools/trace_report.py --url http://localhost:54321 --trace job-3
    python tools/trace_report.py bundle.json --out trace.json --summary
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _fetch_live(url: str, trace: str | None, n: int | None) -> dict:
    q = [f"n={n}" if n else "n=0", "format=trace"]
    if trace:
        q.append(f"trace={trace}")
    with urllib.request.urlopen(
            url.rstrip("/") + "/3/FlightRecorder?" + "&".join(q),
            timeout=30) as r:
        return json.loads(r.read())


def _from_bundle(path: str, trace: str | None) -> tuple[dict, dict]:
    """(trace_json, bundle) from an incident bundle file."""
    from h2o3_tpu.utils import flightrec

    with open(path) as f:
        bundle = json.load(f)
    evs = bundle.get("events") or []
    return flightrec.render_trace(evs, trace=trace), bundle


def summarize(tj: dict, jobs: dict | None = None) -> str:
    """Human-readable digest of a trace JSON: per-lane span totals (who
    spent how long where), then any per-job ledgers riding along."""
    lanes: dict[int, str] = {}
    totals: dict[tuple[int, str], tuple[int, float]] = {}
    for e in tj.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            lanes[e["tid"]] = e["args"]["name"]
        elif e.get("ph") == "X":
            k = (e["tid"], e["name"])
            n, tot = totals.get(k, (0, 0.0))
            totals[k] = (n + 1, tot + float(e.get("dur", 0.0)) / 1e3)
    lines = []
    for (tid, name), (n, tot_ms) in sorted(
            totals.items(), key=lambda kv: (kv[0][0], -kv[1][1])):
        lines.append(f"  {lanes.get(tid, f'tid {tid}'):<28} "
                     f"{name:<28} n={n:<5} total={tot_ms:9.3f}ms")
    for job, led in sorted((jobs or {}).items()):
        lines.append(f"  ledger {job}: "
                     f"device={led.get('device_seconds')}s "
                     f"dispatches={led.get('dispatches')} "
                     f"window_bytes={led.get('window_bytes')} "
                     f"queue_wait={led.get('queue_wait_seconds')}s")
    return "\n".join(lines) if lines else "  (no spans)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", nargs="?",
                    help="incident bundle JSON to render (omit for "
                         "--url or the local ring)")
    ap.add_argument("--url", help="fetch the trace from a live server "
                                  "instead of a bundle file")
    ap.add_argument("--trace", help="keep only this trace id's lane")
    ap.add_argument("--n", type=int, default=None,
                    help="newest N ring events (default: all)")
    ap.add_argument("--out", help="write trace JSON here "
                                  "(default: stdout)")
    ap.add_argument("--summary", action="store_true",
                    help="print a per-lane span digest to stderr")
    args = ap.parse_args(argv)

    jobs = None
    if args.url:
        tj = _fetch_live(args.url, args.trace, args.n)
    elif args.bundle:
        tj, bundle = _from_bundle(args.bundle, args.trace)
        jobs = bundle.get("jobs")
    else:
        from h2o3_tpu.utils import flightrec

        tj = flightrec.trace_export(trace=args.trace, n=args.n)

    line = json.dumps(tj)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"trace written to {args.out} "
              f"({len(tj.get('traceEvents', []))} events, traces: "
              f"{tj.get('otherData', {}).get('traces')})", file=sys.stderr)
    else:
        print(line)
    if args.summary:
        print(summarize(tj, jobs), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
