"""h2o3_tpu — a TPU-native distributed ML platform with the capabilities of H2O-3.

This is a from-scratch JAX/XLA/Pallas rebuild of the H2O-3 architecture
(reference fork: chatebhagwat/h2o-3, upstream h2oai/h2o-3), NOT a port:

- H2O's distributed compressed columnar ``water.fvec.Frame`` [UNVERIFIED
  upstream path, see SURVEY.md §0] becomes a row-sharded ``jax.Array`` frame
  living in TPU HBM (:mod:`h2o3_tpu.frame`).
- H2O's ``water.MRTask`` map-reduce fabric becomes ``shard_map`` + XLA
  collectives over the ICI mesh (:mod:`h2o3_tpu.parallel`).
- The algorithm suite (GLM IRLS Gram, GBM/DRF histogram trees, MLP, KMeans,
  PCA, ...) compiles to XLA; the histogram inner loop runs as a Pallas TPU
  kernel (:mod:`h2o3_tpu.ops.hist_pallas` — VMEM one-hot tiles contracted on
  the MXU), with scatter-add on CPU meshes.
- The DKV (``water.DKV``) becomes a host-side object registry
  (:mod:`h2o3_tpu.cluster`), the REST API (``water.api.RequestServer``) a
  stdlib HTTP server (:mod:`h2o3_tpu.api`), and the Python client surface
  (``h2o.init / h2o.import_file / h2o.estimators``) is mirrored at top level.

The package directory is ``h2o3_tpu`` (the project name "h2o-3_tpu" is not a
valid Python identifier).
"""

__version__ = "0.1.0"

from h2o3_tpu.cluster.cloud import init, cluster_info, shutdown
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame import ops  # attaches Rapids-successor operators to Frame/Vec
from h2o3_tpu.frame.ops import (
    group_by,
    merge,
    quantile,
    table,
    unique,
    cut,
    impute,
    ifelse,
    cor,
    interaction,
)
from h2o3_tpu.frame.parse import import_file, upload_file, parse_setup
from h2o3_tpu.models.metrics import make_metrics
from h2o3_tpu.cluster.registry import get_frame, get_model, ls, remove, remove_all


def profiler(logdir: str):
    """jax.profiler.trace context manager (the /3/Profiler successor)."""
    from h2o3_tpu.utils.telemetry import profiler as _p

    return _p(logdir)


def export_file(frame, path: str, force: bool = False, format: str | None = None) -> str:
    """Frame → CSV/Parquet on disk (h2o.export_file successor)."""
    from h2o3_tpu.persist import export_file as _ef

    return _ef(frame, path, force=force, format=format)


def save_model(model, path: str, force: bool = True) -> str:
    """Binary model save (h2o.save_model successor)."""
    from h2o3_tpu.persist import save_model as _sm

    return _sm(model, path, force=force)


def load_model(path: str):
    """Binary model load (h2o.load_model successor)."""
    from h2o3_tpu.persist import load_model as _lm

    return _lm(path)


def import_mojo(path: str, model_id: str | None = None):
    """Re-import a portable artifact as a LIVE server-side model (the
    hex.generic successor); it lands in the DKV and predicts like any model.
    For cluster-free offline scoring use :class:`h2o3_tpu.genmodel.MojoModel`."""
    from h2o3_tpu.models.generic import import_mojo_model

    return import_mojo_model(path, model_id)


def start_server(ip: str = "127.0.0.1", port: int | None = None):
    """Start the REST server (water.api.RequestServer successor).

    Default port comes from the H2O3_TPU_PORT knob (config.py)."""
    from h2o3_tpu.api.server import start_server as _ss

    return _ss(ip, port)


def connect(url: str | None = None, **kw):
    """Connect to a remote coordinator over REST (h2o.connect successor).

    Default URL tracks the same H2O3_TPU_PORT knob start_server uses."""
    from h2o3_tpu.client import connect as _c

    return _c(url, **kw)

__all__ = [
    "init",
    "cluster_info",
    "shutdown",
    "Frame",
    "import_file",
    "upload_file",
    "parse_setup",
    "get_frame",
    "get_model",
    "ls",
    "remove",
    "remove_all",
    "start_server",
    "connect",
    "save_model",
    "export_file",
    "profiler",
    "load_model",
    "import_mojo",
    "interaction",
    "make_metrics",
]
