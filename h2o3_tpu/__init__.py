"""h2o3_tpu — a TPU-native distributed ML platform with the capabilities of H2O-3.

This is a from-scratch JAX/XLA/Pallas rebuild of the H2O-3 architecture
(reference fork: chatebhagwat/h2o-3, upstream h2oai/h2o-3), NOT a port:

- H2O's distributed compressed columnar ``water.fvec.Frame`` [UNVERIFIED
  upstream path, see SURVEY.md §0] becomes a row-sharded ``jax.Array`` frame
  living in TPU HBM (:mod:`h2o3_tpu.frame`).
- H2O's ``water.MRTask`` map-reduce fabric becomes ``shard_map`` + XLA
  collectives over the ICI mesh (:mod:`h2o3_tpu.parallel`).
- The algorithm suite (GLM IRLS Gram, GBM/DRF histogram trees, MLP, KMeans,
  PCA, ...) compiles to XLA; the histogram inner loop has a Pallas kernel
  (:mod:`h2o3_tpu.ops`).
- The DKV (``water.DKV``) becomes a host-side object registry
  (:mod:`h2o3_tpu.cluster`), the REST API (``water.api.RequestServer``) a
  stdlib HTTP server (:mod:`h2o3_tpu.api`), and the Python client surface
  (``h2o.init / h2o.import_file / h2o.estimators``) is mirrored at top level.

The package directory is ``h2o3_tpu`` (the project name "h2o-3_tpu" is not a
valid Python identifier).
"""

__version__ = "0.1.0"

from h2o3_tpu.cluster.cloud import init, cluster_info, shutdown
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame import ops  # attaches Rapids-successor operators to Frame/Vec
from h2o3_tpu.frame.ops import (
    group_by,
    merge,
    quantile,
    table,
    unique,
    cut,
    impute,
    ifelse,
    cor,
)
from h2o3_tpu.frame.parse import import_file, upload_file, parse_setup
from h2o3_tpu.cluster.registry import get_frame, get_model, ls, remove, remove_all

__all__ = [
    "init",
    "cluster_info",
    "shutdown",
    "Frame",
    "import_file",
    "upload_file",
    "parse_setup",
    "get_frame",
    "get_model",
    "ls",
    "remove",
    "remove_all",
]
