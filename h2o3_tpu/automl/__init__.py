"""AutoML orchestration — successor of ``ai.h2o.automl`` (h2o-automl)
[UNVERIFIED upstream paths, SURVEY.md §2.3, §3.5]."""

from h2o3_tpu.automl.automl import AutoML, Leaderboard

__all__ = ["AutoML", "Leaderboard"]
