"""AutoML orchestration — successor of ``ai.h2o.automl`` (h2o-automl)
[UNVERIFIED upstream paths, SURVEY.md §2.3, §3.5]."""

from h2o3_tpu.automl.automl import AutoML, Leaderboard


def get_leaderboard(aml: AutoML, extra_columns=()):
    """Upstream ``h2o.automl.get_leaderboard`` parity: leaderboard rows with
    optional extra columns ("training_time_ms" or "ALL")."""
    lb = aml.leaderboard
    return lb.as_table(extra_columns=extra_columns) if lb else []


__all__ = ["AutoML", "Leaderboard", "get_leaderboard"]
