"""AutoML — successor of ``ai.h2o.automl.AutoML`` / ``Leaderboard`` /
``modeling/*Steps`` [UNVERIFIED upstream paths, SURVEY.md §2.3, §3.5].

H2O AutoML plans a budgeted sequence of modeling steps — preset XGBoosts, preset GBMs, a GBM
grid, GLM, DRF + XRT (extremely randomized trees), DeepLearning grids, then
two Stacked Ensembles ("BestOfFamily" and "All") — every model cross-validated
so the ensembles can stack the holdout predictions, ranked on a leaderboard
by a task-appropriate metric, with an events log of what ran when.

The step tables below mirror H2O's default model parameter presets
(``modeling/GBMStepsProvider`` etc. [UNVERIFIED]) at reduced counts tuned for
chip-sized budgets; the orchestration itself is pure host-side Python over
the same ModelBuilder/Grid/SE jobs a user would drive by hand — the TPU never
idles on orchestration, which is exactly how H2O keeps its cluster busy from
a single driver node.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.model_base import Model, stopping_metric_direction
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log

_AUTOML_STEPS = _mx.counter(
    "automl_steps_total", "AutoML plan steps executed, by kind")
_AUTOML_STEP_SECONDS = _mx.histogram(
    "automl_step_seconds", "AutoML plan step wall time, by kind")


@dataclass
class AutoMLSpec:
    max_models: int = 0                # 0 = unbounded (use max_runtime_secs)
    max_runtime_secs: float = 3600.0
    max_runtime_secs_per_model: float = 0.0
    nfolds: int = 5
    seed: int = -1
    stopping_metric: str = "AUTO"
    stopping_rounds: int = 3
    stopping_tolerance: float = 1e-3
    sort_metric: str = "AUTO"
    include_algos: Sequence[str] | None = None
    exclude_algos: Sequence[str] | None = None
    balance_classes: bool = False
    keep_cross_validation_predictions: bool = True
    project_name: str = ""
    # ["target_encoding"] enables TE preprocessing of categorical features
    # (ai.h2o.automl preprocessing=["target_encoding"] analog)
    preprocessing: Sequence[str] | None = None
    # > 0 enables the exploitation phase (h2o's exploitation_ratio): the
    # incumbent best GBM is refined with annealed learn-rate + more trees,
    # and the refinement build is capped at ratio * max_runtime_secs
    exploitation_ratio: float = 0.0
    # crash durability (docs/RECOVERY.md): every finished model/grid step is
    # saved here and recorded in an AutoML manifest keyed by project_name, so
    # a killed run restarted with the SAME spec+data recovers the finished
    # steps from disk instead of rebuilding them. Grid steps additionally
    # recover per-model through the grid manifest in the same directory.
    export_checkpoints_dir: str | None = None


class Leaderboard:
    """Ranked model table — successor of ``ai.h2o.automl.Leaderboard``.

    When a ``leaderboard_frame`` is supplied, models are ranked on metrics
    scored against it (H2O semantics); otherwise on CV > validation >
    training metrics, in that order of preference."""

    def __init__(self, sort_metric: str, larger_is_better: bool, leaderboard_frame=None):
        self.sort_metric = sort_metric
        self.larger = larger_is_better
        self.leaderboard_frame = leaderboard_frame
        self.models: list[Model] = []
        self._lb_metrics: dict[str, Any] = {}  # model key -> metrics on lb frame

    def add(self, *models: Model) -> None:
        for m in models:
            if m is not None:
                self.models.append(m)
        self.models.sort(key=self._key)

    def _key(self, m: Model):
        v = self._metric_of(m)
        return (np.isnan(v), -v if self.larger else v)

    def _metrics_for(self, m: Model):
        if self.leaderboard_frame is not None:
            if m.key not in self._lb_metrics:
                self._lb_metrics[m.key] = m._score_metrics(self.leaderboard_frame)
            return self._lb_metrics[m.key]
        return m.cross_validation_metrics or m.validation_metrics or m.training_metrics

    def _metric_of(self, m: Model) -> float:
        mm = self._metrics_for(m)
        return mm.value(self.sort_metric) if mm else float("nan")

    @property
    def leader(self) -> Model | None:
        return self.models[0] if self.models else None

    def as_table(self, extra_columns=()) -> list[dict]:
        """Leaderboard rows; ``extra_columns`` accepts upstream's
        ``get_leaderboard(aml, extra_columns=...)`` names
        ("training_time_ms", "ALL")."""
        if extra_columns == "ALL" or "ALL" in tuple(extra_columns or ()):
            extra_columns = ("training_time_ms",)
        rows = []
        for m in self.models:
            mm = self._metrics_for(m)
            row = {"model_id": m.key, "algo": m.algo, self.sort_metric: self._metric_of(m)}
            if mm is not None:
                for extra in ("auc", "logloss", "rmse", "mse", "mean_per_class_error", "mean_residual_deviance"):
                    if extra != self.sort_metric and not np.isnan(mm.value(extra)):
                        row[extra] = mm.value(extra)
            if "training_time_ms" in (extra_columns or ()):
                row["training_time_ms"] = int(getattr(m, "run_time_ms", 0) or 0)
            rows.append(row)
        return rows

    def __repr__(self):
        lines = [f"Leaderboard (sorted by {self.sort_metric}):"]
        for r in self.as_table():
            lines.append("  " + "  ".join(f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}" for k, v in r.items()))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# AutoML checkpoint manifest (extends the grid-manifest pattern in
# models/grid.py to whole modeling steps; written atomically through persist)


def _automl_id(spec: "AutoMLSpec") -> str:
    return spec.project_name or "automl"


def _automl_fingerprint(spec: "AutoMLSpec", x, y, train) -> str:
    """Invalidates recovery when anything but the checkpoint dir changed.
    NOTE: the training frame enters by KEY — stable recovery across process
    restarts needs a stable frame key (``destination_frame=``)."""
    import dataclasses
    import hashlib
    import json

    sd = {f.name: getattr(spec, f.name) for f in dataclasses.fields(spec)
          if f.name != "export_checkpoints_dir"}
    payload = json.dumps(
        {"spec": sd, "x": list(x) if x else None, "y": y,
         "frame": getattr(train, "key", str(train))},
        sort_keys=True, default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _automl_manifest_path(ckdir: str, aml_id: str) -> str:
    import os

    return os.path.join(ckdir, f"{aml_id}.automl.json")


def _read_automl_manifest(
    ckdir: str, aml_id: str, fingerprint: str
) -> tuple[dict[str, list[str]], dict[str, int]]:
    """Returns (finished step -> model keys, step -> recorded build
    attempts). Attempt counts survive auto-resumes so the poison-step guard
    (``H2O3_TPU_AUTOML_STEP_RETRIES``) can skip a step that crashes every
    resume at the same place."""
    import json
    import os

    path = _automl_manifest_path(ckdir, aml_id)
    if not os.path.exists(path):
        return {}, {}
    with open(path) as f:
        payload = json.load(f)
    if payload.get("fingerprint") not in (None, fingerprint):
        Log.warn(
            f"AutoML {aml_id}: checkpoint dir was built with a different "
            "spec / data — ignoring it and rebuilding"
        )
        return {}, {}
    return (
        {k: list(v) for k, v in payload.get("steps", {}).items()},
        {k: int(v) for k, v in payload.get("attempts", {}).items()},
    )


def _write_automl_manifest(ckdir: str, aml_id: str, fingerprint: str,
                           steps: dict[str, list[str]],
                           attempts: dict[str, int] | None = None) -> None:
    import json

    from h2o3_tpu.persist import write_bytes

    write_bytes(
        json.dumps({"automl_id": aml_id, "fingerprint": fingerprint,
                    "steps": steps, "attempts": attempts or {}}).encode(),
        _automl_manifest_path(ckdir, aml_id),
    )


@dataclass
class _Step:
    name: str
    kind: str          # "model" | "grid" | "ensemble"
    algo: str
    params: dict = field(default_factory=dict)
    hyper: dict = field(default_factory=dict)
    weight: int = 10   # relative budget share (H2O step weights)


def _default_plan() -> list[_Step]:
    """The default modeling plan, mirroring H2O's step order:
    preset XGBoosts → preset GBMs → GLM → DRF → XRT → GBM grid → DL grid →
    ensembles (upstream runs its XGBoost defaults FIRST —
    ``modeling/XGBoostStepsProvider`` [UNVERIFIED])."""
    return [
        _Step("def_xgb_1", "model", "xgboost", dict(ntrees=50, max_depth=10, min_child_weight=5, sample_rate=0.6, col_sample_rate_per_tree=0.8, reg_lambda=0.8, reg_alpha=0.0)),
        _Step("def_xgb_2", "model", "xgboost", dict(ntrees=50, max_depth=20, min_child_weight=10, sample_rate=0.6, col_sample_rate_per_tree=0.8, reg_lambda=0.8, reg_alpha=0.0)),
        _Step("def_xgb_3", "model", "xgboost", dict(ntrees=50, max_depth=5, min_child_weight=3, sample_rate=0.8, col_sample_rate_per_tree=0.8, reg_lambda=1.0, reg_alpha=0.0)),
        _Step("def_gbm_1", "model", "gbm", dict(ntrees=50, max_depth=6, learn_rate=0.1, sample_rate=0.8, col_sample_rate=0.8)),
        _Step("def_gbm_2", "model", "gbm", dict(ntrees=50, max_depth=3, learn_rate=0.1, sample_rate=0.9, col_sample_rate=1.0)),
        _Step("def_gbm_3", "model", "gbm", dict(ntrees=50, max_depth=9, learn_rate=0.1, sample_rate=0.7, col_sample_rate=0.6)),
        _Step("def_glm", "model", "glm", dict()),
        _Step("def_drf", "model", "drf", dict(ntrees=50)),
        _Step("def_xrt", "model", "xrt", dict(ntrees=50)),
        _Step(
            "grid_gbm", "grid", "gbm",
            dict(ntrees=50),
            hyper={
                "max_depth": [3, 5, 7],
                "learn_rate": [0.05, 0.1, 0.3],
                "sample_rate": [0.6, 0.8, 1.0],
            },
            weight=60,
        ),
        _Step(
            "grid_dl", "grid", "deeplearning",
            dict(epochs=20),
            hyper={
                "hidden": [[32, 32], [64], [128, 64]],
                "input_dropout_ratio": [0.0, 0.1],
            },
            weight=30,
        ),
        _Step("exploit_gbm_lr_annealing", "exploit", "gbm", weight=10),
        _Step("se_best_of_family", "ensemble", "stackedensemble", dict(flavor="best_of_family")),
        _Step("se_all", "ensemble", "stackedensemble", dict(flavor="all")),
    ]


class AutoML:
    """``H2OAutoML`` successor.

    >>> aml = AutoML(max_models=8, seed=1)
    >>> aml.train(y="label", training_frame=fr)
    >>> aml.leaderboard.leader
    """

    def __init__(self, **kwargs):
        self.spec = AutoMLSpec(**kwargs)
        self.key = DKV.make_key("automl")
        self.leaderboard: Leaderboard | None = None
        self.event_log: list[dict] = []
        self.job: Job | None = None
        self._t0 = 0.0
        DKV.put(self.key, self)

    # -- public ----------------------------------------------------------
    def train(self, x=None, y=None, training_frame=None, validation_frame=None,
              leaderboard_frame=None) -> Model | None:
        self.job = Job(
            lambda j: self._drive(j, x, y, training_frame, validation_frame, leaderboard_frame),
            f"AutoML {self.spec.project_name or self.key}",
        )
        self.job.run_sync()
        return self.leader

    @property
    def leader(self) -> Model | None:
        return self.leaderboard.leader if self.leaderboard else None

    # -- internals -------------------------------------------------------
    def _log(self, stage: str, message: str) -> None:
        self.event_log.append({"ts": time.time(), "stage": stage, "message": message})
        Log.info(f"AutoML[{self.key}] {stage}: {message}")

    def _remaining(self) -> float:
        if not self.spec.max_runtime_secs:
            return float("inf")
        return self.spec.max_runtime_secs - (time.time() - self._t0)

    def _algo_allowed(self, algo: str) -> bool:
        inc, exc = self.spec.include_algos, self.spec.exclude_algos
        canon = {"gbm": "GBM", "xgboost": "XGBoost", "glm": "GLM", "drf": "DRF",
                 "xrt": "XRT", "deeplearning": "DeepLearning",
                 "stackedensemble": "StackedEnsemble"}[algo]
        if inc is not None:
            return canon in inc
        if exc is not None:
            return canon not in exc
        return True

    def _builder_cls(self, algo: str):
        from h2o3_tpu import models as M

        return {"gbm": M.GBM, "xgboost": M.XGBoost, "glm": M.GLM, "drf": M.DRF,
                "xrt": M.XRT, "deeplearning": M.DeepLearning}[algo]

    def _builder(self, algo: str, params: dict):
        return self._builder_cls(algo)(**params)

    def _exploit_gbm(self, family_best, x, y, train, validation_frame):
        """Exploitation: retrain the incumbent best GBM with halved
        learn_rate and doubled trees (upstream's lr_annealing refinement)."""
        best = family_best.get("gbm")
        if best is None:
            return None
        s = self.spec
        p = best.params
        kw = {
            **self._common(),
            "ntrees": max(p.ntrees * 2, p.ntrees + 50),
            "max_depth": p.max_depth,
            "learn_rate": max(p.learn_rate * 0.5, 1e-3),
            "sample_rate": p.sample_rate,
            "col_sample_rate": p.col_sample_rate,
        }
        # the exploitation budget IS the ratio share of the total budget,
        # additionally capped by whatever remains of the run; with no total
        # budget the per-model cap from _common() stays in force
        if s.max_runtime_secs:
            kw["max_runtime_secs"] = min(
                s.max_runtime_secs * s.exploitation_ratio,
                max(self._remaining(), 1.0),
            )
        m = self._builder("gbm", kw).train(
            x=x, y=y, training_frame=train, validation_frame=validation_frame
        )
        if self._te is not None:
            m.preprocessors.append(self._te)
        return m

    def _common(self) -> dict:
        # seed passes through verbatim: seed<=0 keeps each builder's own
        # "unseeded = random" contract, seed>0 makes the whole run reproducible
        s = self.spec
        out = dict(
            nfolds=s.nfolds,
            keep_cross_validation_predictions=True,
            seed=s.seed,
        )
        if s.max_runtime_secs_per_model:
            out["max_runtime_secs"] = s.max_runtime_secs_per_model
        if s.max_runtime_secs:
            # one model must never blow the WHOLE AutoML budget (upstream
            # allocates each step a share of the remaining time; observed
            # here: a depth-20 preset overshooting a 600 s budget to 1127 s,
            # leaving a 2-model leaderboard). Builders honor max_runtime as
            # a soft deadline and keep the partial model.
            rem = max(self._remaining(), 1.0)
            out["max_runtime_secs"] = min(out.get("max_runtime_secs") or rem, rem)
        return out

    def _drive(self, job: Job, x, y, training_frame, validation_frame, leaderboard_frame):
        s = self.spec
        self._t0 = time.time()
        train = training_frame if isinstance(training_frame, Frame) else DKV.get(str(training_frame))
        assert isinstance(train, Frame), "training_frame required"
        yv = train.vec(y)
        classification = yv.is_categorical()
        nclasses = len(yv.domain) if classification else 1
        sort_metric, larger = stopping_metric_direction(
            s.sort_metric if s.sort_metric.lower() != "auto"
            else ("auc" if (classification and nclasses == 2) else "AUTO"),
            classification, nclasses,
        )
        lb_frame = None
        if leaderboard_frame is not None:
            lb_frame = leaderboard_frame if isinstance(leaderboard_frame, Frame) else DKV.get(str(leaderboard_frame))
        self.leaderboard = Leaderboard(sort_metric, larger, leaderboard_frame=lb_frame)
        self._log("init", f"AutoML build started: {'classification' if classification else 'regression'}, sort_metric={sort_metric}")

        # optional target-encoding preprocessing: fit a KFold encoder on the
        # training frame (holdout-safe) and train every step on the frame
        # with appended _te columns (SURVEY.md §2.3 TE row)
        self._te = None
        if s.preprocessing and "target_encoding" in [str(q).lower() for q in s.preprocessing]:
            from h2o3_tpu.models.target_encoding import TargetEncoder

            cat_cols = [
                n for n in train.names
                if train.vec(n).is_categorical() and n != y
            ]
            if classification and nclasses > 2:
                self._log("preprocessing",
                          "target_encoding skipped: multiclass targets unsupported")
                cat_cols = []
            if cat_cols:
                te = TargetEncoder(
                    holdout_type="kfold", nfolds=max(s.nfolds, 2), blending=True,
                    seed=abs(s.seed) if s.seed and s.seed > 0 else 1,
                )
                te.fit(train, y, cat_cols)
                train = te.transform(train, as_training=True)
                if validation_frame is not None:
                    vf = validation_frame if isinstance(validation_frame, Frame) else DKV.get(str(validation_frame))
                    validation_frame = te.transform(vf)
                if lb_frame is not None:
                    lb_frame = te.transform(lb_frame)
                    self.leaderboard.leaderboard_frame = lb_frame
                self._te = te
                if x is not None:
                    x = list(x) + [c + "_te" for c in cat_cols if c in (x or [])]
                self._log("preprocessing", f"target encoding applied to {cat_cols}")

        plan = [st for st in _default_plan() if self._algo_allowed(st.algo)]
        n_models_built = 0
        family_best: dict[str, Model] = {}
        total_w = sum(st.weight for st in plan) or 1
        done_w = 0

        # crash recovery: finished steps recorded in the AutoML manifest
        # reload from the checkpoint dir instead of rebuilding (grid steps
        # additionally recover per-model through the grid manifest)
        ckdir = s.export_checkpoints_dir
        aml_id = _automl_id(s)
        fingerprint = None
        step_models: dict[str, list[str]] = {}
        step_attempts: dict[str, int] = {}
        if ckdir:
            fingerprint = _automl_fingerprint(s, x, y, train)
            step_models, step_attempts = _read_automl_manifest(
                ckdir, aml_id, fingerprint)

        def _recover_step(st) -> list[Model] | None:
            if not ckdir or st.name not in step_models:
                return None
            from h2o3_tpu.models.grid import _load_checkpointed

            ms = [_load_checkpointed(ckdir, k) for k in step_models[st.name]]
            return ms if ms and all(m is not None for m in ms) else None

        def _record_step(st, models: list[Model]) -> None:
            if not ckdir:
                return
            step_models[st.name] = [m.key for m in models]
            step_attempts.pop(st.name, None)  # finished: attempts moot
            _write_automl_manifest(ckdir, aml_id, fingerprint, step_models,
                                   step_attempts)

        from h2o3_tpu import config as _config

        step_retries = _config.get_int("H2O3_TPU_AUTOML_STEP_RETRIES")

        for st in plan:
            if self._remaining() <= 0:
                self._log("budget", "max_runtime_secs exhausted; stopping plan")
                break
            # ensembles and exploitation never count against max_models
            # (upstream: SEs are always attempted; exploitation is gated on
            # its own budget ratio)
            if s.max_models and n_models_built >= s.max_models and st.kind not in ("ensemble", "exploit"):
                done_w += st.weight
                job.update(done_w / total_w)
                continue
            # poison-step guard: the manifest records how many times this
            # step's build has STARTED across auto-resumes; a step that
            # crashed its whole retry budget is skipped so a
            # deterministically-failing step cannot kill every resume at the
            # same place forever (the supervised-recovery loop depends on
            # resumes making progress)
            if ckdir and st.kind in ("model", "grid") and st.name not in step_models:
                att = step_attempts.get(st.name, 0)
                if 0 < step_retries <= att:
                    Log.warn(
                        f"AutoML step {st.name} skipped: {att} crashed "
                        f"attempt(s) recorded in the manifest "
                        f"(H2O3_TPU_AUTOML_STEP_RETRIES={step_retries}) — "
                        "a poisoned step must not kill every auto-resume"
                    )
                    self._log("skip", f"{st.name} skipped after {att} "
                                      "crashed attempts (poison-step guard)")
                    done_w += st.weight
                    job.update(done_w / total_w)
                    continue
                step_attempts[st.name] = att + 1
                _write_automl_manifest(ckdir, aml_id, fingerprint,
                                       step_models, step_attempts)
            _st_t0 = time.time()
            _st_span = _mx.span("automl.step", step=st.name, kind=st.kind)
            _st_span.__enter__()
            try:
                if st.kind == "model":
                    recovered = _recover_step(st)
                    if recovered is not None:
                        for m in recovered:
                            self.leaderboard.add(m)
                            n_models_built += 1
                            self._update_family_best(family_best, m)
                        self._log("recover", f"{st.name} recovered from checkpoint dir")
                    else:
                        mkw = {**st.params, **self._common()}
                        if ckdir:
                            # the builder's own _drive saves the finished
                            # model into the dir AND writes interval
                            # snapshots while building (crash protection
                            # within the step, not just between steps)
                            mkw["export_checkpoints_dir"] = ckdir
                        m = self._builder(st.algo, mkw).train(
                            x=x, y=y, training_frame=train, validation_frame=validation_frame
                        )
                        if self._te is not None:
                            m.preprocessors.append(self._te)
                        self.leaderboard.add(m)
                        n_models_built += 1
                        self._update_family_best(family_best, m)
                        _record_step(st, [m])
                        self._log("model", f"{st.name} -> {m.key} {sort_metric}={self.leaderboard._metric_of(m):.5g}")
                    faults.die_check("automl")  # chaos: worker death
                    faults.abort_check("automl", n_models_built)
                elif st.kind == "grid":
                    recovered = _recover_step(st)
                    if recovered is not None:
                        for m in recovered:
                            self.leaderboard.add(m)
                            n_models_built += 1
                            self._update_family_best(family_best, m)
                        self._log("recover", f"{st.name} recovered {len(recovered)} models from checkpoint dir")
                        faults.abort_check("automl", n_models_built)
                        done_w += st.weight
                        job.update(done_w / total_w)
                        continue
                    from h2o3_tpu.models.grid import GridSearch, SearchCriteria

                    budget = self._remaining()
                    n_left = (s.max_models - n_models_built) if s.max_models else 0
                    crit = SearchCriteria(
                        strategy="RandomDiscrete",
                        max_models=max(1, n_left) if s.max_models else 0,
                        max_runtime_secs=budget * st.weight / max(1, total_w - done_w) if np.isfinite(budget) else 0.0,
                        seed=s.seed,
                        stopping_rounds=s.stopping_rounds,
                        stopping_metric=s.stopping_metric,
                        stopping_tolerance=s.stopping_tolerance,
                    )
                    gkw = {**st.params, **self._common()}
                    grid_id = None
                    if ckdir:
                        # a stable grid id + shared dir lets a killed grid
                        # step recover its finished combos per-model through
                        # the grid manifest on the next run
                        gkw["export_checkpoints_dir"] = ckdir
                        grid_id = f"{aml_id}_{st.name}"
                    gs = GridSearch(self._builder_cls(st.algo), st.hyper,
                                    search_criteria=crit, grid_id=grid_id,
                                    **gkw)
                    grid = gs.train(x=x, y=y, training_frame=train,
                                    validation_frame=validation_frame)
                    self.leaderboard.add(*grid.models)
                    n_models_built += len(grid.models)
                    for m in grid.models:
                        self._update_family_best(family_best, m)
                    _record_step(st, grid.models)
                    self._log("grid", f"{st.name} built {len(grid.models)} models")
                    faults.abort_check("automl", n_models_built)
                elif st.kind == "exploit":
                    if s.exploitation_ratio <= 0:
                        pass  # disabled by default, like upstream
                    else:
                        m = self._exploit_gbm(family_best, x, y, train, validation_frame)
                        if m is not None:
                            self.leaderboard.add(m)
                            n_models_built += 1
                            self._update_family_best(family_best, m)
                            self._log("exploit", f"{st.name} -> {m.key} {sort_metric}={self.leaderboard._metric_of(m):.5g}")
                elif st.kind == "ensemble":
                    m = self._build_ensemble(st, family_best, y, train, validation_frame)
                    if m is not None:
                        self.leaderboard.add(m)
                        self._log("ensemble", f"{st.name} -> {m.key} {sort_metric}={self.leaderboard._metric_of(m):.5g}")
            except faults.TrainAbort:
                raise  # simulated kill -9: die with the manifest on disk
            except Exception as e:
                from h2o3_tpu.cluster import recovery as _recovery

                if _recovery.is_cloud_failure(e):
                    # a dead/degraded cloud fails every later step the same
                    # way — die with the manifest on disk so the recovery
                    # supervisor (or the operator) resumes the whole run
                    raise
                self._log("error", f"{st.name} failed: {e!r}")
            finally:  # runs on the recovered-grid continue and TrainAbort too
                _st_span.__exit__(None, None, None)
                _AUTOML_STEPS.inc(kind=st.kind)
                _AUTOML_STEP_SECONDS.observe(time.time() - _st_t0, kind=st.kind)
            done_w += st.weight
            job.update(done_w / total_w)

        self._log("done", f"AutoML ended: {len(self.leaderboard.models)} models on leaderboard")
        return self.leaderboard

    def _update_family_best(self, family_best: dict[str, Model], m: Model) -> None:
        cur = family_best.get(m.algo)
        if cur is None or self.leaderboard._key(m) < self.leaderboard._key(cur):
            family_best[m.algo] = m

    def _build_ensemble(self, st: _Step, family_best: dict[str, Model], y, train, valid):
        from h2o3_tpu.models.ensemble import StackedEnsemble

        if st.params.get("flavor") == "best_of_family":
            base = list(family_best.values())
        else:
            base = [m for m in self.leaderboard.models if m.algo != "stackedensemble"]
        base = [m for m in base if m.cv_predictions is not None]
        if len(base) < 2:
            self._log("ensemble", f"{st.name} skipped (<2 stackable base models)")
            return None
        return StackedEnsemble(base_models=base, seed=self.spec.seed).train(
            y=y, training_frame=train, validation_frame=valid
        )
