"""Lazy client-side frame expressions — successor of ``h2o-py/h2o/expr.py``
(``ExprNode`` / lazy ``H2OFrame``) [UNVERIFIED upstream paths, SURVEY.md
§2.3].

The upstream client never computes frame ops locally: every operation on an
``H2OFrame`` appends to a lazy expression tree, which is rendered to the
Rapids wire grammar and shipped to ``POST /99/Rapids`` only when a result is
demanded (a print, a train call, ``to_pandas``). The same contract here:

    fr = H2OFrame.import_file(conn, "/data/x.csv")
    g = fr[fr["age"] > 30]          # nothing sent yet
    g["income"].mean()              # ONE rapids round-trip evaluates the tree

Materialization assigns a server-side temp key (``tmp=``), so chained ops
reuse server results instead of re-shipping subtrees.
"""

from __future__ import annotations

import io
import itertools
from typing import Any, Sequence

_TMP = itertools.count()


def _quote(s: str) -> str:
    return "'" + str(s).replace("\\", "\\\\").replace("'", "\\'") + "'"


class _RawSym(str):
    """A bare (unquoted) wire symbol, e.g. a GB aggregate name."""


def _render(x: Any) -> str:
    if isinstance(x, H2OFrame):
        return x._expr_str()
    if isinstance(x, _RawSym):
        return str(x)
    if isinstance(x, str):
        return _quote(x)
    if isinstance(x, bool):
        return "TRUE" if x else "FALSE"
    if isinstance(x, (list, tuple)):
        return "[" + " ".join(_render(v) for v in x) + "]"
    if isinstance(x, float):
        import math

        return "NaN" if math.isnan(x) else repr(x)  # bare nan is no symbol
    return repr(x)


class H2OFrame:
    """A lazy, server-backed frame: key OR pending expression."""

    def __init__(self, conn, key: str | None = None, expr: list | None = None):
        self._conn = conn
        self._key = key
        self._expr = expr  # [op, arg, ...] tree of H2OFrame/str/num/list

    # -- constructors --------------------------------------------------------
    @classmethod
    def import_file(cls, conn, path: str, destination_frame: str | None = None):
        key = conn.import_file(path, destination_frame)
        return cls(conn, key=key)

    @classmethod
    def from_key(cls, conn, key: str):
        return cls(conn, key=key)

    # -- expression plumbing -------------------------------------------------
    def _expr_str(self) -> str:
        if self._key is not None:
            return self._key
        op, *args = self._expr
        return "(" + " ".join([op] + [_render(a) for a in args]) + ")"

    def _node(self, op: str, *args) -> "H2OFrame":
        return H2OFrame(self._conn, expr=[op, self, *args])

    def refresh(self) -> "H2OFrame":
        """Force evaluation; afterwards this frame IS a server key."""
        if self._key is None:
            key = f"py_tmp_{next(_TMP)}"
            self._conn.rapids(f"(tmp= {key} {self._expr_str()})")
            self._key = key
            self._expr = None
        return self

    @property
    def frame_id(self) -> str:
        return self.refresh()._key

    # -- selection -----------------------------------------------------------
    def __getitem__(self, sel):
        if isinstance(sel, H2OFrame):  # boolean mask rows
            return self._node("rows", sel)
        if isinstance(sel, str):
            return self._node("cols_py", sel)
        if isinstance(sel, (list, tuple)) and all(isinstance(s, str) for s in sel):
            return self._node("cols_py", list(sel))
        if isinstance(sel, int):
            return self._node("cols_py", sel)
        if isinstance(sel, tuple) and len(sel) == 2:
            rows, cols = sel
            base = self[cols] if not isinstance(cols, slice) else self
            if isinstance(rows, H2OFrame):
                return base._node("rows", rows)
            return base
        raise TypeError(f"unsupported selector {sel!r}")

    # -- arithmetic / comparison --------------------------------------------
    def _bin(self, op, other, flip=False):
        a, b = (other, self) if flip else (self, other)
        return H2OFrame(self._conn, expr=[op, a, b])

    def __add__(self, o): return self._bin("+", o)
    def __radd__(self, o): return self._bin("+", o, True)
    def __sub__(self, o): return self._bin("-", o)
    def __rsub__(self, o): return self._bin("-", o, True)
    def __mul__(self, o): return self._bin("*", o)
    def __rmul__(self, o): return self._bin("*", o, True)
    def __truediv__(self, o): return self._bin("/", o)
    def __rtruediv__(self, o): return self._bin("/", o, True)
    def __pow__(self, o): return self._bin("^", o)
    def __mod__(self, o): return self._bin("%", o)
    def __gt__(self, o): return self._bin(">", o)
    def __ge__(self, o): return self._bin(">=", o)
    def __lt__(self, o): return self._bin("<", o)
    def __le__(self, o): return self._bin("<=", o)
    def __eq__(self, o): return self._bin("==", o)  # noqa: PLW3201
    def __ne__(self, o): return self._bin("!=", o)  # noqa: PLW3201
    __hash__ = None  # lazy frames are not hashable (== is symbolic)

    def __and__(self, o): return self._bin("&", o)
    def __or__(self, o): return self._bin("|", o)
    def __invert__(self): return self._node("not")

    # -- math ----------------------------------------------------------------
    def log(self): return self._node("log")
    def exp(self): return self._node("exp")
    def sqrt(self): return self._node("sqrt")
    def abs(self): return self._node("abs")
    def floor(self): return self._node("floor")
    def ceil(self): return self._node("ceiling")
    def tanh(self): return self._node("tanh")
    def round(self, digits: int = 0): return self._node("round", digits)
    def signif(self, digits: int = 6): return self._node("signif", digits)
    def cumsum(self): return self._node("cumsum")
    def cumprod(self): return self._node("cumprod")
    def cummin(self): return self._node("cummin")
    def cummax(self): return self._node("cummax")
    def difflag1(self): return self._node("difflag1")

    def fillna(self, method: str = "forward", axis: int = 0, maxlen: int = 0):
        return self._node("h2o.fillna", method, axis, maxlen)

    # -- scalar reductions (eager: they return numbers) ----------------------
    def _reduce(self, op: str) -> float:
        res = self._conn.rapids(f"({op} {self._expr_str()})")
        return res.get("scalar")

    def sum(self): return self._reduce("sum")
    def mean(self): return self._reduce("mean")
    def min(self): return self._reduce("min")
    def max(self): return self._reduce("max")
    def sd(self): return self._reduce("sd")
    def median(self): return self._reduce("median")
    def skewness(self): return self._reduce("skewness")
    def kurtosis(self): return self._reduce("kurtosis")
    def all(self): return bool(self._reduce("all"))
    def any(self): return bool(self._reduce("any"))
    def anyna(self): return bool(self._reduce("anyNA"))

    # -- string ops ----------------------------------------------------------
    def toupper(self): return self._node("toupper")
    def tolower(self): return self._node("tolower")
    def trim(self): return self._node("trim")
    def lstrip(self, chars: str | None = None):
        return self._node("lstrip", chars) if chars else self._node("lstrip")
    def rstrip(self, chars: str | None = None):
        return self._node("rstrip", chars) if chars else self._node("rstrip")
    def nchar(self): return self._node("nchar")
    def entropy(self): return self._node("entropy")
    def countmatches(self, patterns):
        pats = [patterns] if isinstance(patterns, str) else list(patterns)
        return self._node("countmatches", pats)

    # -- frame verbs ---------------------------------------------------------
    def unique(self): return self._node("unique")

    def table(self): return self._node("table")

    def match(self, table, nomatch=float("nan")):
        return self._node("match", list(table), nomatch)

    def isin(self, table):
        return self._node("%in%", list(table))

    def which(self): return self._node("which")

    def na_omit(self): return self._node("na.omit")

    def pivot(self, index: str, column: str, value: str):
        return self._node("pivot", index, column, value)

    def stratified_split(self, test_frac: float = 0.2, seed: int = -1):
        return self._node("h2o.random_stratified_split", test_frac, seed)

    def split_frame(self, ratios=(0.75,), destination_frames=None,
                    seed: int = 1234) -> list["H2OFrame"]:
        """Random row split via /3/SplitFrame (materializes this frame
        first) — the h2o.split_frame client verb."""
        keys = self._conn.split_frame(
            self.frame_id, list(ratios), destination_frames, seed=seed
        )
        return [H2OFrame(self._conn, key=k) for k in keys]

    def sort(self, by, ascending=True):
        cols = [by] if isinstance(by, str) else list(by)
        asc = [ascending] * len(cols) if isinstance(ascending, bool) else list(ascending)
        return self._node("sort", cols, asc)

    def merge(self, other: "H2OFrame", all_x: bool = False, all_y: bool = False):
        """Join on the shared columns — (merge l r all_x all_y) wire form."""
        return H2OFrame(self._conn, expr=["merge", self, other, all_x, all_y])

    def cbind(self, other: "H2OFrame"):
        return H2OFrame(self._conn, expr=["cbind", self, other])

    def rbind(self, other: "H2OFrame"):
        return H2OFrame(self._conn, expr=["rbind", self, other])

    def group_by(self, by, **aggs):
        """(GB frame [by] agg col na …) triples — aggs like income='mean'."""
        spec: list = []
        for col, how in aggs.items():
            spec.extend([_RawSym(how), col, "all"])
        by_l = [by] if isinstance(by, str) else list(by)
        return H2OFrame(self._conn, expr=["GB", self, by_l, *spec])

    def ifelse(self, yes, no):
        return H2OFrame(self._conn, expr=["ifelse", self, yes, no])

    # -- materialization -----------------------------------------------------
    def to_pandas(self):
        import pandas as pd

        key = self.frame_id
        raw = self._conn.download_csv(key)
        return pd.read_csv(io.BytesIO(raw))

    def head(self, n: int = 10):
        return self.to_pandas().head(n)

    def describe(self) -> dict:
        return self._conn.get(f"/3/Frames/{self.frame_id}/summary")

    @property
    def shape(self) -> tuple[int, int]:
        info = self._conn.frame(self.frame_id)  # already the frame schema
        return info["rows"], info["column_count"]

    @property
    def names(self) -> list[str]:
        info = self._conn.frame(self.frame_id)
        return [c["label"] for c in info["columns"]]

    def __repr__(self) -> str:
        if self._key is not None:
            return f"<H2OFrame {self._key}>"
        return f"<H2OFrame lazy: {self._expr_str()}>"
