"""Microtimer, successor of ``water.util.Timer`` [UNVERIFIED upstream path]."""

from __future__ import annotations

import time


class Timer:
    def __init__(self) -> None:
        self.start = time.perf_counter()

    def time_ms(self) -> float:
        return (time.perf_counter() - self.start) * 1e3

    def time_s(self) -> float:
        return time.perf_counter() - self.start

    def __str__(self) -> str:
        ms = self.time_ms()
        return f"{ms:.1f} ms" if ms < 1e3 else f"{ms / 1e3:.2f} s"
