"""Tracing / profiling — successor of ``water.TimeLine`` / ``/3/Timeline``
and the ``/3/Profiler`` stack sampler [UNVERIFIED upstream paths, SURVEY.md
§5.1].

On TPU, XLA compile time IS the dominant hidden cost (AutoML builds many
small programs), so the timeline's first-class events are compilations:
``install()`` hooks jax's compile logging into a ring buffer. ``profiler``
wraps ``jax.profiler.trace`` (xplane dumps viewable in TensorBoard/XProf) —
the JProfile/stack-sampling analog for a compiled runtime.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time

_EVENTS: collections.deque = collections.deque(maxlen=4096)
_LOCK = threading.Lock()
_INSTALLED = False


def record(kind: str, msg: str) -> None:
    with _LOCK:
        _EVENTS.append({"ts": time.time(), "kind": kind, "msg": msg})


def events(n: int = 200) -> list[dict]:
    with _LOCK:
        return list(_EVENTS)[-n:]


class _CompileHandler(logging.Handler):
    def emit(self, rec: logging.LogRecord) -> None:
        m = rec.getMessage()
        if "compil" in m.lower():
            record("compile", m)


def install() -> None:
    """Capture XLA compile events into the timeline (idempotent)."""
    global _INSTALLED
    if _INSTALLED:
        return
    import jax

    try:
        jax.config.update("jax_log_compiles", True)
    except Exception:
        return
    h = _CompileHandler()
    h.setLevel(logging.DEBUG)
    for name in ("jax._src.dispatch", "jax._src.interpreters.pxla"):
        lg = logging.getLogger(name)
        lg.addHandler(h)
        if lg.level > logging.DEBUG or lg.level == logging.NOTSET:
            lg.setLevel(logging.DEBUG)
    _INSTALLED = True
    record("telemetry", "compile-event capture installed")


@contextlib.contextmanager
def profiler(logdir: str):
    """``jax.profiler.trace`` wrapper — xplane dumps for TensorBoard/XProf."""
    import jax

    record("profiler", f"trace started → {logdir}")
    with jax.profiler.trace(logdir):
        yield
    record("profiler", f"trace written → {logdir}")


def timeline(n: int = 200) -> dict:
    """The GET /3/Timeline payload."""
    evs = events(n)
    return {
        "events": evs,
        "compile_count": sum(1 for e in _EVENTS if e["kind"] == "compile"),
    }
