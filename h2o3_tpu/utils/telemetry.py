"""Tracing / profiling — successor of ``water.TimeLine`` / ``/3/Timeline``
and the ``/3/Profiler`` stack sampler [UNVERIFIED upstream paths, SURVEY.md
§5.1].

On TPU, XLA compile time IS the dominant hidden cost (AutoML builds many
small programs), so the timeline's first-class events are compilations:
``install()`` hooks jax's compile logging into a ring buffer. ``profiler``
wraps ``jax.profiler.trace`` (xplane dumps viewable in TensorBoard/XProf) —
the JProfile/stack-sampling analog for a compiled runtime.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import threading
import time

_EVENTS: collections.deque = collections.deque(maxlen=4096)
_LOCK = threading.Lock()
_INSTALLED = False


def record(kind: str, msg: str) -> None:
    with _LOCK:
        _EVENTS.append({"ts": time.time(), "kind": kind, "msg": msg})


def events(n: int = 200) -> list[dict]:
    with _LOCK:
        return list(_EVENTS)[-n:]


class _CompileHandler(logging.Handler):
    def emit(self, rec: logging.LogRecord) -> None:
        m = rec.getMessage()
        if "compil" in m.lower():
            record("compile", m)


def install() -> None:
    """Capture XLA compile events into the timeline (idempotent)."""
    global _INSTALLED
    if _INSTALLED:
        return
    import jax

    try:
        jax.config.update("jax_log_compiles", True)
    except Exception:
        return
    h = _CompileHandler()
    h.setLevel(logging.DEBUG)
    for name in ("jax._src.dispatch", "jax._src.interpreters.pxla"):
        lg = logging.getLogger(name)
        lg.addHandler(h)
        if lg.level > logging.DEBUG or lg.level == logging.NOTSET:
            lg.setLevel(logging.DEBUG)
    _INSTALLED = True
    record("telemetry", "compile-event capture installed")


@contextlib.contextmanager
def profiler(logdir: str):
    """``jax.profiler.trace`` wrapper — xplane dumps for TensorBoard/XProf.
    Start/end also stamp the flight-recorder ring (utils/flightrec.py), so
    an xplane capture window cross-references with the dispatch events by
    timestamp — which programs the profiler saw is readable from the ring."""
    import jax

    from h2o3_tpu.utils import flightrec

    record("profiler", f"trace started → {logdir}")
    flightrec.record("profiler_start", logdir=logdir)
    with jax.profiler.trace(logdir):
        yield
    record("profiler", f"trace written → {logdir}")
    flightrec.record("profiler_end", logdir=logdir)


def timeline(n: int = 200) -> dict:
    """The GET /3/Timeline payload: compile/profiler events merged with the
    metrics layer's recent span events, by timestamp."""
    # ONE snapshot under the lock serves both the event tail and the compile
    # count — iterating the live deque unlocked raced concurrent record()
    # appends (RuntimeError: deque mutated during iteration)
    with _LOCK:
        snap = list(_EVENTS)
    compile_count = sum(1 for e in snap if e["kind"] == "compile")
    evs = snap[-n:]
    span_count = 0
    try:
        from h2o3_tpu.utils import metrics

        spans = metrics.recent_spans(n)
        span_count = len(spans)
        evs = evs + [
            {"ts": s["ts"], "kind": "span",
             "msg": s["name"], "dur_ms": round(s["dur_s"] * 1e3, 3),
             **({"job": s["trace"]} if s["trace"] else {})}
            for s in spans
        ]
        evs = sorted(evs, key=lambda e: e["ts"])[-n:]
    except Exception:  # metrics layer disabled/broken must not sink /3/Timeline
        pass
    return {
        "events": evs,
        "compile_count": compile_count,
        "span_count": span_count,
    }
