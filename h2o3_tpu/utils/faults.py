"""Deterministic fault injection — the chaos half of the fail-stop story
(SURVEY.md §5.3: "restart is the recovery path; durability comes from model
checkpoints").

The cloud's failure machinery (persist retry/backoff, the degraded latch,
checkpoint-resume) is only trustworthy if it can be *exercised*, and real
faults — a flaky NFS mount, a kill -9 mid-forest, a dead mesh member — are
neither deterministic nor CI-safe. This module provides the three synthetic
failure modes the chaos test suite (``pytest -m chaos``) drives:

- **persist IO failures**: ``io_check(site)`` raises :class:`InjectedIOError`
  (a *transient* ``OSError`` the persist retry wrapper is allowed to retry)
  for the first N calls at a site (``persist_write``, ``persist_read``).
- **mid-train aborts**: ``abort_check(site, iteration)`` raises
  :class:`TrainAbort` when the driver reaches the armed iteration — the
  in-process stand-in for kill -9, placed AFTER the interval checkpoint
  export so the snapshot on disk is exactly what a crash would leave.
- **coordination-service death**: ``make_death_error()`` builds an exception
  whose type name and message match the signatures
  ``spmd._maybe_mark_dead_member`` latches on, and ``death_check(site)``
  raises one at an armed site (e.g. ``spmd_run``) to drive the full
  broadcast-failure → ``cloud.mark_degraded`` path without a real dead rank.
- **process death at a collective boundary**: ``die_check(site)`` raises the
  same death-signature error one-shot, but its call sites live at the
  COLLECTIVE BOUNDARIES of the training drivers (the per-interval
  checkpoint boundary of GBM/DRF/GLM/DL/AutoML, the spmd command broadcast)
  — the in-process stand-in for a WORKER dying mid-collective, which is
  what the supervised-recovery drills (cluster/recovery.py) recover from.
- **persist blackout**: ``blackout:SECS`` makes EVERY persist IO call fail
  transiently for a wall-clock window of SECS from arming — the storage
  *outage* stand-in (vs ``site=N``'s counted flakes): proves the retry
  backoff rides out an outage shorter than its budget horizon and surfaces
  cleanly past it.
- **stalls** (the overload/hang chaos half): ``stall_check(site)`` sleeps the
  armed number of seconds ONCE (the in-process stand-in for a wedged
  collective — drives the spmd watchdog), and ``slow_check(site)`` sleeps at
  EVERY call while armed (slow-handler injection: makes a REST handler or a
  training interval slow enough for admission-control/drain tests to
  observe overload deterministically).
- **device OOM** (ISSUE 19): ``oom_check(site)`` raises one synthetic
  :class:`XlaRuntimeError` carrying the real ``RESOURCE_EXHAUSTED``
  signature at a flightrec dispatch site (``oom:site``) — the
  OOM-catch-and-degrade drills prove classify → incident → degraded
  retry without actually exhausting HBM.
- **dispatch hangs** (ISSUE 19): ``hang_check(site)`` sleeps the armed
  seconds ONCE *inside* the dispatch span (``hang:site:SECS``) — unlike
  ``stall:`` (which wedges a collective outside any dispatch), this leaves
  an OPEN ``dispatch_start`` in the flight-recorder ring, which is exactly
  what the overload hang watchdog walks for.

Arming is explicit (context manager / ``configure``) or via the
``H2O3_TPU_FAULTS`` env knob (config.py), spec ``;``-separated:
``site=N`` fails the first N IO calls, ``site@K`` aborts at iteration K,
``death:site`` raises a synthetic death error at the site, ``die:site``
raises one at a collective-boundary site, ``reshape:RxC`` induces a
one-shot TOPOLOGY CHANGE at the next collective boundary (the death error
fires and the RxC target parks for ``recovery.reform`` to consume via
:func:`take_reshape` — the elastic-recovery chaos primitive, ISSUE 17),
``blackout:SECS`` fails all persist IO for a SECS window,
``stall:site:SECS`` sleeps once, ``slow:site:SECS`` sleeps every call,
``oom:site`` raises one synthetic RESOURCE_EXHAUSTED at a dispatch site,
``hang:site:SECS`` sleeps once inside the dispatch at the site.
When nothing is armed every check is a single module-bool test — hot paths
pay ~nothing.

Determinism contract: counters are keyed by site and incremented in call
order, so a seeded single-threaded run injects at exactly the same point
every time (and on every rank of a replicated command, preserving the spmd
lockstep contract).
"""

from __future__ import annotations

import contextlib
import threading


class InjectedIOError(OSError):
    """Transient IO failure injected by the fault harness (retryable)."""


class TrainAbort(RuntimeError):
    """Simulated hard process death mid-train.

    Deliberately NOT swallowed by the grid/AutoML per-model failure handlers
    (a real kill -9 gives them no chance either): they re-raise it so the
    whole job dies with the latest interval checkpoint on disk.
    """


class XlaRuntimeError(Exception):
    """Synthetic stand-in matching the real jaxlib XlaRuntimeError by TYPE
    NAME — ``spmd._maybe_mark_dead_member`` keys on the name, so chaos tests
    can drive the degraded latch without a real dead mesh member."""


_lock = threading.Lock()
_armed = False
_fail: dict[str, int] = {}      # io site -> remaining injected failures
_abort: dict[str, int] = {}     # abort site -> iteration to die at
_death: set[str] = set()        # sites where a synthetic death error fires
_die: set[str] = set()          # collective-boundary sites (worker death)
_blackout_until: float | None = None  # persist outage window end (monotonic)
_stall: dict[str, float] = {}   # site -> one-shot sleep seconds (wedge)
_slow: dict[str, float] = {}    # site -> per-call sleep seconds (slowdown)
_oom: set[str] = set()          # dispatch sites raising one RESOURCE_EXHAUSTED
_hang: dict[str, float] = {}    # site -> one-shot in-dispatch sleep seconds
_counts: dict[str, int] = {}    # site -> observed check calls (tests assert)
# elastic-recovery chaos (ISSUE 17): an induced TOPOLOGY CHANGE at the next
# collective boundary. _reshape is the armed (rows, cols) target; when the
# one-shot fires (die_check, any site) it moves to _reshape_pending, where
# recovery.reform() consumes it via take_reshape() and re-forms the mesh
# onto that shape — the in-process stand-in for "the autoscaler gave the
# job back a different pod".
_reshape: tuple[int, int] | None = None
_reshape_pending: tuple[int, int] | None = None

_DEATH_MSG = ("injected fault: coordination service reports peer task is "
              "unhealthy (heartbeat timeout)")


def _parse_reshape(val: str) -> tuple[int, int]:
    """'RxC' (or 'R×C') -> (rows, cols); rows=1 means the 1-D mesh."""
    m = val.strip().lower().replace("×", "x").split("x")
    if len(m) != 2:
        raise ValueError(f"bad reshape spec {val!r} (want RxC, e.g. 2x4)")
    r, c = int(m[0]), int(m[1])
    if r < 1 or c < 1:
        raise ValueError(f"bad reshape spec {val!r} (rows/cols must be >=1)")
    return r, c


def _parse_spec(spec: str) -> None:
    """Arm from an ``H2O3_TPU_FAULTS`` spec string (see module docstring)."""
    global _armed, _blackout_until, _reshape
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("death:"):
            _death.add(part[len("death:"):])
        elif part.startswith("reshape:"):
            _reshape = _parse_reshape(part[len("reshape:"):])
        elif part.startswith("die:"):
            _die.add(part[len("die:"):])
        elif part.startswith("blackout:"):
            import time

            secs = float(part[len("blackout:"):])
            _blackout_until = time.monotonic() + secs
        elif part.startswith("oom:"):
            _oom.add(part[len("oom:"):])
        elif part.startswith(("stall:", "slow:", "hang:")):
            kind, rest = part.split(":", 1)
            site, _, secs = rest.rpartition(":")
            if not site:
                raise ValueError(f"bad H2O3_TPU_FAULTS entry {part!r} "
                                 "(want stall:site:SECS, slow:site:SECS "
                                 "or hang:site:SECS)")
            {"stall": _stall, "slow": _slow,
             "hang": _hang}[kind][site] = float(secs)
        elif "@" in part:
            site, at = part.split("@", 1)
            _abort[site] = int(at)
        elif "=" in part:
            site, n = part.split("=", 1)
            _fail[site] = int(n)
        else:
            raise ValueError(
                f"bad H2O3_TPU_FAULTS entry {part!r} (want site=N, site@K, "
                "death:site, die:site, reshape:RxC, blackout:SECS, "
                "stall:site:SECS, slow:site:SECS, oom:site or "
                "hang:site:SECS)")
    _armed = bool(_fail or _abort or _death or _die or _blackout_until
                  or _stall or _slow or _oom or _hang or _reshape)


def configure(fail: dict[str, int] | None = None,
              abort: dict[str, int] | None = None,
              death: set[str] | frozenset[str] | None = None,
              die: set[str] | frozenset[str] | None = None,
              blackout: float | None = None,
              stall: dict[str, float] | None = None,
              slow: dict[str, float] | None = None,
              oom: set[str] | frozenset[str] | None = None,
              hang: dict[str, float] | None = None,
              reshape: tuple[int, int] | str | None = None) -> None:
    """Arm the harness programmatically (additive to whatever is armed)."""
    global _armed, _blackout_until, _reshape
    with _lock:
        _fail.update(fail or {})
        _abort.update(abort or {})
        _death.update(death or ())
        _die.update(die or ())
        if blackout is not None:
            import time

            _blackout_until = time.monotonic() + float(blackout)
        _stall.update(stall or {})
        _slow.update(slow or {})
        _oom.update(oom or ())
        _hang.update(hang or {})
        if reshape is not None:
            _reshape = (_parse_reshape(reshape) if isinstance(reshape, str)
                        else (int(reshape[0]), int(reshape[1])))
        _armed = bool(_fail or _abort or _death or _die or _blackout_until
                      or _stall or _slow or _oom or _hang or _reshape)


def armed() -> bool:
    """True when any fault primitive is armed. Chaos-aware subsystems read
    this to clamp batching/fusion that would move abort or snapshot
    boundaries (the DL epoch-chunk loop drops to one epoch per dispatch so
    ``site@K`` aborts land at exact epoch counts)."""
    return _armed


def reset() -> None:
    """Disarm everything and clear counters (re-reads the env knob)."""
    global _armed, _blackout_until, _reshape, _reshape_pending
    with _lock:
        _fail.clear()
        _abort.clear()
        _death.clear()
        _die.clear()
        _blackout_until = None
        _stall.clear()
        _slow.clear()
        _oom.clear()
        _hang.clear()
        _counts.clear()
        _reshape = None
        _reshape_pending = None
        _armed = False
        from h2o3_tpu import config

        spec = config.get("H2O3_TPU_FAULTS")
        if spec:
            _parse_spec(spec)


@contextlib.contextmanager
def inject(fail: dict[str, int] | None = None,
           abort: dict[str, int] | None = None,
           death: set[str] | frozenset[str] | None = None,
           die: set[str] | frozenset[str] | None = None,
           blackout: float | None = None,
           stall: dict[str, float] | None = None,
           slow: dict[str, float] | None = None,
           oom: set[str] | frozenset[str] | None = None,
           hang: dict[str, float] | None = None,
           reshape: tuple[int, int] | str | None = None):
    """Scoped arming for tests: arms on entry, fully resets on exit."""
    configure(fail=fail, abort=abort, death=death, die=die,
              blackout=blackout, stall=stall, slow=slow, oom=oom,
              hang=hang, reshape=reshape)
    try:
        yield
    finally:
        reset()


def counts() -> dict[str, int]:
    """Observed check calls per site (armed sites only) — test assertions."""
    with _lock:
        return dict(_counts)


def io_check(site: str, detail: str = "") -> None:
    """Raise an :class:`InjectedIOError` while the site has fail budget.

    Called once per persist IO *attempt* — the retry wrapper re-enters it,
    so ``fail={"persist_write": 2}`` means attempts 1–2 fail and attempt 3
    succeeds (proving retry-within-budget)."""
    if not _armed:
        return
    with _lock:
        _counts[site] = _counts.get(site, 0) + 1
        if _blackout_until is not None:
            import time

            if time.monotonic() < _blackout_until:
                raise InjectedIOError(
                    f"injected persist blackout at {site} (outage window "
                    "still open)")
        left = _fail.get(site, 0)
        if left <= 0:
            return
        _fail[site] = left - 1
    raise InjectedIOError(
        f"injected transient IO failure at {site}"
        + (f" ({detail})" if detail else "")
    )


def abort_check(site: str, iteration: int) -> None:
    """Raise :class:`TrainAbort` when the armed iteration is reached.

    Drivers call this at every scoring-interval boundary AFTER the interval
    checkpoint export, with the number of units (trees/iterations/epochs/
    models) completed so far."""
    if not _armed:
        return
    with _lock:
        at = _abort.get(site)
        if at is None or int(iteration) < at:
            return
        # one-shot: a restarted (resumed) run in the same process must not
        # die again at the same boundary
        _abort.pop(site, None)
    raise TrainAbort(
        f"injected mid-train abort at {site} iteration {iteration} "
        "(simulated process death; resume from the latest checkpoint)"
    )


def stall_check(site: str) -> None:
    """Sleep the armed seconds ONCE at the site — the wedged-collective
    stand-in (a replicated command that stops making progress). One-shot so
    the command FINISHES after the stall: the spmd watchdog's latch, not the
    sleep itself, is what the chaos test asserts on."""
    if not _armed:
        return
    with _lock:
        secs = _stall.pop(site, None)
        if secs is None:
            return
        _counts[site] = _counts.get(site, 0) + 1
    import time

    time.sleep(secs)


def slow_check(site: str) -> None:
    """Sleep the armed seconds at EVERY call while the site stays armed —
    slow-handler injection (an overloaded route, a slow training interval).
    Stays armed until reset so concurrent requests all feel the slowdown."""
    if not _armed:
        return
    with _lock:
        secs = _slow.get(site)
        if secs is None:
            return
        _counts[site] = _counts.get(site, 0) + 1
    import time

    time.sleep(secs)


def oom_check(site: str) -> None:
    """Raise one synthetic :class:`XlaRuntimeError` carrying the real
    ``RESOURCE_EXHAUSTED`` signature at an armed dispatch site (one-shot:
    the degraded retry of the same job must not OOM again). The overload
    plane classifies it exactly like a real device OOM (text match on
    RESOURCE_EXHAUSTED), so the catch-and-degrade path is drillable on
    the CPU proxy."""
    if not _armed:
        return
    with _lock:
        if site not in _oom:
            return
        _oom.discard(site)
        _counts[site] = _counts.get(site, 0) + 1
    raise XlaRuntimeError(
        f"RESOURCE_EXHAUSTED: injected out-of-memory while allocating "
        f"device buffer at dispatch site {site!r} (synthetic: attempting "
        "to allocate more than available HBM)")


def hang_check(site: str) -> None:
    """Sleep the armed seconds ONCE *inside* the dispatch span at the site
    — the wedged-dispatch stand-in. The sleep happens after the flight
    recorder stamps ``dispatch_start``, so the ring shows an open dispatch
    the whole time: exactly the state the overload hang watchdog detects.
    One-shot so the dispatch eventually unwedges — the watchdog's trip
    (latch + incident + hung-span fail-stop), not the sleep, is what the
    drills assert on."""
    if not _armed:
        return
    with _lock:
        secs = _hang.pop(site, None)
        if secs is None:
            return
        _counts[site] = _counts.get(site, 0) + 1
    import time

    time.sleep(secs)


def make_death_error(msg: str = _DEATH_MSG) -> Exception:
    """An exception carrying a coordination-service death signature that
    ``spmd._maybe_mark_dead_member`` recognizes (by type name + message)."""
    return XlaRuntimeError(msg)


def death_check(site: str) -> None:
    """Raise a synthetic coordination-service death error at an armed site
    (one-shot, like a real dead member poisoning the next collective)."""
    if not _armed:
        return
    with _lock:
        if site not in _death:
            return
        _death.discard(site)
    raise make_death_error()


def die_check(site: str) -> None:
    """Simulated WORKER death at a collective boundary (one-shot): raises
    the same death-signature error as :func:`death_check`, but its call
    sites live where the training drivers cross collective boundaries (the
    per-interval loops of GBM/DRF/GLM/DL/AutoML — right after the interval
    checkpoint export, so the snapshot on disk is exactly what a real death
    would leave — and the spmd command broadcast). The supervised-recovery
    chaos drills arm this to prove detection → reform → resume end-to-end."""
    global _reshape, _reshape_pending
    if not _armed:
        return
    with _lock:
        if _reshape is not None:
            # induced topology change (ISSUE 17): the formation "comes back
            # different" at this collective boundary — one-shot; the target
            # shape parks in the pending slot until recovery.reform()
            # consumes it via take_reshape()
            shape, _reshape = _reshape, None
            _reshape_pending = shape
            _counts[site] = _counts.get(site, 0) + 1
            raise make_death_error(
                f"injected fault: topology changed at collective boundary "
                f"{site!r} — formation re-plans to {shape[0]}x{shape[1]} "
                "(coordination service reports peer task is unhealthy; "
                "heartbeat timeout)")
        if site not in _die:
            return
        _die.discard(site)
        _counts[site] = _counts.get(site, 0) + 1
    raise make_death_error(
        f"injected fault: worker died at collective boundary {site!r} "
        "(coordination service reports peer task is unhealthy; "
        "heartbeat timeout)")


def take_reshape() -> tuple[int, int] | None:
    """Consume (and clear) the pending induced-reshape target, if any —
    called by ``recovery.reform`` so the resume lands on the new shape."""
    global _reshape_pending
    with _lock:
        shape, _reshape_pending = _reshape_pending, None
    return shape


# env-armed at import so `H2O3_TPU_FAULTS=... pytest` / launch.py work
# without code changes; import cost is one config read
reset()
