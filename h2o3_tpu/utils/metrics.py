"""Cluster-wide metrics registry + per-job span tracing — the first-class
observability layer (successor of ``water.util.Log`` counters + ``/3/Timeline``
phase timing, done as one subsystem; docs/OBSERVABILITY.md is the runbook).

Three pieces, one module:

- **Registry** (:data:`REGISTRY`): thread-safe labeled counters, gauges and
  bucketed histograms. Served as Prometheus text exposition over
  ``GET /3/Metrics`` (JSON with ``?format=json``) and snapshotted into bench
  artifacts, so the live endpoint and the bench numbers can never disagree.
- **Spans** (:func:`span`): a hierarchical timing context manager.
  ``span("gbm.build_tree", trees=8)`` nests under the enclosing span and
  under the active Job's trace (:func:`trace`, entered by ``Job.start``);
  every completed span lands in the per-trace event list (served as
  Chrome-trace JSON over ``GET /3/Jobs/{key}/trace``), in the recent-span
  ring merged into ``/3/Timeline``, and in the ``span_seconds`` latency
  histogram.
- **Gate**: ``H2O3_TPU_METRICS=0`` turns the layer into near-free no-ops
  (read once at import — the hot paths must not re-read the environment).
  Counters created with ``always=True`` keep counting even when gated:
  the tree-build counters behind the ``BUILD_STATS`` back-compat alias are
  a test/bench CONTRACT (dispatch/compile accounting), not optional
  telemetry.

Hot-path budget: one ``perf_counter`` pair + one locked dict update per
span/observe — the bench fused-tree acceptance bound is <= 2% overhead
registry-on vs ``H2O3_TPU_METRICS=0``.
"""

from __future__ import annotations

import bisect
import collections
import contextlib
import contextvars
import itertools
import threading
import time

# read ONCE at import: the gate is checked on every counter bump and span
# enter — config.get (env lookup) per call would itself be the overhead the
# gate exists to remove. set_enabled() is the test/bench override.
from h2o3_tpu import config as _config

_ENABLED: bool = _config.get_bool("H2O3_TPU_METRICS")


def enabled() -> bool:
    return _ENABLED


def set_enabled(flag: bool) -> None:
    """Test/ops override of the import-time H2O3_TPU_METRICS gate."""
    global _ENABLED
    _ENABLED = bool(flag)


# ---------------------------------------------------------------------------
# metric families

# Prometheus default buckets extended down (sub-ms device dispatches) and up
# (multi-minute AutoML steps).
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _escape_label(v) -> str:
    return (
        str(v).replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render without the .0 tail."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Family:
    kind = "untyped"

    def __init__(self, name: str, help: str, always: bool = False):
        self.name = name
        self.help = help
        self.always = always  # True: bypass the H2O3_TPU_METRICS gate
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def _on(self) -> bool:
        return _ENABLED or self.always

    def remove(self, **labels) -> None:
        """Drop one labeled child. Bounded-cardinality families (the per-job
        ledger's ``job_*`` series) evict LRU jobs through this so the
        registry can't grow one child per job forever."""
        with self._lock:
            self._children.pop(_label_key(labels), None)


class Counter(_Family):
    kind = "counter"

    def __init__(self, name: str, help: str, always: bool = False):
        super().__init__(name, help, always)
        self._children[()] = 0.0  # unlabeled child renders from creation

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._on():
            return
        k = _label_key(labels)
        with self._lock:
            self._children[k] = self._children.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._children.get(_label_key(labels), 0.0))

    def set_(self, v: float, **labels) -> None:
        """Non-monotonic write — ONLY for the BUILD_STATS back-compat alias
        (``BUILD_STATS[k] = v``) and counter resets; not part of the
        Prometheus counter contract."""
        with self._lock:
            self._children[_label_key(labels)] = float(v)

    def samples(self):
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._children.items())]


class Gauge(_Family):
    kind = "gauge"

    def __init__(self, name: str, help: str, always: bool = False):
        super().__init__(name, help, always)
        self._children[()] = 0.0

    def set(self, v: float, **labels) -> None:
        if not self._on():
            return
        with self._lock:
            self._children[_label_key(labels)] = float(v)

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._on():
            return
        k = _label_key(labels)
        with self._lock:
            self._children[k] = self._children.get(k, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._children.get(_label_key(labels), 0.0))

    samples = Counter.samples


class _HistChild:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # last slot = +Inf overflow
        self.sum = 0.0
        self.count = 0


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name: str, help: str, buckets=None, always: bool = False):
        super().__init__(name, help, always)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def observe(self, v: float, **labels) -> None:
        if not self._on():
            return
        k = _label_key(labels)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            child = self._children.get(k)
            if child is None:
                child = self._children[k] = _HistChild(len(self.buckets))
            child.counts[i] += 1
            child.sum += v
            child.count += 1

    def samples(self):
        """[(labels, cumulative_bucket_counts, sum, count)] — cumulative per
        the Prometheus histogram contract (``le`` buckets are inclusive
        prefixes)."""
        out = []
        with self._lock:
            for k, c in sorted(self._children.items(), key=lambda kv: kv[0]):
                cum, tot = [], 0
                for n in c.counts:
                    tot += n
                    cum.append(tot)
                out.append((dict(k), cum, c.sum, c.count))
        return out


class MetricsRegistry:
    """Process-wide family registry (one per coordinator process; followers
    keep their own — REST serves the coordinator's, like H2O's per-node
    logs)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get(self, name: str, cls, *args, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(name, *args, **kw)
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            return fam

    def counter(self, name: str, help: str = "", always: bool = False) -> Counter:
        return self._get(name, Counter, help, always)

    def gauge(self, name: str, help: str = "", always: bool = False) -> Gauge:
        return self._get(name, Gauge, help, always)

    def histogram(self, name: str, help: str = "", buckets=None,
                  always: bool = False) -> Histogram:
        return self._get(name, Histogram, help, buckets, always)

    def families(self) -> list[_Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every family."""
        lines: list[str] = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            if isinstance(fam, Histogram):
                for labels, cum, s, n in fam.samples():
                    base = [f'{k}="{_escape_label(v)}"'
                            for k, v in sorted(labels.items())]
                    for le, c in zip(
                        [*(_fmt(b) for b in fam.buckets), "+Inf"], cum
                    ):
                        lab = ",".join(base + [f'le="{le}"'])
                        lines.append(f"{fam.name}_bucket{{{lab}}} {c}")
                    suffix = "{" + ",".join(base) + "}" if base else ""
                    lines.append(f"{fam.name}_sum{suffix} {_fmt(s)}")
                    lines.append(f"{fam.name}_count{suffix} {n}")
            else:
                for labels, v in fam.samples():
                    if labels:
                        lab = ",".join(
                            f'{k}="{_escape_label(val)}"'
                            for k, val in sorted(labels.items())
                        )
                        lines.append(f"{fam.name}{{{lab}}} {_fmt(v)}")
                    else:
                        lines.append(f"{fam.name} {_fmt(v)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Full JSON-shape dump (the ``?format=json`` payload)."""
        out = {}
        for fam in self.families():
            if isinstance(fam, Histogram):
                vals = [
                    {"labels": labels,
                     "buckets": {(_fmt(b) if i < len(fam.buckets) else "+Inf"): c
                                 for i, (b, c) in enumerate(
                                     zip([*fam.buckets, float("inf")], cum))},
                     "sum": s, "count": n}
                    for labels, cum, s, n in fam.samples()
                ]
            else:
                vals = [{"labels": labels, "value": v}
                        for labels, v in fam.samples()]
            out[fam.name] = {"type": fam.kind, "help": fam.help, "values": vals}
        return out

    def compact_snapshot(self) -> dict:
        """One-line-JSON-friendly registry block for bench artifacts:
        counters/gauges keep per-child values (labels inlined as
        ``name{k=v}``), histograms compact to ``{count, sum}``."""
        out: dict = {}
        for fam in self.families():
            if isinstance(fam, Histogram):
                for labels, _cum, s, n in fam.samples():
                    out[_flat_name(fam.name, labels)] = {
                        "count": n, "sum": round(s, 6)
                    }
            else:
                for labels, v in fam.samples():
                    out[_flat_name(fam.name, labels)] = (
                        int(v) if float(v).is_integer() else round(v, 6)
                    )
        return out

    def reset(self) -> None:
        """Drop every family (tests/bench phase isolation)."""
        with self._lock:
            self._families.clear()


def _flat_name(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def render_snapshot(snap: dict) -> str:
    """Prometheus text exposition of a :meth:`MetricsRegistry.snapshot`-shaped
    dict. The pod-federation path (cluster/federation.py) merges per-rank
    snapshots into one dict that lives in no registry — this renders it with
    the exact same escaping/formatting rules as :meth:`to_prometheus`."""
    lines: list[str] = []
    for name in sorted(snap):
        fam = snap[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam.get('type', 'untyped')}")
        for val in fam.get("values", ()):
            base = [f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(val.get("labels", {}).items())]
            if "buckets" in val:
                for le, c in val["buckets"].items():
                    lab = ",".join(base + [f'le="{le}"'])
                    lines.append(f"{name}_bucket{{{lab}}} {_fmt(c)}")
                suffix = "{" + ",".join(base) + "}" if base else ""
                lines.append(f"{name}_sum{suffix} {_fmt(val['sum'])}")
                lines.append(f"{name}_count{suffix} {_fmt(val['count'])}")
            elif base:
                lines.append(f"{name}{{{','.join(base)}}} {_fmt(val['value'])}")
            else:
                lines.append(f"{name} {_fmt(val['value'])}")
    return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "", always: bool = False) -> Counter:
    return REGISTRY.counter(name, help, always)


def gauge(name: str, help: str = "", always: bool = False) -> Gauge:
    return REGISTRY.gauge(name, help, always)


def histogram(name: str, help: str = "", buckets=None,
              always: bool = False) -> Histogram:
    return REGISTRY.histogram(name, help, buckets, always)


def counter_value(name: str, **labels) -> float:
    """Registry read without create-on-miss (0.0 for unknown families)."""
    fam = REGISTRY._families.get(name)
    return fam.value(**labels) if isinstance(fam, (Counter, Gauge)) else 0.0


# ---------------------------------------------------------------------------
# spans

# trace id (the owning Job's key) and active span id flow through
# contextvars: Job.start copies the creator's context into the worker
# thread, so spans opened anywhere inside the job body nest under it, while
# unrelated REST threads stay untraced.
_TRACE_VAR: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "h2o3_trace", default=None
)
_SPAN_VAR: contextvars.ContextVar[int | None] = contextvars.ContextVar(
    "h2o3_span", default=None
)

_TRACE_KIND_VAR: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "h2o3_trace_kind", default=None
)

_IDS = itertools.count(1)

_MAX_TRACES = 128
_MAX_SPANS_PER_TRACE = 4096
_TRACE_LOCK = threading.Lock()
_TRACES: "collections.OrderedDict[str, list[dict]]" = collections.OrderedDict()
_RECENT: collections.deque = collections.deque(maxlen=1024)

_SPAN_SECONDS = histogram(
    "span_seconds", "wall time of named spans (the trace tree's histogram view)"
)


@contextlib.contextmanager
def trace(trace_id: str, kind: str = "job"):
    """Enter a trace scope (Job.start does this with the job key; the REST
    server with a per-request id and ``kind="request"``). Joins an
    already-active JOB trace instead of replacing it: a Job nested inside a
    replicated command (spmd _exec_build's inner Job) contributes its spans
    to the OUTER job's trace — the one the client is polling. A job entered
    under a REQUEST trace is the opposite case: the job outlives the
    request and is polled by its own key, so a ``kind="job"`` trace SHADOWS
    an active request trace (the POST that launched a 10-minute build must
    not be charged the build's device-seconds).

    NOT gated by H2O3_TPU_METRICS: the trace id is the attribution key the
    flight-recorder ring and the per-job ledger (utils/jobacct.py) stamp on
    every dispatch, and those run in every process all the time. The gate
    only controls whether :func:`span` RECORDS into the registry."""
    if _TRACE_VAR.get() is not None and not (
        kind == "job" and _TRACE_KIND_VAR.get() == "request"
    ):
        yield
        return
    token = _TRACE_VAR.set(str(trace_id))
    ktoken = _TRACE_KIND_VAR.set(kind)
    # a NEW trace roots its own span tree: clear any span inherited from
    # the shadowed scope (a job thread copies the launching request's
    # contextvars — without this the job's root span would parent under
    # the request's rest.request span, a node in a DIFFERENT trace)
    stoken = _SPAN_VAR.set(None)
    try:
        yield
    finally:
        _SPAN_VAR.reset(stoken)
        _TRACE_VAR.reset(token)
        _TRACE_KIND_VAR.reset(ktoken)


def current_trace() -> str | None:
    return _TRACE_VAR.get()


def current_span() -> int | None:
    """Active span id (None outside any span) — the parent the flight
    recorder links its dispatch events under."""
    return _SPAN_VAR.get()


def next_span_id() -> int:
    """Allocate a span id from the shared sequence. The ring's dispatch
    spans and the registry spans draw from ONE counter so a trace tree
    mixing both never collides."""
    return next(_IDS)


def push_span(sid: int):
    """Make ``sid`` the active span (returns the reset token). The flight
    recorder's dispatch context manager uses this so nested dispatches —
    and registry spans opened inside one — parent correctly even under
    H2O3_TPU_METRICS=0."""
    return _SPAN_VAR.set(sid)


def pop_span(token) -> None:
    _SPAN_VAR.reset(token)


def _record_span(ev: dict) -> None:
    _RECENT.append(ev)
    tid = ev["trace"]
    if tid is None:
        return
    with _TRACE_LOCK:
        spans = _TRACES.get(tid)
        if spans is None:
            while len(_TRACES) >= _MAX_TRACES:
                _TRACES.popitem(last=False)
            spans = _TRACES[tid] = []
        if len(spans) < _MAX_SPANS_PER_TRACE:
            spans.append(ev)


@contextlib.contextmanager
def span(name: str, **labels):
    """Time a named region. Nests under the active span/trace; on exit the
    completed span is recorded into the trace tree, the recent ring (merged
    into /3/Timeline) and the ``span_seconds`` histogram."""
    if not _ENABLED:
        yield None
        return
    sid = next(_IDS)
    parent = _SPAN_VAR.get()
    token = _SPAN_VAR.set(sid)
    ts = time.time()
    t0 = time.perf_counter()
    try:
        yield sid
    finally:
        dur = time.perf_counter() - t0
        _SPAN_VAR.reset(token)
        _record_span({
            "name": name,
            "trace": _TRACE_VAR.get(),
            "id": sid,
            "parent": parent,
            "ts": ts,
            "dur_s": dur,
            "thread": threading.get_ident(),
            "labels": {k: str(v) for k, v in labels.items()},
        })
        _SPAN_SECONDS.observe(dur, name=name)


def trace_events(trace_id: str) -> list[dict]:
    with _TRACE_LOCK:
        return list(_TRACES.get(str(trace_id), ()))


def trace_summary(trace_id: str) -> dict:
    """Per-span-name {count, total_ms} rollup — the Job dict's phase
    summary (stable once the job has finished: no new spans arrive)."""
    out: dict[str, dict] = {}
    for ev in trace_events(trace_id):
        agg = out.setdefault(ev["name"], {"count": 0, "total_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += ev["dur_s"] * 1e3
    for agg in out.values():
        agg["total_ms"] = round(agg["total_ms"], 3)
    return out


def chrome_trace(trace_id: str) -> dict:
    """Chrome-trace/Perfetto JSON for one trace (``GET /3/Jobs/{key}/trace``).
    Complete events ("ph": "X") carry span/parent ids in args so the tree
    reconstructs exactly even when sibling spans share a thread lane."""
    evs = [{"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
            "args": {"name": "h2o3_tpu coordinator"}}]
    for s in trace_events(trace_id):
        evs.append({
            "name": s["name"],
            "ph": "X",
            "ts": s["ts"] * 1e6,          # Chrome trace wants microseconds
            "dur": max(s["dur_s"] * 1e6, 1.0),
            "pid": 1,
            "tid": s["thread"] % 1_000_000,
            "args": {"span_id": s["id"], "parent_id": s["parent"],
                     **s["labels"]},
        })
    return {"traceEvents": evs, "displayTimeUnit": "ms",
            "otherData": {"trace": str(trace_id)}}


def recent_spans(n: int = 200) -> list[dict]:
    """Most recent completed spans across ALL traces (the /3/Timeline merge
    source)."""
    return list(_RECENT)[-n:]


def reset_spans() -> None:
    """Drop all recorded spans/traces (tests)."""
    with _TRACE_LOCK:
        _TRACES.clear()
    _RECENT.clear()
