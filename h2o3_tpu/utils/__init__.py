from h2o3_tpu.utils.log import Log
from h2o3_tpu.utils.timer import Timer

__all__ = ["Log", "Timer"]
