"""Overload-survival plane — memory-aware admission, OOM auto-degrade and
the dispatch hang watchdog (the ISSUE-19 tentpole).

After PR 10 (crash self-healing) and PR 17 (elastic resume) the stack only
survives failures it *didn't cause*: ``devmem.headroom()`` publishes a
measured HBM budget but nothing consults it, a real ``XlaRuntimeError
RESOURCE_EXHAUSTED`` at a dispatch site is an unclassified fatal error, and
a wedged dispatch hangs a job forever with no detection. This module is the
policy layer that turns those signals into survival decisions — the
multi-tenant prerequisite ROADMAP item 3 names ("one tenant's OOM or poison
step cannot take the pod down"):

- **Footprint model** (:func:`per_row_device_bytes`,
  :func:`estimate_build_bytes`): the ``tools/tpu_mem_analysis.py`` capacity
  math, shared so the admission preflight and the offline model agree —
  resident tree builds cost ``C*4 + C + 24`` bytes/row (f32 columns +
  bins_u8 + per-row f32 state lanes), compressed builds ``C + 24``, GLM
  ``(P+3)*4``, DL ``(d+2)*4 + 8``.
- **Memory-aware admission** (:func:`admit` / :func:`Shed` /
  :func:`job_scope`): a job whose estimated footprint fits the usable share
  of measured headroom takes a reservation in the devmem reserve/release
  ledger (``hbm_reserved_bytes{job}``) and runs resident; one that doesn't
  fit resident is routed to the streamed lane (``ChunkStore.plan`` consults
  :func:`plan_window`); one that fits nowhere is shed with a Retry-After
  computed from the reservation queue (:func:`retry_after_estimate`) —
  never a hardcoded constant.
- **OOM catch-and-degrade**: the flightrec-wrapped dispatch sites report
  errors here (:func:`note_dispatch_error`) — a RESOURCE_EXHAUSTED is
  classified (:func:`is_oom`), an incident bundle freezes the evidence, and
  ``recovery.run_supervised`` retries the job ONCE under
  :func:`degrade_scope` (streamed mode / a halved ChunkStore window —
  :func:`plan_window` reads the scope). ``oom_degrades_total{site,outcome}``
  counts retried/recovered/exhausted; deterministic errors never retry.
- **Dispatch hang watchdog** (:func:`install_watchdog` /
  :func:`watchdog_pass`): a background thread walks the flight-recorder
  ring for dispatches open longer than ``H2O3_TPU_HANG_FACTOR`` × their
  site's rolling duration baseline (floored at ``H2O3_TPU_HANG_MIN_SECS``
  so a legitimately long first compile never false-trips), trips
  ``dispatch_hangs_total{site}``, captures an incident, latches
  ``cloud.mark_degraded`` so the PR-10 supervisor/fencing takes over, and
  flags the site in the ``dispatch_hung{site}`` gauge — which the pod
  federation scrape rank-labels, so the lagging rank of a multi-process
  pod is readable from the coordinator. A tripped dispatch that later
  unwedges fail-stops at its own exit (flightrec consults the hung-span
  set): its result belongs to a formation the supervisor already gave up
  on, and raising there is what hands the job to ``run_supervised``.

``H2O3_TPU_OVERLOAD=0`` disables the whole plane and pins today's behavior
bit-for-bit: no admission routing, no reservations, no OOM retry, no
watchdog trips, and the REST shed responses keep their historical
Retry-After constants. All metric families here are ``always=True``: shed
and degrade decisions must stay observable under ``H2O3_TPU_METRICS=0``.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import deque

from h2o3_tpu.utils import metrics as _mx

_OOM_DEGRADES = _mx.counter(
    "oom_degrades_total",
    "RESOURCE_EXHAUSTED dispatches handled by the degrade-once supervisor "
    "branch, by site/outcome: retried = the job relaunched once under the "
    "degrade scope (streamed / halved window), recovered = that degraded "
    "relaunch finished, exhausted = a second OOM while already degraded "
    "surfaced to the caller", always=True)
_HANGS = _mx.counter(
    "dispatch_hangs_total",
    "dispatches the hang watchdog declared wedged (open longer than "
    "H2O3_TPU_HANG_FACTOR x the site's rolling duration baseline, floored "
    "at H2O3_TPU_HANG_MIN_SECS), by site — each trip captures an incident "
    "and latches the degraded fail-stop", always=True)
_HUNG = _mx.gauge(
    "dispatch_hung",
    "seconds the oldest overdue open dispatch at a site has been wedged "
    "(0 when the site has none) — on a federated pod scrape the gauge is "
    "rank-labeled, so this series IS the lagging-rank flag", always=True)

# -- capacity model (shared with tools/tpu_mem_analysis.py) ------------------

#: per-row f32 state lanes of a tree build (w/y/F/wy/wh f32 + nid i32)
STATE_BYTES = 24
#: share of HBM the capacity model treats as usable by data (the rest is
#: reserved for compiled programs/temporaries — the 10M-row OOM lesson)
USABLE_FRACTION = 0.70

_GLM_FAMILY = ("glm", "gam", "anovaglm", "modelselection", "coxph", "hglm")


def per_row_device_bytes(ncols: int, algo: str = "gbm",
                         compressed: bool | None = None) -> float:
    """Estimated device bytes per padded row of a build's streamed lanes —
    the ``tools/tpu_mem_analysis.py --oocore`` model, shared so the
    admission preflight and the offline capacity table agree. ``compressed``
    defaults to the live ``H2O3_TPU_FRAME_COMPRESS`` setting."""
    if compressed is None:
        from h2o3_tpu.frame import chunkstore as _cs

        compressed = _cs.compress_on()
    ncols = max(int(ncols), 1)
    a = (algo or "gbm").lower()
    if a in _GLM_FAMILY:
        return (ncols + 3) * 4  # f32 design-matrix row + y/w/eta lanes
    if a == "deeplearning":
        return (ncols + 2) * 4 + 8  # f32 features + y/w + shuffle index
    # tree family and default: bins_u8 codes + per-row f32 state; resident
    # (uncompressed) keeps the f32 columns beside the binned matrix
    return (ncols + STATE_BYTES) if compressed else (ncols * 5 + STATE_BYTES)


def estimate_build_bytes(frame, algo: str = "gbm") -> int:
    """Preflight device-footprint estimate of a build over ``frame``:
    padded rows x the per-row lane model (the response column doesn't join
    the feature lanes, hence ncols - 1)."""
    ncols = max(len(frame.names) - 1, 1)
    return int(frame.npad * per_row_device_bytes(ncols, algo))


# -- gate --------------------------------------------------------------------

def enabled() -> bool:
    """H2O3_TPU_OVERLOAD: '0' disables the whole plane (admission routing,
    reservations, OOM degrade, hang watchdog, computed Retry-After) and
    pins pre-ISSUE-19 behavior bit-for-bit."""
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_OVERLOAD")


def _frac() -> float:
    from h2o3_tpu import config

    try:
        v = config.get_float("H2O3_TPU_ADMIT_HEADROOM_FRAC")
    except (TypeError, ValueError):
        return USABLE_FRACTION
    return min(max(v, 0.05), 1.0)


# -- admission + per-job reservations ----------------------------------------

class Shed(Exception):
    """The job fits nowhere (neither resident nor streamed within the
    usable headroom share): shed it. ``retry_after`` is the reservation-
    queue estimate the REST layer surfaces as the Retry-After header."""

    def __init__(self, msg: str, retry_after: float):
        super().__init__(msg)
        self.retry_after = float(retry_after)


_HOLD_LOCK = threading.Lock()
_HOLDS: deque = deque(maxlen=32)      # completed reservation hold seconds
_STARTED: dict[str, float] = {}        # live reservation -> monotonic start
_SELF_RES: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "h2o3_overload_self_reservation", default=None)


def retry_after_estimate() -> float:
    """Honest Retry-After for a shed response: the mean measured
    reservation hold time of recent jobs (5 s prior before any completes)
    scaled by the live reservation-queue depth — a deeper queue means a
    longer wait — clamped to [1, 120] seconds."""
    from h2o3_tpu.utils import devmem as _dm

    with _HOLD_LOCK:
        avg = (sum(_HOLDS) / len(_HOLDS)) if _HOLDS else 5.0
    depth = max(len(_dm.reservations()), 1)
    return float(max(1.0, min(120.0, avg * depth)))


def admit(key: str, need_bytes: int, algo: str = "") -> str:
    """Admission decision for a job with an estimated device footprint:

    - ``"resident"`` — fits the usable headroom share net of other jobs'
      reservations; a reservation for the full footprint is taken.
    - ``"streamed"`` — doesn't fit resident but a streamed window does;
      the reservation covers the window share and ``ChunkStore.plan``
      (via :func:`plan_window`) picks the matching geometry at build time.
    - raises :class:`Shed` when it fits nowhere.
    - ``"off"`` — plane disabled; no reservation, no routing.

    On backends whose devices report no ``memory_stats`` (the CPU proxy)
    headroom is unmeasured: the job is admitted resident but STILL takes
    its reservation, so ``hbm_reserved_bytes{job}`` and the hold-time
    estimator keep working everywhere. Release with :func:`finish` (the
    :func:`job_scope` context does it for you)."""
    if not enabled():
        return "off"
    from h2o3_tpu.frame import chunkstore as _cs
    from h2o3_tpu.utils import devmem as _dm

    need = max(int(need_bytes), 0)
    head = _dm.headroom()
    if head is None:
        _reserve(key, need)
        return "resident"
    avail = max(head * _frac() - _dm.reserved_total(), 0.0)
    if need <= avail:
        _reserve(key, need)
        return "resident"
    if _cs.compress_on():
        win = int(avail)
        if win >= _min_window_bytes():
            _reserve(key, win)
            return "streamed"
    raise Shed(
        f"insufficient device memory: estimated footprint {need} B "
        f"({algo or 'job'}) exceeds the usable headroom share "
        f"({int(avail)} B of {int(head)} B measured headroom, "
        f"H2O3_TPU_ADMIT_HEADROOM_FRAC={_frac()}) and no streamed window "
        "fits; retry after reserved HBM frees",
        retry_after_estimate())


def _reserve(key: str, nbytes: int) -> None:
    from h2o3_tpu.utils import devmem as _dm

    _dm.reserve(key, nbytes)
    with _HOLD_LOCK:
        _STARTED[key] = time.monotonic()


def finish(key: str) -> None:
    """Release a job's reservation and feed its measured hold time into the
    Retry-After estimator. Idempotent; safe for never-reserved keys."""
    from h2o3_tpu.utils import devmem as _dm

    _dm.release(key)
    with _HOLD_LOCK:
        t0 = _STARTED.pop(key, None)
        if t0 is not None:
            _HOLDS.append(time.monotonic() - t0)


@contextlib.contextmanager
def job_scope(key: str):
    """Run a job's work under its reservation identity: ``plan_window``
    excludes the job's OWN reservation from the headroom math (a resident
    admission must not push itself to the streamed lane), and the
    reservation releases on exit whatever the outcome."""
    tok = _SELF_RES.set(key)
    try:
        yield
    finally:
        _SELF_RES.reset(tok)
        finish(key)


# -- streamed-lane routing (ChunkStore.plan consults this) -------------------

_DEGRADE: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "h2o3_overload_degrade", default=False)


@contextlib.contextmanager
def degrade_scope():
    """Scope a degraded relaunch: ``plan_window`` halves the streamed
    window (or forces a previously-resident frame to stream through half
    its footprint) for every ``ChunkStore.plan`` under the scope."""
    tok = _DEGRADE.set(True)
    try:
        yield
    finally:
        _DEGRADE.reset(tok)


def degrade_active() -> bool:
    return bool(_DEGRADE.get())


def _min_window_bytes() -> int:
    """Smallest window worth streaming through: one quantum block of the
    cheapest lane is meaningless — require a few MiB so block geometry has
    room to double-buffer."""
    return 4 << 20


def plan_window(need_bytes: float, static_window: int) -> int | None:
    """The overload plane's window override for ``ChunkStore.plan``:

    - under :func:`degrade_scope`: half the static window when the frame
      was already streaming, else half the frame's own footprint (forces
      the streamed lane) — the OOM degrade-once geometry;
    - otherwise, with NO static window configured: when the lanes exceed
      the usable share of measured headroom (net of OTHER jobs'
      reservations), a headroom-derived window — the auto-route that sends
      too-big-for-resident jobs down the streamed lane;
    - None everywhere else (plane disabled, operator window wins, frame
      fits, headroom unmeasured): the legacy static-knob policy applies.
    """
    if not enabled():
        return None
    need = max(int(need_bytes), 1)
    if _DEGRADE.get():
        base = static_window if (static_window and need > static_window) \
            else need
        return max(int(base) // 2, 1)
    if static_window:
        return None
    from h2o3_tpu.utils import devmem as _dm

    head = _dm.headroom()
    if head is None:
        return None
    own = _SELF_RES.get()
    res = _dm.reservations()
    others = sum(v for k, v in res.items() if k != own)
    avail = max(head * _frac() - others, 0.0)
    if need <= avail:
        return None
    win = int(avail)
    return win if win >= _min_window_bytes() else _min_window_bytes()


# -- OOM classification ------------------------------------------------------

_OOM_MARKS = ("resource_exhausted", "out of memory")
_OOM_LOCK = threading.Lock()
_last_oom: tuple[float, str] | None = None  # (monotonic, site)


def is_oom(exc: BaseException) -> bool:
    """True when the exception carries an XLA RESOURCE_EXHAUSTED signature
    (matched on repr+str like the death signatures: Job.join re-wraps
    worker exceptions with their traceback text)."""
    msg = (repr(exc) + " " + str(exc)).lower()
    return any(m in msg for m in _OOM_MARKS)


def note_dispatch_error(site: str, exc: BaseException) -> None:
    """Called by ``flightrec._Dispatch.__exit__`` on every failed dispatch:
    a RESOURCE_EXHAUSTED is classified, stamped into the ring and frozen
    into an incident bundle naming the OOM dispatch site — BEFORE any
    retry/unwind discards the dying state. Never raises."""
    global _last_oom
    try:
        if not enabled() or not is_oom(exc):
            return
        from h2o3_tpu.utils import flightrec as _fr

        with _OOM_LOCK:
            _last_oom = (time.monotonic(), site)
        _fr.record("oom", site=site, error=type(exc).__name__)
        _fr.capture_incident(
            f"RESOURCE_EXHAUSTED at dispatch site {site!r}: "
            f"{type(exc).__name__}: {exc}", trigger="oom")
    except Exception:  # noqa: BLE001 — telemetry must never mask the OOM
        pass


def oom_site(exc: BaseException, max_age: float = 600.0) -> str | None:
    """The dispatch site behind an OOM exception (None when ``exc`` is not
    an OOM or the plane is disabled): the site the flight recorder noted
    within ``max_age`` seconds, else ``"unknown"`` — an OOM raised outside
    any instrumented dispatch still degrades."""
    if not enabled() or not is_oom(exc):
        return None
    with _OOM_LOCK:
        if _last_oom and time.monotonic() - _last_oom[0] <= max_age:
            return _last_oom[1]
    return "unknown"


def count_degrade(site: str, outcome: str) -> None:
    _OOM_DEGRADES.inc(site=site, outcome=outcome)


# -- dispatch hang watchdog --------------------------------------------------

def _hang_factor() -> float:
    from h2o3_tpu import config

    try:
        return max(config.get_float("H2O3_TPU_HANG_FACTOR"), 1.0)
    except (TypeError, ValueError):
        return 8.0


def _hang_min_secs() -> float:
    from h2o3_tpu import config

    try:
        return max(config.get_float("H2O3_TPU_HANG_MIN_SECS"), 0.0)
    except (TypeError, ValueError):
        return 120.0


def _hang_poll_secs() -> float:
    from h2o3_tpu import config

    try:
        return max(config.get_float("H2O3_TPU_HANG_POLL_SECS"), 0.1)
    except (TypeError, ValueError):
        return 2.0


#: minimum completed dispatches at a site before its rolling mean is
#: trusted over the floor — the first dispatch of a program includes its
#: compile, and Nx a tiny warm baseline would false-trip it
_BASELINE_MIN_SAMPLES = 3

_WD_LOCK = threading.Lock()
_tripped_spans: set = set()
_flagged_sites: set[str] = set()


def watchdog_pass(now: float | None = None) -> list[dict]:
    """One ring walk: find dispatches open longer than their budget
    (``max(H2O3_TPU_HANG_FACTOR x site rolling mean, H2O3_TPU_HANG_MIN_SECS)``;
    floor-only for sites with < 3 completed dispatches — the long-first-
    compile guard) and trip each once: ``dispatch_hangs_total{site}``, an
    incident bundle, the degraded latch, the ``dispatch_hung{site}`` gauge,
    and the span lands in the flightrec hung-span set so the dispatch
    fail-stops at its own exit if it ever unwedges. ``now`` is injectable
    for tests. Returns the trips made this pass."""
    if not enabled():
        return []
    from h2o3_tpu.utils import flightrec as _fr

    evs = _fr.events()
    if now is None:
        now = time.time()
    durs: dict[str, list[float]] = {}
    open_spans: dict = {}
    for e in evs:
        kind = e["kind"]
        if kind == "dispatch_start":
            if e.get("span") is not None:
                open_spans[e["span"]] = e
        elif kind == "dispatch_end":
            open_spans.pop(e.get("span"), None)
            if "error" not in e:
                durs.setdefault(e.get("site", "?"), []).append(
                    float(e.get("dur_ms") or 0.0) / 1e3)
    factor, floor = _hang_factor(), _hang_min_secs()
    trips: list[dict] = []
    overdue_sites: set[str] = set()
    for span, e in open_spans.items():
        site = e.get("site", "?")
        age = now - float(e["ts"])
        d = durs.get(site, ())
        budget = floor if len(d) < _BASELINE_MIN_SAMPLES else max(
            factor * (sum(d) / len(d)), floor)
        if budget <= 0 or age <= budget:
            continue
        overdue_sites.add(site)
        _HUNG.set(round(age, 3), site=site)
        with _WD_LOCK:
            if span in _tripped_spans:
                continue
            _tripped_spans.add(span)
            # bound the trip memory to what the ring can still show
            if len(_tripped_spans) > 4 * max(len(open_spans), 64):
                _tripped_spans.intersection_update(open_spans)
            _flagged_sites.add(site)
        trips.append({"site": site, "span": span, "age_s": round(age, 3),
                      "budget_s": round(budget, 3)})
        _trip(site, span, age, budget)
    with _WD_LOCK:
        cleared = _flagged_sites - overdue_sites
        _flagged_sites.intersection_update(overdue_sites)
    for site in cleared:
        _HUNG.set(0.0, site=site)
    return trips


def _trip(site: str, span, age: float, budget: float) -> None:
    from h2o3_tpu.cluster import cloud
    from h2o3_tpu.utils import flightrec as _fr
    from h2o3_tpu.utils.log import Log

    reason = (f"dispatch hang: site {site!r} open {age:.1f}s > budget "
              f"{budget:.1f}s (H2O3_TPU_HANG_FACTOR x rolling baseline, "
              f"floored at H2O3_TPU_HANG_MIN_SECS) — span {span} declared "
              "wedged")
    _HANGS.inc(site=site)
    _fr.record("watchdog_trip", site=site, span=span,
               age_s=round(age, 3), budget_s=round(budget, 3))
    _fr.mark_span_hung(span)
    Log.err(reason)
    # incident first (dedups with the latch capture), then the latch: the
    # ring still holds the wedged dispatch_start when the bundle freezes
    _fr.capture_incident(reason, trigger="hang")
    cloud.mark_degraded(reason)


_WD_THREAD: threading.Thread | None = None
_WD_STOP = threading.Event()


def _wd_loop() -> None:
    while not _WD_STOP.wait(_hang_poll_secs()):
        try:
            watchdog_pass()
        except Exception:  # noqa: BLE001 — the watchdog must never die loud
            pass


def install_watchdog() -> None:
    """Start the background hang watchdog (idempotent; daemon). start_server
    and launch.py install it on the coordinator; each pass no-ops while the
    plane is disabled, so installing is always safe."""
    global _WD_THREAD
    if _WD_THREAD is not None and _WD_THREAD.is_alive():
        return
    _WD_STOP.clear()
    _WD_THREAD = threading.Thread(
        target=_wd_loop, name="h2o3-hang-watchdog", daemon=True)
    _WD_THREAD.start()


def uninstall_watchdog() -> None:
    """Stop the background watchdog (tests)."""
    global _WD_THREAD
    _WD_STOP.set()
    if _WD_THREAD is not None:
        _WD_THREAD.join(timeout=5)
    _WD_THREAD = None


def _reset_for_tests() -> None:
    global _last_oom
    with _WD_LOCK:
        _tripped_spans.clear()
        _flagged_sites.clear()
    with _OOM_LOCK:
        _last_oom = None
    with _HOLD_LOCK:
        _HOLDS.clear()
        _STARTED.clear()
