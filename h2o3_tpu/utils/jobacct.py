"""Per-job resource ledger — job/request-scoped accounting on top of the
flight recorder's plane-level attribution (the ISSUE-18 tentpole, piece b).

PR 13 answered "which *plane* spent it": ``hbm_owned_bytes{owner}`` knows
"frame_window" holds bytes, ``dispatch_device_seconds{site}`` knows "tree"
burned device time. This module answers "which *job*": every dispatch,
collective tally, ChunkStore window upload and batcher queue-wait that runs
under a trace (``metrics.trace`` — entered by ``Job.start`` with the job
key, and by the REST server with a per-request id) accumulates into a
bounded per-job ledger keyed by that trace id.

The ledger is the measured budget signal the multi-tenant scheduler
(ROADMAP item 3) will enforce against, so it accumulates **always** —
including under ``H2O3_TPU_METRICS=0`` — exactly like the flight-recorder
ring it rides on. Publication is two-channel:

- registry families ``job_device_seconds{job}`` / ``job_hbm_bytes{job}``
  (gauges mirroring the ledger totals; LRU-evicted children are removed so
  cardinality stays bounded at :data:`_MAX_JOBS`) and the unlabeled
  ``job_queue_wait_seconds`` histogram — these follow the normal
  ``H2O3_TPU_METRICS`` gate;
- :func:`snapshot` — the raw dict embedded in every ``/3/Jobs`` entry and
  in bench.py's per-phase artifact block, gate or no gate.

Hot-path budget: one lock + dict update per dispatch — same order as the
``dispatch_device_seconds`` histogram observe that already runs at every
site. Call sites pass the trace id they already read for ring stamping, so
no extra contextvar lookups happen here.
"""

from __future__ import annotations

import collections
import threading

from h2o3_tpu.utils import metrics as _mx

# LRU bound on tracked jobs: grid/AutoML runs launch hundreds of child jobs
# per session; the scheduler only needs the live ones and /3/Jobs only shows
# recent ones. Evicting a job drops its registry children too.
_MAX_JOBS = 128

_JOB_DEVICE_SECONDS = _mx.gauge(
    "job_device_seconds",
    "device-dispatch wall seconds attributed to each live job/request "
    "trace (sum over that job's dispatch spans; LRU-bounded cardinality)")
_JOB_HBM_BYTES = _mx.gauge(
    "job_hbm_bytes",
    "ChunkStore window bytes uploaded on behalf of each live job trace "
    "(frame_window plane, attributed per job; LRU-bounded cardinality)")
_JOB_QUEUE_WAIT = _mx.histogram(
    "job_queue_wait_seconds",
    "per-request wait between batcher submit and dispatch start — the "
    "queue-wait leg of the request span tree (unlabeled: one histogram "
    "across all models, the batch-window tuning input)")

_LOCK = threading.Lock()
_LEDGERS: "collections.OrderedDict[str, dict]" = collections.OrderedDict()


def _ledger(job: str) -> dict:
    """Get-or-create under _LOCK; touches LRU order and evicts past the
    bound (registry children of evicted jobs are removed)."""
    led = _LEDGERS.get(job)
    if led is None:
        while len(_LEDGERS) >= _MAX_JOBS:
            old, _ = _LEDGERS.popitem(last=False)
            _JOB_DEVICE_SECONDS.remove(job=old)
            _JOB_HBM_BYTES.remove(job=old)
        led = _LEDGERS[job] = {
            "device_seconds": 0.0,
            "dispatches": {},        # site -> count
            "collective_bytes": {},  # lane -> bytes (exact/quantized/...)
            "window_bytes": 0,
            "queue_wait_seconds": 0.0,
            "queue_waits": 0,
        }
    else:
        _LEDGERS.move_to_end(job)
    return led


def on_dispatch(job: str | None, site: str, dur_s: float) -> None:
    """One device dispatch ran for ``dur_s`` under ``job``'s trace. Called
    by flightrec._Dispatch.__exit__ with the trace id it already stamped
    into the ring (None outside any trace → unattributed, not ledgered)."""
    if not job:
        return
    with _LOCK:
        led = _ledger(job)
        led["device_seconds"] += dur_s
        led["dispatches"][site] = led["dispatches"].get(site, 0) + 1
        total = led["device_seconds"]
    _JOB_DEVICE_SECONDS.set(total, job=job)


def on_collective_bytes(job: str | None, nbytes: float,
                        lane: str = "exact") -> None:
    """Collective wire bytes moved for ``job`` (lane-split, same lanes as
    ``tree_collective_bytes_total``: exact intra-host vs quantized DCN)."""
    if not job or nbytes <= 0:
        return
    with _LOCK:
        led = _ledger(job)
        led["collective_bytes"][lane] = (
            led["collective_bytes"].get(lane, 0) + int(nbytes))


def on_window_bytes(job: str | None, nbytes: int) -> None:
    """ChunkStore uploaded ``nbytes`` into the device window for ``job``."""
    if not job or nbytes <= 0:
        return
    with _LOCK:
        led = _ledger(job)
        led["window_bytes"] += int(nbytes)
        total = led["window_bytes"]
    _JOB_HBM_BYTES.set(total, job=job)


def on_queue_wait(job: str | None, seconds: float) -> None:
    """One request spent ``seconds`` queued in the batcher before its batch
    dispatched. Observed into the histogram even without a trace (the
    latency curve wants every request); ledgered only under one."""
    _JOB_QUEUE_WAIT.observe(max(seconds, 0.0))
    if not job:
        return
    with _LOCK:
        led = _ledger(job)
        led["queue_wait_seconds"] += max(seconds, 0.0)
        led["queue_waits"] += 1


def snapshot(job: str) -> dict | None:
    """Ledger dict for one job (None if never traced / already evicted).
    Embedded in the job's ``/3/Jobs`` entry and bench phase artifacts."""
    with _LOCK:
        led = _LEDGERS.get(job)
        if led is None:
            return None
        return {
            "device_seconds": round(led["device_seconds"], 6),
            "dispatches": dict(led["dispatches"]),
            "collective_bytes": dict(led["collective_bytes"]),
            "window_bytes": led["window_bytes"],
            "queue_wait_seconds": round(led["queue_wait_seconds"], 6),
            "queue_waits": led["queue_waits"],
        }


def all_jobs() -> dict[str, dict]:
    """{job_id: ledger} for every tracked job, LRU order (oldest first)."""
    with _LOCK:
        keys = list(_LEDGERS)
    out = {}
    for k in keys:
        snap = snapshot(k)
        if snap is not None:
            out[k] = snap
    return out


def reset() -> None:
    """Drop every ledger and its registry children (tests/bench phases)."""
    with _LOCK:
        keys = list(_LEDGERS)
        _LEDGERS.clear()
    for k in keys:
        _JOB_DEVICE_SECONDS.remove(job=k)
        _JOB_HBM_BYTES.remove(job=k)
