"""Incident flight recorder — an always-on bounded ring of structured
events + automatic incident bundles (the ISSUE-13 tentpole, pieces 2–3).

The PR-10 self-healing cloud retries past failures but used to discard
exactly the evidence a postmortem needs: what the dead generation was
dispatching when the latch tripped. This module keeps the last
``H2O3_TPU_FLIGHTREC_SIZE`` events in a preallocated ring whose append is
O(µs) and lock-free (one atomic counter bump + one list-slot store — safe
under the GIL; readers snapshot and sort by sequence number), so it runs in
EVERY process all the time, including ``H2O3_TPU_METRICS=0``:

- program dispatch start/end with program key + shape bucket + mesh key
  (the cached-program key carries all three) via :func:`dispatch`, which
  also feeds the ``dispatch_device_seconds{site}`` histogram — measured
  device-time attribution per hot site (tree chunk, IRLS chunk, DL chunk,
  serving batch, stream block), cross-referenceable by timestamp with
  ``tools/profile_train_stages.py`` and the ``jax.profiler`` wrapper
  (utils/telemetry.py stamps ``profiler`` events into the same ring);
- collective phase tallies (per-dispatch byte totals, models/tree);
- stream-block fetch/evict (frame/chunkstore.py), serving
  page-in/eviction (serving/residency.py);
- generation ticks, degraded latches, watchdog trips (cluster/*).

**Incident bundles**: :func:`capture_incident` freezes the evidence — ring
dump + metrics registry snapshot + devmem attribution state + the log tail
— into one JSON file written atomically through persist (temp-file +
``os.replace``; survives a crash mid-write) under
``H2O3_TPU_INCIDENT_DIR``. ``cloud.mark_degraded`` captures at the latch
(the watchdog/death-signature instant — the ring still holds the dying
dispatch), ``recovery.reform`` captures before any reform/retry, and the
supervised-restart loop surfaces the bundle path in the job's recovery
block. Captures dedup per degraded episode (same cloud generation within
:data:`_DEDUP_SECS`) so a failure storm writes one bundle, not hundreds.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time

from h2o3_tpu import config as _config
from h2o3_tpu.utils import faults as _faults
from h2o3_tpu.utils import jobacct as _jobacct
from h2o3_tpu.utils import metrics as _mx

_DISPATCH_SECONDS = _mx.histogram(
    "dispatch_device_seconds",
    "wall seconds inside hot device-dispatch sites, by site (tree = fused "
    "tree/level programs, irls_chunk = fused GLM chunk, dl_chunk = DL "
    "epoch-chunk program, serving_batch = batched scorer dispatch, "
    "stream_block = out-of-core per-block compute). Host wall of the "
    "dispatch call: on the synchronous proxy/tunnel paths this IS device "
    "time; async residue attributes to the site that syncs")
_INCIDENTS = _mx.counter(
    "incident_bundles_total",
    "incident bundles written (ring dump + metrics + devmem + log tail), "
    "by trigger", always=True)

# ring size is read ONCE at import (like H2O3_TPU_METRICS): the append is
# the hot path and must not re-read the environment. 0 disables the ring.
try:
    _SIZE = max(int(_config.get("H2O3_TPU_FLIGHTREC_SIZE")), 0)
except (TypeError, ValueError):
    _SIZE = 4096

_RING: list = [None] * _SIZE
_SEQ = itertools.count()
_last_seq = -1  # advisory high-water for status(); exact value via events()


def record(kind: str, **fields) -> None:
    """Append one structured event. O(µs), no locks: one atomic counter
    bump + one slot store (field values should be JSON-safe scalars)."""
    global _last_seq
    if not _SIZE:
        return
    i = next(_SEQ)
    _RING[i % _SIZE] = (i, time.time(), kind, fields)
    _last_seq = i


def events(n: int | None = None, kind: str | None = None) -> list[dict]:
    """Snapshot of the ring, oldest→newest (sorted by sequence number;
    torn slots from concurrent appends simply reflect whichever event won
    the slot). ``kind`` filters; ``n`` keeps the newest n."""
    snap = [e for e in list(_RING) if e is not None]
    snap.sort(key=lambda e: e[0])
    out = [
        {"seq": s, "ts": ts, "kind": k, **f}
        for s, ts, k, f in snap
        if kind is None or k == kind
    ]
    return out[-n:] if n else out


def ring_status() -> dict:
    nxt = _last_seq + 1
    return {
        "size": _SIZE,
        "next_seq": nxt,
        "dropped": max(nxt - _SIZE, 0),
    }


def trace_export(trace: str | None = None, n: int | None = None) -> dict:
    """Chrome/Perfetto trace JSON of the ring (``GET
    /3/FlightRecorder?format=trace``; tools/trace_report.py renders the
    same shape from an incident bundle). One lane per trace id:
    ``dispatch_end`` events — which carry the measured duration plus
    trace/span/parent ids — render as complete ("X") spans positioned at
    end-timestamp minus duration; every other ring kind (chunk_fetch,
    queue_wait, collectives, …) renders as an instant event on its trace's
    lane; ``profiler_start``/``profiler_end`` pairs render the xplane
    capture window on a dedicated lane, so which dispatches the profiler
    saw is readable by timestamp overlap. Registry spans of the exported
    traces (the "job" / "rest.request" parents) merge onto the same lanes,
    completing the span tree Perfetto shows."""
    return render_trace(events(n=n), trace=trace,
                        span_fetch=_mx.trace_events)


def render_trace(evs: list[dict], trace: str | None = None,
                 span_fetch=None) -> dict:
    """Render a list of ring-shaped events (live ring or an incident
    bundle's ``events``) as Chrome/Perfetto trace JSON. ``span_fetch``
    (trace_id -> registry span list) merges in-process registry spans —
    pass None when rendering a bundle, whose registry spans are gone."""
    if trace is not None:
        trace = str(trace)
        evs = [e for e in evs if e.get("trace") == trace
               or e["kind"] in ("profiler_start", "profiler_end")]
    out: list[dict] = [{"name": "process_name", "ph": "M", "pid": 1,
                        "tid": 0, "args": {"name": "h2o3_tpu flight recorder"}}]
    lanes: dict[str, int] = {}

    def lane(tr) -> int:
        key = tr if tr else "(untraced)"
        tid = lanes.get(key)
        if tid is None:
            tid = lanes[key] = len(lanes) + 1
        return tid

    if trace is not None:
        lane(trace)  # registry-only traces still get their lane
    prof_open: dict[str, float] = {}
    for e in evs:
        kind = e["kind"]
        args = {k: v for k, v in e.items()
                if k not in ("ts", "kind") and v is not None}
        if kind == "dispatch_start":
            continue  # the matching dispatch_end carries the measured span
        if kind == "dispatch_end" or "dur_ms" in e:
            # duration-carrying events (dispatch_end, the batcher's
            # queue_wait, …) render as complete spans anchored at their
            # end timestamp minus the measured duration
            dur_s = float(e.get("dur_ms") or 0.0) / 1e3
            name = (f"dispatch:{e.get('site', '?')}"
                    if kind == "dispatch_end" else kind)
            out.append({"name": name, "ph": "X",
                        "ts": (e["ts"] - dur_s) * 1e6,
                        "dur": max(dur_s * 1e6, 1.0),
                        "pid": 1, "tid": lane(e.get("trace")), "args": args})
        elif kind == "profiler_start":
            prof_open[str(e.get("logdir") or "")] = e["ts"]
        elif kind == "profiler_end":
            t0 = prof_open.pop(str(e.get("logdir") or ""), None)
            if t0 is not None:
                out.append({"name": "xplane_capture", "ph": "X",
                            "ts": t0 * 1e6,
                            "dur": max((e["ts"] - t0) * 1e6, 1.0),
                            "pid": 1, "tid": 0, "args": args})
        else:
            out.append({"name": kind, "ph": "i", "s": "t",
                        "ts": e["ts"] * 1e6,
                        "pid": 1, "tid": lane(e.get("trace")), "args": args})
    if span_fetch is not None:
        for tr, tid in list(lanes.items()):
            for s in span_fetch(tr):
                out.append({"name": s["name"], "ph": "X",
                            "ts": s["ts"] * 1e6,
                            "dur": max(s["dur_s"] * 1e6, 1.0), "pid": 1,
                            "tid": tid,
                            "args": {"span_id": s["id"],
                                     "parent_id": s["parent"],
                                     **s["labels"]}})
    out.append({"name": "thread_name", "ph": "M", "pid": 1, "tid": 0,
                "args": {"name": "profiler"}})
    for tr, tid in lanes.items():
        out.append({"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                    "args": {"name": f"trace {tr}"}})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"traces": sorted(lanes),
                          **({"trace": trace} if trace else {})}}


def reset() -> None:
    """Drop every recorded event (tests). Sequence numbers keep counting
    so ordering stays monotonic across a reset."""
    for i in range(_SIZE):
        _RING[i] = None


# -- per-dispatch device-time attribution ------------------------------------

#: span ids the overload hang watchdog declared wedged (overload.py adds
#: via :func:`mark_span_hung`): a dispatch that UNWEDGES after its trip
#: fail-stops at its own exit — its result belongs to a formation the
#: supervisor already gave up on, and raising there is what hands the job
#: to recovery.run_supervised. Module-level set: the clean-exit check is
#: one truthiness test when nothing is hung.
_HUNG_SPANS: set = set()


def mark_span_hung(span) -> None:
    """Flag an open dispatch span as watchdog-tripped (overload.py)."""
    if span is not None:
        _HUNG_SPANS.add(span)


class _Dispatch:
    """Context manager stamping dispatch start/end events into the ring and
    feeding ``dispatch_device_seconds{site}``. A class, not a
    @contextmanager: the hot sites enter/exit this once per device program
    and the generator machinery is measurably slower.

    Every dispatch is also a **span** in the active trace tree (ISSUE-18):
    start/end events carry ``trace`` (the enclosing job/request trace id,
    None when untraced), a fresh ``span`` id from the shared metrics
    sequence, and the ``parent`` span active at entry. The span id is
    pushed as the active span for the dispatch body, so nested dispatches
    (a stream_block wrapping a tree chunk) and registry spans parent
    correctly — all of it gate-free, like the ring itself. On exit the
    measured wall feeds the per-job ledger (utils/jobacct.py) under the
    same trace id."""

    __slots__ = ("site", "meta", "_t0", "_trace", "_span", "_parent", "_tok")

    def __init__(self, site: str, meta: dict):
        self.site = site
        self.meta = meta

    def __enter__(self):
        self._trace = _mx.current_trace()
        self._parent = _mx.current_span()
        self._span = _mx.next_span_id()
        record("dispatch_start", site=self.site, trace=self._trace,
               span=self._span, parent=self._parent, **self.meta)
        self._tok = _mx.push_span(self._span)
        self._t0 = time.perf_counter()
        if _faults.armed():
            # chaos hooks INSIDE the open span: hang_check sleeps while the
            # ring shows an open dispatch_start (what the hang watchdog
            # walks for); oom_check raises a synthetic RESOURCE_EXHAUSTED.
            # A raise here must still stamp dispatch_end + classify, so
            # route it through our own __exit__ before propagating.
            try:
                _faults.hang_check(self.site)
                _faults.oom_check(self.site)
            except BaseException:
                import sys

                self.__exit__(*sys.exc_info())
                raise
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        _mx.pop_span(self._tok)
        record("dispatch_end", site=self.site,
               dur_ms=round(dur * 1e3, 3),
               trace=self._trace, span=self._span, parent=self._parent,
               **({"error": exc_type.__name__} if exc_type else {}))
        _DISPATCH_SECONDS.observe(dur, site=self.site)
        _jobacct.on_dispatch(self._trace, self.site, dur)
        from h2o3_tpu.utils import devmem

        devmem.on_dispatch()  # high-water marks sample at dispatch boundaries
        if exc is not None:
            from h2o3_tpu.utils import overload as _ov

            _ov.note_dispatch_error(self.site, exc)
        elif _HUNG_SPANS and self._span in _HUNG_SPANS:
            # the hang watchdog tripped on this span and already latched the
            # cloud degraded: a late result from a wedged dispatch must not
            # be trusted — fail-stop so the supervisor's reform+resume owns
            # the job from here.
            _HUNG_SPANS.discard(self._span)
            raise RuntimeError(
                f"cloud is degraded (fail-stop): dispatch site "
                f"{self.site!r} span {self._span} was declared wedged by "
                "the hang watchdog and its late result is discarded; "
                "supervised jobs resume from their latest snapshot")
        return False


def dispatch(site: str, **meta) -> _Dispatch:
    """Wrap one hot device dispatch: ``with flightrec.dispatch("tree",
    program=key): out = fn(*args)``. Meta lands in the ring only (free-form
    — program keys, block indices), never as metric labels."""
    return _Dispatch(site, meta)


# -- incident bundles --------------------------------------------------------

_DEDUP_SECS = 30.0
_CAP_LOCK = threading.Lock()
_last_bundle: tuple[float, int, str] | None = None  # (monotonic, gen, path)


def incident_dir() -> str:
    """H2O3_TPU_INCIDENT_DIR ('' = <tmp>/h2o3_incidents)."""
    d = _config.get("H2O3_TPU_INCIDENT_DIR").strip()
    return d or os.path.join(tempfile.gettempdir(), "h2o3_incidents")


def last_incident() -> str | None:
    """Path of the most recently written bundle (None before the first)."""
    return _last_bundle[2] if _last_bundle else None


def _rank() -> int:
    """This process's pod rank (0 single-process / before jax init)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — capture must work before jax init
        return 0


def _sibling_bundles(path: str, gen: int) -> list[str]:
    """Other ranks' bundles for the same degraded episode. Every rank's
    latch fires `capture_incident` locally (collectives are dead on the
    failure path, so no gather — each rank freezes its OWN ring), and the
    incident dir is a shared volume on pods: bundles of the same cloud
    generation ARE the pod-wide capture. This cross-references them so one
    bundle leads a postmortem to the rest."""
    d = os.path.dirname(path)
    if not d or "://" in path:
        return []
    try:
        tag = f"_gen{gen}_"
        return sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if tag in f and f.endswith(".json")
            and os.path.join(d, f) != path
        )
    except OSError:
        return []


def capture_incident(reason: str, trigger: str = "degraded",
                     extra: dict | None = None) -> str | None:
    """Freeze the evidence for a postmortem: ring dump + metrics registry
    snapshot + devmem attribution + log tail, written atomically through
    persist BEFORE any reform/retry discards the dying state. Returns the
    bundle path (the cached one when this degraded episode — same cloud
    generation within the dedup window — already captured), or None when
    capture itself fails (never raises: this runs on failure paths)."""
    global _last_bundle
    try:
        from h2o3_tpu.cluster import cloud

        gen = cloud.generation()
    except Exception:  # noqa: BLE001 — capture must work before cloud init
        gen = -1
    with _CAP_LOCK:
        if (_last_bundle is not None and _last_bundle[1] == gen
                and time.monotonic() - _last_bundle[0] < _DEDUP_SECS):
            return _last_bundle[2]
        try:
            from h2o3_tpu import persist
            from h2o3_tpu.utils import devmem
            from h2o3_tpu.utils.log import Log

            rank = _rank()
            bundle = {
                "schema": "h2o3_incident/2",
                "ts": time.time(),
                "reason": str(reason)[:2000],
                "trigger": trigger,
                "generation": gen,
                "rank": rank,
                "ring": ring_status(),
                "events": events(),
                "devmem": devmem.status(),
                "metrics": _mx.REGISTRY.compact_snapshot(),
                "jobs": _jobacct.all_jobs(),
                "log_tail": Log.tail(200),
                **(extra or {}),
            }
            stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            path = os.path.join(
                incident_dir(),
                f"incident_{stamp}_gen{gen}_r{rank}_{os.getpid()}.json")
            d = os.path.dirname(path)
            if d and "://" not in path:
                os.makedirs(d, exist_ok=True)
            # each rank captures its OWN ring at its own latch; siblings of
            # this generation already on the (shared) volume get linked so
            # the bundle set is discoverable from any one of them.
            bundle["pod_bundles"] = _sibling_bundles(path, gen)
            persist.write_bytes(
                json.dumps(bundle, default=str).encode(), path)
            _last_bundle = (time.monotonic(), gen, path)
            _INCIDENTS.inc(trigger=trigger)
            record("incident", path=path, trigger=trigger,
                   reason=str(reason)[:200])
            Log.warn(f"incident bundle written: {path} ({trigger}: "
                     f"{str(reason)[:120]})")
            return path
        except Exception as e:  # noqa: BLE001 — never raise on a failure path
            try:
                from h2o3_tpu.utils.log import Log

                Log.warn(f"incident bundle capture failed: {e!r}")
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
            return None


def _reset_incidents_for_tests() -> None:
    global _last_bundle
    with _CAP_LOCK:
        _last_bundle = None
