"""Per-device HBM ledger — attribution of live device bytes to an OWNER
(the ISSUE-13 tentpole, piece 1).

Three residency planes now compete for the same HBM — the out-of-core frame
window (``H2O3_TPU_HBM_WINDOW_BYTES``, frame/chunkstore.py), the serving
residency LRU (``H2O3_TPU_SERVE_HBM_BYTES``, serving/residency.py) and
XLA's own program/temp buffers — and before this module each tracked its
own bytes in plane-local gauges that could not be cross-read. The ledger is
the ONE place they report into, plus the ONE low-rate reader of
``device.memory_stats()`` (``cluster/cloud.py``'s health probe routes here
instead of probing ad hoc):

- ``hbm_owned_bytes{owner}`` — live device bytes each plane claims
  (``frame_window`` = ChunkStore LRU windows, ``frame_resident`` = Vec
  device arrays, ``serving`` = paged scorer payloads, ``parse`` = the
  transient ingest upload staging buffer), with a computed
  ``owner="unattributed"`` series (device in_use − Σ owned = the XLA
  program/temp share — the OOM-forensics number).
- ``device_hbm_bytes{device, kind=in_use|peak|limit}`` — what the runtime
  itself reports per local device, polled at most once per
  ``H2O3_TPU_DEVMEM_POLL_SECS`` (the CPU proxy's devices return
  ``memory_stats() = None``: the per-owner ledger still works, the
  device series and the unattributed split just stay absent).
- ``hbm_headroom_bytes`` — Σ limit − Σ in_use across local devices: the
  number the ChunkStore/Residency planes can consult (:func:`headroom`)
  instead of trusting their static budgets.

High-water marks are sampled at dispatch boundaries: every
``flightrec.dispatch(...)`` site calls :func:`on_dispatch`, which refreshes
the rate-limited poll — so the per-owner peaks (exact, updated on every
``adjust``) and the device peaks (``memory_stats()['peak_bytes_in_use']``)
line up with the program dispatches the flight-recorder ring records.

The ledger is ALWAYS on (``always=True`` gauges): the planes' budget
decisions and the incident bundles read it, so ``H2O3_TPU_METRICS=0``
must not blind it.
"""

from __future__ import annotations

import threading
import time

from h2o3_tpu.utils import metrics as _mx

#: the registered residency planes (docs/OBSERVABILITY.md has the rows);
#: "unattributed" is computed, never adjusted directly
OWNERS = ("frame_window", "frame_resident", "serving", "parse")

_DEVICE_HBM = _mx.gauge(
    "device_hbm_bytes",
    "per-local-device HBM as the runtime reports it (memory_stats), by "
    "kind: in_use = bytes_in_use, peak = peak_bytes_in_use, limit = "
    "bytes_limit; absent on backends whose devices report no stats "
    "(the CPU proxy)", always=True)
_OWNED = _mx.gauge(
    "hbm_owned_bytes",
    "live device bytes attributed to an owning residency plane "
    "(frame_window = out-of-core chunk windows, frame_resident = Vec "
    "device arrays, serving = paged scorer payloads, parse = ingest "
    "upload staging); owner=unattributed is computed at poll time as "
    "device in_use - sum(owned) — the XLA program/temp share", always=True)
_HEADROOM = _mx.gauge(
    "hbm_headroom_bytes",
    "sum(limit) - sum(in_use) across local devices at the last poll — the "
    "measured budget the residency planes can consult instead of their "
    "static byte knobs (0 while the backend reports no stats)", always=True)
_RESERVED = _mx.gauge(
    "hbm_reserved_bytes",
    "admission reservations by job key (utils/overload.py): bytes the "
    "memory-aware admission gate has promised a live job — resident "
    "admissions reserve their full estimated footprint, streamed "
    "admissions their window share — so concurrent training + serving "
    "cannot overcommit the measured headroom; the series is removed when "
    "the job releases", always=True)

_LOCK = threading.Lock()
_owned: dict[str, float] = {}
_peak: dict[str, float] = {}
_last_poll = 0.0            # monotonic stamp of the last real stats read
_poll_lock = threading.Lock()
_devices: list[dict] = []   # cached per-device stats (the ONE-reader cache)
_in_use_total: float | None = None
_limit_total: float | None = None
_unattributed: float | None = None


def poll_period() -> float:
    """H2O3_TPU_DEVMEM_POLL_SECS — the memory_stats read rate bound."""
    from h2o3_tpu import config

    try:
        return max(config.get_float("H2O3_TPU_DEVMEM_POLL_SECS"), 0.05)
    except (TypeError, ValueError):
        return 5.0


def _stats_fn(device) -> dict | None:
    """The one memory_stats call site (tests monkeypatch this to inject
    synthetic in_use/limit on the CPU proxy, whose devices return None)."""
    if not hasattr(device, "memory_stats"):
        return None
    return device.memory_stats()


# -- the owner ledger --------------------------------------------------------

def adjust(owner: str, delta: float) -> None:
    """A residency plane claiming (+) or returning (−) live device bytes.
    Per-owner peaks update here — exact high-water, not poll-sampled."""
    if not delta:
        return
    with _LOCK:
        v = _owned.get(owner, 0.0) + float(delta)
        _owned[owner] = v
        if v > _peak.get(owner, 0.0):
            _peak[owner] = v
    _OWNED.set(v, owner=owner)


def owned() -> dict[str, float]:
    """Current per-owner claims (a copy)."""
    with _LOCK:
        return dict(_owned)


# -- the admission reservation ledger (utils/overload.py writes it) ----------

_reservations: dict[str, float] = {}


def reserve(job: str, nbytes: float) -> None:
    """Record an admission promise of ``nbytes`` to ``job`` (re-reserving
    a live key replaces its amount). ``hbm_reserved_bytes{job}`` publishes
    it until :func:`release`."""
    v = max(float(nbytes), 0.0)
    with _LOCK:
        _reservations[job] = v
    _RESERVED.set(v, job=job)


def release(job: str) -> None:
    """Drop a job's reservation (idempotent) and remove its gauge series —
    reservation sums must return to zero after every job, whatever its
    outcome."""
    with _LOCK:
        had = _reservations.pop(job, None)
    if had is not None:
        _RESERVED.remove(job=job)


def reservations() -> dict[str, float]:
    """Live admission reservations by job key (a copy)."""
    with _LOCK:
        return dict(_reservations)


def reserved_total() -> float:
    """Σ live reservations — what the admission gate subtracts from the
    usable headroom share before admitting the next job."""
    with _LOCK:
        return float(sum(_reservations.values()))


def peaks() -> dict[str, float]:
    """Per-owner high-water marks since process start / :func:`reset_peaks`."""
    with _LOCK:
        return dict(_peak)


def reset_peaks() -> dict[str, float]:
    """Re-arm the per-owner high-water marks (bench phase isolation);
    returns the pre-reset peaks."""
    with _LOCK:
        snap = dict(_peak)
        for k, v in _owned.items():
            _peak[k] = max(v, 0.0)
    return snap


# -- the device poller (the ONE memory_stats reader) -------------------------

def poll(force: bool = False) -> list[dict]:
    """Read every local device's ``memory_stats()`` — rate-limited to one
    real read per :func:`poll_period` unless ``force`` — publish the
    ``device_hbm_bytes``/``hbm_headroom_bytes`` gauges and the computed
    ``unattributed`` owner series, and return the per-device list
    (cluster/cloud.py builds its ``/3/Cloud`` node table from this)."""
    global _last_poll, _devices, _in_use_total, _limit_total, _unattributed

    now = time.monotonic()
    if not force and _devices and now - _last_poll < poll_period():
        return list(_devices)
    with _poll_lock:
        now = time.monotonic()
        if not force and _devices and now - _last_poll < poll_period():
            return list(_devices)
        import jax

        devs: list[dict] = []
        in_use = limit = 0.0
        any_stats = False
        for d in jax.local_devices():
            node = {"id": d.id, "platform": d.platform,
                    "process": getattr(d, "process_index", 0), "error": None}
            try:
                stats = _stats_fn(d)
            except Exception as e:  # noqa: BLE001 — the probe must not throw
                stats = None
                node["error"] = repr(e)[:200]
            if stats:
                any_stats = True
                for kind, skey in (("in_use", "bytes_in_use"),
                                   ("peak", "peak_bytes_in_use"),
                                   ("limit", "bytes_limit")):
                    v = stats.get(skey)
                    if v is not None:
                        node[kind] = int(v)
                        _DEVICE_HBM.set(float(v), device=str(d.id), kind=kind)
                in_use += float(stats.get("bytes_in_use") or 0)
                limit += float(stats.get("bytes_limit") or 0)
            devs.append(node)
        _devices = devs
        _last_poll = time.monotonic()
        if any_stats:
            _in_use_total = in_use
            _limit_total = limit if limit else None
            owned_total = sum(owned().values())
            # the OOM-forensics number: what the runtime holds that no
            # plane claims = XLA program/temp buffers (+ poll jitter)
            _unattributed = max(in_use - owned_total, 0.0)
            _OWNED.set(_unattributed, owner="unattributed")
            if _limit_total:
                _HEADROOM.set(max(_limit_total - in_use, 0.0))
        else:
            # no device reported stats this poll: headroom is UNMEASURED,
            # not frozen at the last reading — overload admission must not
            # route on a stale total (and tests un-patching _stats_fn get
            # the proxy's honest None back)
            _in_use_total = _limit_total = _unattributed = None
        return list(devs)


def device_stats(force: bool = False) -> list[dict]:
    """The cached per-device list (≤ one poll period old) — the single
    entry point every health/diagnostic reader goes through."""
    return poll(force=force)


def headroom() -> float | None:
    """Measured Σ limit − Σ in_use at the last poll, or None while the
    backend reports no stats — what a residency plane consults before
    trusting its static byte budget."""
    poll()
    with _poll_lock:
        if _limit_total is None or _in_use_total is None:
            return None
        return max(_limit_total - _in_use_total, 0.0)


def on_dispatch() -> None:
    """Dispatch-boundary sampling hook (called by every
    ``flightrec.dispatch`` site): refresh the rate-limited poll so device
    high-water marks land at program boundaries. O(ns) between polls —
    one monotonic read and a compare."""
    if time.monotonic() - _last_poll >= poll_period():
        try:
            poll()
        except Exception:  # noqa: BLE001 — telemetry must never sink a dispatch
            pass


def status() -> dict:
    """One attribution snapshot — the ``/3/FlightRecorder`` devmem block,
    the incident-bundle devmem section, and ``tpu_mem_analysis --live``'s
    table source."""
    with _LOCK:
        own, pk, res = dict(_owned), dict(_peak), dict(_reservations)
    return {
        "owned_bytes": {k: int(v) for k, v in own.items()},
        "peak_owned_bytes": {k: int(v) for k, v in pk.items()},
        "owned_total_bytes": int(sum(own.values())),
        "reserved_bytes": {k: int(v) for k, v in res.items()},
        "reserved_total_bytes": int(sum(res.values())),
        "in_use_bytes": None if _in_use_total is None else int(_in_use_total),
        "limit_bytes": None if _limit_total is None else int(_limit_total),
        "unattributed_bytes": (
            None if _unattributed is None else int(_unattributed)),
        "headroom_bytes": (
            None if (_limit_total is None or _in_use_total is None)
            else int(max(_limit_total - _in_use_total, 0.0))),
        "devices": list(_devices),
    }


# -- background poller (idle servers still publish fresh series) -------------

_POLLER: threading.Thread | None = None
_POLL_STOP = threading.Event()


def _poll_loop() -> None:
    while not _POLL_STOP.wait(poll_period()):
        try:
            poll()
        except Exception:  # noqa: BLE001 — the poller must never die loud
            pass


def install() -> None:
    """Start the low-rate background poller (idempotent; daemon). The REST
    coordinator installs it at start_server so an IDLE server's device
    series stay fresh — busy processes refresh through on_dispatch."""
    global _POLLER
    if _POLLER is not None and _POLLER.is_alive():
        return
    _POLL_STOP.clear()
    _POLLER = threading.Thread(
        target=_poll_loop, name="h2o3-devmem", daemon=True)
    _POLLER.start()


def uninstall() -> None:
    """Stop the background poller (tests)."""
    global _POLLER
    _POLL_STOP.set()
    if _POLLER is not None:
        _POLLER.join(timeout=5)
    _POLLER = None


def _reset_for_tests() -> None:
    global _last_poll, _devices, _in_use_total, _limit_total, _unattributed
    with _LOCK:
        _owned.clear()
        _peak.clear()
        for job in _reservations:
            _RESERVED.remove(job=job)
        _reservations.clear()
    with _poll_lock:
        _last_poll = 0.0
        _devices = []
        _in_use_total = _limit_total = _unattributed = None
