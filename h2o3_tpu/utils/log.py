"""Leveled logging, successor of ``water.util.Log`` [UNVERIFIED upstream path].

H2O keeps per-node rolling log files fetchable over REST; here a single
process hosts the coordinator, so we wrap :mod:`logging` with H2O's level
names and keep an in-memory ring buffer that the REST layer can serve
(``GET /3/Logs``-equivalent).
"""

from __future__ import annotations

import collections
import logging
import threading

_LEVELS = {
    "FATAL": logging.CRITICAL,
    "ERRR": logging.ERROR,
    "WARN": logging.WARNING,
    "INFO": logging.INFO,
    "DEBUG": logging.DEBUG,
    "TRACE": logging.DEBUG,
}


class _RingHandler(logging.Handler):
    def __init__(self, capacity: int = 4096):
        super().__init__()
        self.buffer: collections.deque[str] = collections.deque(maxlen=capacity)
        self._lock2 = threading.Lock()

    def emit(self, record: logging.LogRecord) -> None:
        with self._lock2:
            self.buffer.append(self.format(record))


class Log:
    _logger = logging.getLogger("h2o3_tpu")
    _ring = _RingHandler()
    _configured = False

    @classmethod
    def _ensure(cls) -> None:
        if cls._configured:
            return
        fmt = logging.Formatter("%(asctime)s %(levelname)-5s %(message)s")
        cls._ring.setFormatter(fmt)
        cls._logger.addHandler(cls._ring)
        handler = logging.StreamHandler()
        handler.setFormatter(fmt)
        cls._logger.addHandler(handler)
        cls._logger.setLevel(logging.INFO)
        cls._configured = True

    @classmethod
    def set_level(cls, level: str) -> None:
        cls._ensure()
        cls._logger.setLevel(_LEVELS.get(level.upper(), logging.INFO))

    @classmethod
    def info(cls, *msg) -> None:
        cls._ensure()
        cls._logger.info(" ".join(str(m) for m in msg))

    @classmethod
    def warn(cls, *msg) -> None:
        cls._ensure()
        cls._logger.warning(" ".join(str(m) for m in msg))

    @classmethod
    def err(cls, *msg) -> None:
        cls._ensure()
        cls._logger.error(" ".join(str(m) for m in msg))

    @classmethod
    def debug(cls, *msg) -> None:
        cls._ensure()
        cls._logger.debug(" ".join(str(m) for m in msg))

    @classmethod
    def tail(cls, n: int = 100) -> list[str]:
        cls._ensure()
        return list(cls._ring.buffer)[-n:]
