"""Leveled logging, successor of ``water.util.Log`` [UNVERIFIED upstream path].

H2O keeps per-node rolling log files fetchable over REST; here a single
process hosts the coordinator, so we wrap :mod:`logging` with H2O's level
names and keep an in-memory ring buffer that the REST layer can serve
(``GET /3/Logs``-equivalent).
"""

from __future__ import annotations

import collections
import logging
import threading

_LEVELS = {
    "FATAL": logging.CRITICAL,
    "ERRR": logging.ERROR,
    "WARN": logging.WARNING,
    "INFO": logging.INFO,
    "DEBUG": logging.DEBUG,
    "TRACE": logging.DEBUG,
}


class _RingHandler(logging.Handler):
    def __init__(self, capacity: int = 4096):
        super().__init__()
        # (levelno, formatted line): the REST /3/Logs level filter needs the
        # numeric level — parsing it back out of the formatted string would
        # break the moment the format changes
        self.buffer: collections.deque[tuple[int, str]] = collections.deque(
            maxlen=capacity
        )
        self._lock2 = threading.Lock()

    def emit(self, record: logging.LogRecord) -> None:
        with self._lock2:
            self.buffer.append((record.levelno, self.format(record)))

    def tail(self, n: int, min_levelno: int | None = None) -> list[str]:
        with self._lock2:
            snap = list(self.buffer)
        if min_levelno is not None:
            snap = [(lv, s) for lv, s in snap if lv >= min_levelno]
        return [s for _, s in snap[-n:]] if n > 0 else []


class Log:
    _logger = logging.getLogger("h2o3_tpu")
    _ring = _RingHandler()
    _configured = False

    @classmethod
    def _ensure(cls) -> None:
        if cls._configured:
            return
        fmt = logging.Formatter("%(asctime)s %(levelname)-5s %(message)s")
        cls._ring.setFormatter(fmt)
        cls._logger.addHandler(cls._ring)
        handler = logging.StreamHandler()
        handler.setFormatter(fmt)
        cls._logger.addHandler(handler)
        cls._logger.setLevel(logging.INFO)
        cls._configured = True

    @classmethod
    def set_level(cls, level: str) -> None:
        cls._ensure()
        cls._logger.setLevel(_LEVELS.get(level.upper(), logging.INFO))

    @classmethod
    def info(cls, *msg) -> None:
        cls._ensure()
        cls._logger.info(" ".join(str(m) for m in msg))

    @classmethod
    def warn(cls, *msg) -> None:
        cls._ensure()
        cls._logger.warning(" ".join(str(m) for m in msg))

    @classmethod
    def err(cls, *msg) -> None:
        cls._ensure()
        cls._logger.error(" ".join(str(m) for m in msg))

    @classmethod
    def debug(cls, *msg) -> None:
        cls._ensure()
        cls._logger.debug(" ".join(str(m) for m in msg))

    @classmethod
    def tail(cls, n: int = 100, level: str | None = None) -> list[str]:
        """Last ``n`` buffered lines, optionally at or above ``level``
        (H2O level names: FATAL/ERRR/WARN/INFO/DEBUG/TRACE)."""
        cls._ensure()
        min_levelno = _LEVELS.get(level.upper()) if level else None
        if level and min_levelno is None:
            raise ValueError(
                f"unknown log level {level!r} (one of {sorted(_LEVELS)})"
            )
        return cls._ring.tail(n, min_levelno)
