"""Standalone offline scorer — successor of ``h2o-genmodel``
(``hex.genmodel.MojoModel`` + ``easy.EasyPredictModelWrapper``) [UNVERIFIED
upstream paths, SURVEY.md §2.3].

Pure numpy, NO jax / NO cluster: load a ``.zip`` artifact written by
:func:`h2o3_tpu.models.export.export_mojo` and score rows in any Python
process. Row-wise parity with in-cluster ``model.predict`` is asserted by
the export tests (H2O's MOJO-parity regression net, SURVEY.md §4).

>>> m = MojoModel.load("gbm.zip")
>>> m.predict({"age": 31, "sex": "F"})           # one row (EasyPredict style)
>>> m.predict(pandas_dataframe)                  # batch
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Mapping

import numpy as np


def _native_mod():
    """The C++ runtime if importable and enabled, else None.

    Failure-tolerant so this file also runs STANDALONE (the POJO-style
    single-file export embeds it outside the h2o3_tpu package)."""
    try:
        from h2o3_tpu import native

        return native if native.enabled() else None
    except Exception:  # noqa: BLE001 — standalone mode has no package
        return None


class MojoModel:
    def __init__(self, meta: dict, arrays: Mapping[str, np.ndarray]):
        self.meta = meta
        self.arrays = dict(arrays)

    # -- loading ----------------------------------------------------------
    @staticmethod
    def load(path: str) -> "MojoModel":
        with zipfile.ZipFile(path) as z:
            meta = json.loads(z.read("model.json"))
            npz = np.load(io.BytesIO(z.read("arrays.npz")), allow_pickle=False)
            arrays = {k: npz[k] for k in npz.files}
        cls = {
            "gbm": _TreeMojo, "xgboost": _TreeMojo, "drf": _TreeMojo, "xrt": _TreeMojo,
            "glm": _GlmMojo, "deeplearning": _DeepLearningMojo,
            "kmeans": _KMeansMojo,
        }[meta["algo"]]
        return cls(meta, arrays)

    # -- common surface ---------------------------------------------------
    @property
    def algo(self) -> str:
        return self.meta["algo"]

    @property
    def domain(self):
        return self.meta.get("response_domain")

    def _rows_to_table(self, data) -> dict[str, np.ndarray]:
        """dict row / list-of-dicts / DataFrame → column arrays."""
        if hasattr(data, "to_dict") and hasattr(data, "columns"):  # DataFrame
            return {c: data[c].to_numpy() for c in data.columns}
        if isinstance(data, Mapping):
            vals = list(data.values())
            scalars = all(
                np.ndim(v) == 0 or isinstance(v, (str, bytes)) or v is None
                for v in vals
            )
            if scalars:  # one row, EasyPredict style
                return {k: np.asarray([v]) for k, v in data.items()}
            return {k: np.asarray(v) for k, v in data.items()}  # column table
        if isinstance(data, (list, tuple)) and data and isinstance(data[0], Mapping):
            keys = data[0].keys()
            return {k: np.asarray([row.get(k) for row in data]) for k in keys}
        raise TypeError(f"cannot score {type(data).__name__}")

    def predict(self, data) -> dict[str, np.ndarray]:
        """Returns {"predict": labels-or-values, <class>: prob...} — the
        EasyPredictModelWrapper row API, vectorized."""
        table = self._rows_to_table(data)
        raw = self.score_raw(table)
        dom = self.domain
        if dom is None:
            return {"predict": raw if raw.ndim == 1 else raw[:, 0]}
        if len(dom) == 2 and self.meta.get("default_threshold") is not None:
            # H2O labels binary predictions at the max-F1 threshold, not argmax
            idx = (raw[:, 1] >= float(self.meta["default_threshold"])).astype(int)
        else:
            idx = raw.argmax(axis=1)
        labels = np.asarray(dom, dtype=object)[idx]
        out = {"predict": labels}
        for k, d in enumerate(dom):
            out[str(d)] = raw[:, k]
        cal = self._calibration()
        if cal is not None and raw.shape[1] == 2:
            p1 = np.clip(np.asarray(raw[:, 1], np.float64), 1e-12, 1 - 1e-12)
            if cal["method"] == "PlattScaling":
                eta = np.clip(
                    cal["a"] * np.log(p1 / (1 - p1)) + cal["b"], -30.0, 30.0
                )
                cp1 = 1.0 / (1.0 + np.exp(-eta))
            else:
                cp1 = np.clip(
                    np.interp(p1, cal["thresholds_x"], cal["thresholds_y"]),
                    0.0, 1.0,
                )
            out["cal_p0"] = 1.0 - cp1
            out["cal_p1"] = cp1
        return out

    def _calibration(self) -> dict | None:
        method = self.meta.get("calibration_method")
        if method is None:
            return None
        if method == "PlattScaling":
            a, b = self.meta["calibration_platt"]
            return {"method": method, "a": a, "b": b}
        return {"method": method,
                "thresholds_x": self.arrays["cal_thresholds_x"],
                "thresholds_y": self.arrays["cal_thresholds_y"]}

    def score_raw(self, table: dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# shared numeric helpers


def goes_left(b, na_left_n, cat_hit_n, is_cat_n, thr_n):
    """THE split-decision rule, vectorized over rows (bin 0 = NA): NA rows
    follow na_left, categorical rows follow the gathered mask hit, numeric
    rows go left iff bin <= threshold. Single source for every host-side
    tree walk (offline scorer, leaf-node assignment); mirrors the device
    rule in shared_tree._partition_update."""
    return np.where(b == 0, na_left_n, np.where(is_cat_n, cat_hit_n, b <= thr_n))


def _col_numeric(table, name, n) -> np.ndarray:
    if name not in table:
        return np.full(n, np.nan)
    x = table[name]
    out = np.full(len(x), np.nan)
    for i, v in enumerate(x):
        try:
            if v is not None and v == v:  # not NaN
                out[i] = float(v)
        except (TypeError, ValueError):
            pass
    return out


def _col_codes(table, name, domain, n) -> np.ndarray:
    """Categorical → train-domain codes; unseen/missing → -1."""
    if name not in table:
        return np.full(n, -1, np.int64)
    lut = {d: i for i, d in enumerate(domain)}
    x = table[name]
    return np.asarray([lut.get(v if isinstance(v, str) else str(v), -1)
                       if v is not None and v == v else -1 for v in x], np.int64)


def _col_hash_buckets(table, name, n_buckets, n) -> np.ndarray:
    """Feature-hashed categorical → bucket codes; missing → -1.

    The bucket of a value is ``crc32(col_name \\0 level) % n_buckets`` —
    byte-for-byte the rule in ``models.datainfo._hash_lut`` — computed from
    the raw level STRING, so the offline scorer agrees with the cluster with
    no domain shipped in the artifact (that is the point of hashing: the
    train domain may be Criteo-sized)."""
    import zlib

    if name not in table:
        return np.full(n, -1, np.int64)
    prefix = name.encode() + b"\x00"
    return np.asarray(
        [zlib.crc32(prefix + (v if isinstance(v, str) else str(v)).encode())
         % n_buckets
         if v is not None and v == v else -1 for v in table[name]],
        np.int64,
    )


def _n_rows(table: dict) -> int:
    return len(next(iter(table.values())))


def _softmax(z):
    z = z - z.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


# ---------------------------------------------------------------------------
# tree models


class _TreeMojo(MojoModel):
    """Replays the recorded level arrays — CompressedTree.score0 successor."""

    def _bin_features(self, table) -> np.ndarray:
        names = self.meta["names"]
        n = _n_rows(table)
        is_cat = self.arrays["bin_is_cat"]
        nbins = self.arrays["bin_nbins"]
        edges = self.arrays["bin_edges"]
        doms = self.meta["bin_domains"]
        nat = _native_mod()
        cols = []
        for ci, name in enumerate(names):
            if is_cat[ci]:
                codes = _col_codes(table, name, doms[ci] or (), n)
                b = np.clip(codes + 1, 0, int(nbins[ci]))
            else:
                # Bin in float32 with float32 edges — bit-identical to the
                # device path (binning.bin_frame searchsorts f32), so bin
                # codes match exactly even for edge-adjacent values.
                x = _col_numeric(table, name, n).astype(np.float32)
                e = edges[ci][: max(int(nbins[ci]) - 1, 0)].astype(np.float32)
                if nat is not None:
                    b = nat.bin_numeric(x, e)
                else:
                    b = np.searchsorted(e, x, side="left") + 1
                    b[np.isnan(x)] = 0
            cols.append(b.astype(np.int64))
        return np.stack(cols, axis=1)

    def leaf_node_assignment(self, table, type: str = "Path") -> dict[str, np.ndarray]:
        """Terminal leaf per (row, tree, class) — the EasyPredict
        leafNodeAssignment analog, offline. Returns {column name ->
        array}: decision-path strings (type="Path") or node ids in the
        level-flattened numbering the in-cluster
        ``predict_leaf_node_assignment`` uses (type="Node_ID")."""
        if type not in ("Path", "Node_ID"):
            raise ValueError(f"type must be 'Path' or 'Node_ID', got {type!r}")
        bins = self._bin_features(table)
        n = bins.shape[0]
        K = self.meta["n_tree_classes"]
        rows = np.arange(n)
        a = self.arrays
        out: dict[str, np.ndarray] = {}
        for ti, class_levels in enumerate(self.meta["tree_levels"]):
            for ki in range(K):
                n_levels = class_levels[ki]
                nid = np.zeros(n, np.int64)
                term = np.zeros(n, np.int64)
                steps = np.full((n, max(n_levels, 1)), "", dtype="<U1")
                offset = 0
                for li in range(n_levels):
                    pre = f"t{ti}_k{ki}_l{li}_"
                    split_col = a[pre + "split_col"]
                    leaf_now = a[pre + "leaf_now"]
                    active = nid >= 0
                    node = np.where(active, nid, 0)
                    retired = leaf_now[node] & active
                    term = np.where(retired, offset + node, term)
                    b = bins[rows, split_col[node]]
                    go_left = goes_left(
                        b, a[pre + "na_left"][node],
                        a[pre + "cat_mask"][node, b],
                        a[pre + "is_cat"][node], a[pre + "split_bin"][node],
                    )
                    walking = active & ~retired
                    steps[walking, li] = np.where(go_left[walking], "L", "R")
                    child = a[pre + "child_base"][node] + np.where(go_left, 0, 1)
                    nid = np.where(walking, child, -1)
                    offset += len(split_col)
                name = f"T{ti + 1}.C{ki + 1}"
                if type == "Node_ID":
                    out[name] = term
                else:
                    out[name] = np.array(["".join(r) for r in steps], dtype=object)
        return out

    def _forest_sums(self, bins, n: int, K: int, shapes) -> np.ndarray:
        """(n, K) leaf sums over the forest — native C++ walk when the
        library builds (row-major, per-row early exit), numpy level replay
        otherwise. Both accumulate f32 leaves into f64 in the same order, so
        results are bit-identical (the parity tests pin this)."""
        nat = _native_mod()
        if nat is not None:
            return nat.score_forest(self, bins)
        F = np.zeros((n, K), np.float64)
        for ti, class_levels in enumerate(shapes):
            for ki in range(K):
                F[:, ki] += self._walk_tree(bins, ti, ki, class_levels[ki])
        return F

    def _walk_tree(self, bins: np.ndarray, ti: int, ki: int, n_levels: int) -> np.ndarray:
        n = bins.shape[0]
        nid = np.zeros(n, np.int64)
        preds = np.zeros(n, np.float64)
        a = self.arrays
        for li in range(n_levels):
            pre = f"t{ti}_k{ki}_l{li}_"
            split_col = a[pre + "split_col"]
            split_bin = a[pre + "split_bin"]
            is_cat = a[pre + "is_cat"]
            cat_mask = a[pre + "cat_mask"]
            na_left = a[pre + "na_left"]
            leaf_now = a[pre + "leaf_now"]
            leaf_val = a[pre + "leaf_val"].astype(np.float64)
            child_base = a[pre + "child_base"]

            active = nid >= 0
            node = np.where(active, nid, 0)
            col = split_col[node]
            b = bins[np.arange(n), col]
            go_left = goes_left(b, na_left[node], cat_mask[node, b],
                                is_cat[node], split_bin[node])
            child = child_base[node] + np.where(go_left, 0, 1)
            retired = leaf_now[node]
            preds += np.where(active & retired, leaf_val[node], 0.0)
            nid = np.where(active, np.where(retired, -1, child), -1)
        return preds

    def score_raw(self, table) -> np.ndarray:
        bins = self._bin_features(table)
        K = self.meta["n_tree_classes"]
        shapes = self.meta["tree_levels"]
        n = bins.shape[0]
        F = self._forest_sums(bins, n, K, shapes)

        if self.algo in ("drf", "xrt"):
            avg = F / max(self.meta["ntrees_actual"], 1)
            if self.domain is None:
                return avg[:, 0]
            if len(self.domain) == 2:
                p1 = np.clip(avg[:, 0], 0.0, 1.0)
                return np.stack([1 - p1, p1], axis=1)
            P = np.clip(avg, 1e-9, None)
            return P / P.sum(axis=1, keepdims=True)

        # gbm
        dist = self.meta["distribution"]
        init_f = self.meta["init_f"]
        if dist == "multinomial":
            return _softmax(F + np.asarray(init_f)[None, :])
        f = F[:, 0] + (init_f if np.isscalar(init_f) else init_f)
        if dist == "bernoulli":
            mu = 1.0 / (1.0 + np.exp(-f))
            return np.stack([1 - mu, mu], axis=1)
        if dist in ("poisson", "gamma", "tweedie"):
            return np.exp(f)
        return f


# ---------------------------------------------------------------------------
# GLM / DL / KMeans — design-matrix models


def _design_matrix(meta_di: dict, table) -> np.ndarray:
    n = _n_rows(table)
    cols = []
    for c in meta_di["columns"]:
        if c.get("pair"):
            a, b = c["pair"]
            if c.get("pair_domains"):
                # cat x cat combined factor: remap each source onto ITS
                # training domain, then combined code = a*|domain_b| + b
                # (mirrors DataInfo._transform_interaction)
                da, db = c["pair_domains"]
                ca = _col_codes(table, a, da, n)
                cb = _col_codes(table, b, db, n)
                codes = np.where((ca >= 0) & (cb >= 0), ca * len(db) + cb, -1)
                base = 0 if meta_di["use_all_factor_levels"] else 1
                onehot = (
                    (codes - base)[:, None] == np.arange(c["width"])[None, :]
                ).astype(np.float64)
                cols.append(onehot)
                continue
            # TRAINING means of the pair sources (exported with the spec),
            # matching the live transform exactly
            ma, mb = c.get("pair_means") or (0.0, 0.0)
            if c["kind"] == "num":  # numeric product, standardized like num
                xa = _col_numeric(table, a, n)
                xb = _col_numeric(table, b, n)
                xa = np.where(np.isnan(xa), ma, xa)
                xb = np.where(np.isnan(xb), mb, xb)
                x = xa * xb
                if meta_di["standardize"]:
                    x = (x - c["mean"]) / c["sigma"]
                cols.append(x[:, None])
            else:  # onehot(cat) * raw numeric
                codes = _col_codes(table, a, c["domain"], n)
                base = 0 if meta_di["use_all_factor_levels"] else 1
                onehot = ((codes - base)[:, None]
                          == np.arange(c["width"])[None, :]).astype(np.float64)
                xb = _col_numeric(table, b, n)
                xb = np.where(np.isnan(xb), mb, xb)
                cols.append(onehot * xb[:, None])
            continue
        if c["kind"] == "hash":
            # feature-hashed block: bucket straight from the raw level
            # string (crc32(col \0 level) % hash_buckets — the exact rule
            # DataInfo._hash_lut applies on-cluster), no domain needed.
            # use_all_factor_levels=False drops bucket 0 as the reference
            # level, mirroring the cat path; NA (-1) rows go all-zero.
            buckets = _col_hash_buckets(
                table, c["name"], int(meta_di["hash_buckets"]), n
            )
            base = 0 if meta_di["use_all_factor_levels"] else 1
            onehot = ((buckets - base)[:, None]
                      == np.arange(c["width"])[None, :]).astype(np.float64)
            cols.append(onehot)
        elif c["kind"] == "cat":
            codes = _col_codes(table, c["name"], c["domain"], n)
            base = 0 if meta_di["use_all_factor_levels"] else 1
            onehot = ((codes - base)[:, None] == np.arange(c["width"])[None, :]).astype(np.float64)
            cols.append(onehot)
        else:
            x = _col_numeric(table, c["name"], n)
            x = np.where(np.isnan(x), c["mean"], x)
            if meta_di["standardize"]:
                x = (x - c["mean"]) / c["sigma"]
            cols.append(x[:, None])
    if meta_di["add_intercept"]:
        cols.append(np.ones((n, 1)))
    return np.concatenate(cols, axis=1)


class _GlmMojo(MojoModel):
    def score_raw(self, table) -> np.ndarray:
        X = _design_matrix(self.meta["datainfo"], table)
        if "beta_multinomial_std" in self.arrays:
            return _softmax(X @ self.arrays["beta_multinomial_std"].T.astype(np.float64))
        if "theta" in self.arrays:  # ordinal: proportional-odds cumulatives
            eta = X @ self.arrays["beta_std"].astype(np.float64)
            theta = self.arrays["theta"].astype(np.float64)
            cum = 1.0 / (1.0 + np.exp(-(theta[None, :] - eta[:, None])))
            lo = np.concatenate([np.zeros((len(eta), 1)), cum], axis=1)
            hi = np.concatenate([cum, np.ones((len(eta), 1))], axis=1)
            return np.clip(hi - lo, 1e-12, 1.0)
        eta = X @ self.arrays["beta_std"].astype(np.float64)
        fam = self.meta["family"]
        link = self.meta.get("link", "family_default")
        mu = _link_inverse(fam, link, eta, self.meta.get("tweedie_link_power", 1.0))
        if self.domain is not None:
            return np.stack([1 - mu, mu], axis=1)
        return mu


def _link_inverse(family: str, link: str, eta, tweedie_link_power: float):
    if link == "family_default":
        link = {"gaussian": "identity", "binomial": "logit",
                "fractionalbinomial": "logit", "quasibinomial": "logit",
                "poisson": "log", "gamma": "inverse", "negativebinomial": "log",
                "tweedie": "tweedie"}.get(family, "identity")
    if link == "identity":
        return eta
    if link == "logit":
        return 1.0 / (1.0 + np.exp(-eta))
    if link == "log":
        return np.exp(eta)
    if link == "inverse":
        return 1.0 / np.where(np.abs(eta) < 1e-12, 1e-12, eta)
    if link == "tweedie":
        p = tweedie_link_power
        return np.power(np.maximum(eta, 1e-12), 1.0 / p) if p != 0 else np.exp(eta)
    raise ValueError(f"unknown link {link!r}")


class _DeepLearningMojo(MojoModel):
    def score_raw(self, table) -> np.ndarray:
        X = _design_matrix(self.meta["datainfo"], table)
        act_name = self.meta["activation"].lower()
        act = np.tanh if "tanh" in act_name else (lambda z: np.maximum(z, 0.0))
        h = X
        L = self.meta["n_layers"]
        for i in range(L):
            h = h @ self.arrays[f"W{i}"].astype(np.float64) + self.arrays[f"b{i}"].astype(np.float64)
            if i < L - 1:
                h = act(h)
        if self.domain is not None:
            return _softmax(h)
        return h[:, 0]


class _KMeansMojo(MojoModel):
    def score_raw(self, table) -> np.ndarray:
        X = _design_matrix(self.meta["datainfo"], table)
        centers = self.arrays["centers_std"].astype(np.float64)
        d2 = ((X[:, None, :] - centers[None, :, :]) ** 2).sum(axis=2)
        return d2.argmin(axis=1).astype(np.float64)

    def predict(self, data):
        table = self._rows_to_table(data)
        return {"cluster": self.score_raw(table).astype(np.int64)}
