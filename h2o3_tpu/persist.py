"""Binary model save/load + persistence SPI — successor of
``water.persist.Persist`` (URI-scheme byte store) and the ``/99/Models.bin``
save/load endpoints (``water.api.ModelsHandler``) [UNVERIFIED upstream
paths, SURVEY.md §2.1, §5.4].

H2O serializes the whole ``Model`` Iced graph with AutoBuffer; the Python-
native equivalent is pickle — with two twists handled here:
- device arrays (tree level records, betas, DL params) are pulled to host
  numpy on save in ONE batched transfer (a networked TPU charges ~100ms per
  transfer — per-array pulls would take minutes on a big forest);
- jax-traced closures (GLM family objects, the DL apply_fn) are stripped on
  save and rebuilt from their defining parameters on load.

Scheme dispatch mirrors the Persist SPI: ``file:`` (and bare paths) are
implemented; ``s3:``/``hdfs:``/``gs:`` raise cleanly until a backend is
registered (the SPI point is the registry, not any one cloud SDK).

Durability contract (the fail-stop cloud's other half, SURVEY §5.3):
- **atomic publish** — every FS write lands in a same-directory temp file
  and is ``os.replace``d into place on clean close, so a crash mid-write
  never leaves a partial file at the target path (the cloud backends get
  the same guarantee from ``_UploadOnClose``: no partial object is ever
  published);
- **retry with backoff** — transient IO errors are retried
  ``H2O3_TPU_PERSIST_RETRIES`` times with exponential backoff and
  *deterministic* jitter (identical on every rank, preserving the spmd
  lockstep contract), while deterministic errors (collision, bad path,
  corrupt file) fail fast on the first attempt.
"""

from __future__ import annotations

import io
import os
import pickle
import tempfile
import time
import urllib.parse
import zlib
from typing import BinaryIO, Callable

import jax
import numpy as np

from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.models.model_base import Model
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log

# flaky storage must be visible BEFORE it becomes an outage: every transient
# retry bumps this (alongside the Log.warn), and write durations feed the
# checkpoint-cost histogram
_RETRIES_TOTAL = _mx.counter(
    "persist_retries_total", "transient persist IO retries, by operation kind")
_WRITE_SECONDS = _mx.histogram(
    "persist_write_seconds",
    "durable persist write wall time (incl. retries/backoff), by kind")

FORMAT_MAGIC = b"H2O3TPU1"


# ---------------------------------------------------------------------------
# Persist SPI


class PersistBackend:
    def open_read(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def open_write(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        """Scheme-correct existence probe (collision checks, ``force=False``)."""
        raise NotImplementedError

    def is_dir(self, path: str) -> bool:
        """True when the path is a directory-like container. Object stores
        have no real directories — they return False and rely on the
        trailing-``/`` convention for directory-append semantics."""
        return False

    def probe(self, path: str) -> tuple | None:
        """Cheap change-detection etag for ``path`` — any hashable tuple
        that changes when the content does (FS: mtime_ns + size; object
        stores would surface their ETag/generation). ``None`` means the
        backend cannot probe without reading bytes; the serving registry's
        watch loop (serving/registry.py) requires a probing backend."""
        return None

    def list_dir(self, path: str) -> list[str]:
        """File names directly inside a directory-like path (no recursion,
        no directories). Backends without listings raise — the watch loop
        reports the scheme as unwatchable instead of spinning."""
        raise NotImplementedError(f"{type(self).__name__} cannot list {path}")


class _AtomicFile(io.FileIO):
    """FS write handle that publishes atomically on clean close.

    Bytes land in a same-directory temp file; ``os.replace`` moves it onto
    the target only after a successful close — a crash or an exception in
    the ``with`` block deletes the temp and leaves NO partial file at the
    target path. close() stays idempotent like every other file object.
    """

    def __init__(self, fd: int, tmp_path: str, final_path: str):
        super().__init__(fd, "wb")
        self._tmp = tmp_path
        self._final = final_path
        self._aborted = False
        self._published = False

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._aborted = True
        self.close()

    def close(self) -> None:
        if self.closed:
            return
        if not self._aborted:
            try:
                self.flush()
                os.fsync(self.fileno())
            except OSError:  # fsync is best-effort (some FS reject it)
                pass
        super().close()
        if self._aborted or self._published:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass
            return
        self._published = True
        os.replace(self._tmp, self._final)


class PersistFS(PersistBackend):
    def open_read(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def open_write(self, path: str) -> BinaryIO:
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=d, prefix="." + os.path.basename(path) + ".", suffix=".tmp"
        )
        return _AtomicFile(fd, tmp, path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def is_dir(self, path: str) -> bool:
        return os.path.isdir(path)

    def probe(self, path: str) -> tuple | None:
        try:
            st = os.stat(path)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def list_dir(self, path: str) -> list[str]:
        with os.scandir(path) as it:
            return sorted(e.name for e in it if e.is_file())


class _UploadOnClose(io.BytesIO):
    """Write buffer that publishes atomically on clean close.

    A with-block that raises marks the buffer aborted, so NO partial object
    is ever published; close() is idempotent like every other file object.
    """

    def __init__(self, publish):
        super().__init__()
        self._publish = publish
        self._done = False
        self._aborted = False

    def close(self) -> None:
        if not self._done and not self.closed:
            self._done = True
            if not self._aborted:
                self._publish(self.getvalue())
        super().close()

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._aborted = True
        self.close()


class PersistS3(PersistBackend):
    """``s3://bucket/key`` via boto3 (gated: clean error when absent)."""

    def __init__(self):
        import boto3  # raises ImportError when the SDK is not in the image

        self._s3 = boto3.client("s3")

    def _split(self, uri: str) -> tuple[str, str]:
        p = urllib.parse.urlparse(uri)
        return p.netloc, p.path.lstrip("/")

    def open_read(self, path: str) -> BinaryIO:
        bucket, key = self._split(path)
        body = self._s3.get_object(Bucket=bucket, Key=key)["Body"].read()
        return io.BytesIO(body)

    def open_write(self, path: str) -> BinaryIO:
        bucket, key = self._split(path)
        return _UploadOnClose(
            lambda data: self._s3.put_object(Bucket=bucket, Key=key, Body=data)
        )

    def exists(self, path: str) -> bool:
        bucket, key = self._split(path)
        try:
            self._s3.head_object(Bucket=bucket, Key=key)
            return True
        except Exception:  # botocore ClientError 404 — SDK-typed, gated import
            return False

    def probe(self, path: str) -> tuple | None:
        """ETag-based change etag (ISSUE 14: the serving registry's model
        store need not be a filesystem): one HEAD per file per poll — the
        object-store analog of the FS mtime_ns+size stat, never a read."""
        bucket, key = self._split(path)
        try:
            head = self._s3.head_object(Bucket=bucket, Key=key)
        except Exception:  # 404/permission — watch loop treats as vanished
            return None
        return (head.get("ETag", "").strip('"'),
                int(head.get("ContentLength", 0)))

    def list_dir(self, path: str) -> list[str]:
        """Direct children of an s3 'directory' (Delimiter-scoped listing —
        no recursion, no pseudo-directories), paginated."""
        bucket, key = self._split(path)
        prefix = key.rstrip("/") + "/" if key else ""
        names: list[str] = []
        token = None
        while True:
            kw = {"Bucket": bucket, "Prefix": prefix, "Delimiter": "/"}
            if token:
                kw["ContinuationToken"] = token
            resp = self._s3.list_objects_v2(**kw)
            for obj in resp.get("Contents", ()):
                name = obj["Key"][len(prefix):]
                if name:  # skip the prefix marker object itself
                    names.append(name)
            if not resp.get("IsTruncated"):
                break
            token = resp.get("NextContinuationToken")
        return sorted(names)


class PersistGS(PersistBackend):
    """``gs://bucket/key`` via google-cloud-storage (gated)."""

    def __init__(self):
        from google.cloud import storage

        self._client = storage.Client()

    def _blob(self, uri: str):
        p = urllib.parse.urlparse(uri)
        return self._client.bucket(p.netloc).blob(p.path.lstrip("/"))

    def open_read(self, path: str) -> BinaryIO:
        return io.BytesIO(self._blob(path).download_as_bytes())

    def open_write(self, path: str) -> BinaryIO:
        blob = self._blob(path)
        return _UploadOnClose(lambda data: blob.upload_from_string(data))

    def exists(self, path: str) -> bool:
        return bool(self._blob(path).exists())

    def probe(self, path: str) -> tuple | None:
        """Generation/ETag change etag (one metadata GET, never a read).
        GCS generations are monotone per object — strictly stronger than
        mtime: an overwrite ALWAYS changes the etag."""
        blob = self._blob(path)
        try:
            blob.reload()
        except Exception:  # NotFound/permission — treated as vanished
            return None
        return (blob.etag or "", int(blob.generation or 0),
                int(blob.size or 0))

    def list_dir(self, path: str) -> list[str]:
        p = urllib.parse.urlparse(path)
        prefix = p.path.lstrip("/")
        prefix = prefix.rstrip("/") + "/" if prefix else ""
        it = self._client.list_blobs(p.netloc, prefix=prefix, delimiter="/")
        names = [b.name[len(prefix):] for b in it]
        return sorted(n for n in names if n)


class PersistHDFS(PersistBackend):
    """``hdfs://namenode/path`` via pyarrow's HadoopFileSystem (gated)."""

    def __init__(self):
        from pyarrow import fs

        self._fs_mod = fs
        self._conns: dict[tuple[str, int], object] = {}

    def _fs_path(self, uri: str):
        p = urllib.parse.urlparse(uri)
        host = p.hostname or "default"
        port = p.port or 8020
        conn = self._conns.get((host, port))
        if conn is None:
            conn = self._fs_mod.HadoopFileSystem(host, port)
            self._conns[(host, port)] = conn
        return conn, p.path

    def open_read(self, path: str) -> BinaryIO:
        f, pth = self._fs_path(path)
        return f.open_input_stream(pth)

    def open_write(self, path: str) -> BinaryIO:
        f, pth = self._fs_path(path)
        return f.open_output_stream(pth)

    def _info(self, path: str):
        f, pth = self._fs_path(path)
        return f.get_file_info(pth)

    def exists(self, path: str) -> bool:
        return self._info(path).type != self._fs_mod.FileType.NotFound

    def is_dir(self, path: str) -> bool:
        return self._info(path).type == self._fs_mod.FileType.Directory


_BACKENDS: dict[str, PersistBackend] = {"file": PersistFS(), "": PersistFS()}

# cloud schemes construct lazily on first touch: the SDK import happens then,
# and a missing SDK surfaces as a clear registration error, not at import
_LAZY_BACKENDS: dict[str, type] = {
    "s3": PersistS3,
    "gs": PersistGS,
    "hdfs": PersistHDFS,
}


def register_backend(scheme: str, backend: PersistBackend) -> None:
    _BACKENDS[scheme] = backend


def _backend_for(uri: str) -> tuple[PersistBackend, str]:
    parsed = urllib.parse.urlparse(uri)
    scheme = parsed.scheme if len(parsed.scheme) > 1 else ""  # windows-drive safe
    b = _BACKENDS.get(scheme)
    if b is None and scheme in _LAZY_BACKENDS:
        try:
            b = _LAZY_BACKENDS[scheme]()
        except ImportError as e:
            raise ValueError(
                f"persist scheme {scheme!r} needs its SDK ({e.name}) which is "
                "not installed in this image; register a backend with "
                "h2o3_tpu.persist.register_backend"
            ) from e
        _BACKENDS[scheme] = b
    if b is None:
        raise ValueError(
            f"no persist backend for scheme {scheme!r} "
            f"(registered: {sorted(k for k in _BACKENDS if k)}); "
            "register one with h2o3_tpu.persist.register_backend"
        )
    path = uri[len(scheme) + 1:].lstrip("/") if scheme == "file" else uri
    if scheme == "file":
        path = "/" + path if not path.startswith("/") else path
    return b, path


# ---------------------------------------------------------------------------
# retry/backoff wrapper for transient IO


def _is_transient(e: BaseException) -> bool:
    """Transient (retry) vs deterministic (fail fast) classification.

    Deterministic errors must raise identically on every rank and on every
    attempt — retrying them burns the budget AND desynchronizes nothing, so
    they surface immediately. The deterministic OSError subclasses are the
    path-shape family; everything else OS-level (EIO, ENOSPC-after-cleanup,
    connection resets, injected faults) is worth retrying.
    """
    if isinstance(e, (FileNotFoundError, FileExistsError, PermissionError,
                      IsADirectoryError, NotADirectoryError)):
        return False
    return isinstance(e, OSError)


def _retry_delays(desc: str) -> list[float]:
    """The backoff schedule for one operation: exponential with deterministic
    jitter keyed on (op, attempt) — every rank computes the same delays."""
    from h2o3_tpu import config

    retries = max(0, config.get_int("H2O3_TPU_PERSIST_RETRIES"))
    base = max(0.0, config.get_float("H2O3_TPU_PERSIST_BACKOFF"))
    out = []
    for attempt in range(retries):
        jitter = (zlib.crc32(f"{desc}:{attempt}".encode()) % 1000) / 2000.0
        out.append(base * (2 ** attempt) * (1.0 + jitter))
    return out


def _with_retries(op: Callable[[], "T"], desc: str):  # noqa: F821 - doc type
    """Run ``op`` retrying transient IO errors with backoff; the final
    attempt's (or any deterministic) error surfaces unchanged. Every retry
    is LOUD — a Log.warn with op/attempt/backoff plus a
    ``persist_retries_total`` bump — so flaky storage shows up in logs and
    on /3/Metrics before it becomes an outage."""
    delays = _retry_delays(desc)
    kind = desc.split(" ", 1)[0]  # "write"/"read"/"export"/... bounded labels
    for attempt in range(len(delays) + 1):
        try:
            return op()
        except Exception as e:
            if attempt >= len(delays) or not _is_transient(e):
                raise
            _RETRIES_TOTAL.inc(op=kind)
            Log.warn(
                f"persist: transient failure on {desc} (attempt "
                f"{attempt + 1}/{len(delays) + 1}): {e!r} — retrying in "
                f"{delays[attempt]:.2f}s"
            )
            time.sleep(delays[attempt])


def write_bytes(data: bytes, path: str) -> str:
    """Atomic, retried byte write through the scheme dispatch — the one
    durable-write primitive (models, grid/AutoML manifests)."""
    backend, p = _backend_for(path)

    def attempt():
        faults.io_check("persist_write", p)
        with backend.open_write(p) as f:
            f.write(data)

    t0 = time.perf_counter()
    _with_retries(attempt, f"write {p}")
    _WRITE_SECONDS.observe(time.perf_counter() - t0, kind="bytes")
    return p


def probe(path: str) -> tuple | None:
    """Change-detection etag through the scheme dispatch (None = the
    backend cannot probe cheaply, or the path does not exist). The serving
    registry's watch loop stats every candidate file each poll — this must
    stay a metadata call, never a read."""
    backend, p = _backend_for(path)
    return backend.probe(p)


def list_dir(path: str) -> list[str]:
    """File names inside a directory URI through the scheme dispatch."""
    backend, p = _backend_for(path)
    return backend.list_dir(p)


def read_bytes(path: str) -> bytes:
    """Retried whole-file read through the scheme dispatch."""
    backend, p = _backend_for(path)

    def attempt():
        faults.io_check("persist_read", p)
        with backend.open_read(p) as f:
            return f.read()

    return _with_retries(attempt, f"read {p}")


# ---------------------------------------------------------------------------
# device → host conversion of the whole model state, in one batched pull


def _pull_tree_output(out: dict) -> dict:
    out = dict(out)
    if "trees" in out:
        # collect every device array across the forest, fetch once
        import dataclasses as _dc

        from h2o3_tpu.models.tree.shared_tree import Tree, TreeLevel

        # derive from the dataclass so new record fields (node_w burned us
        # once: silently-zero TreeSHAP covers after reload) can't be dropped
        fields = tuple(f.name for f in _dc.fields(TreeLevel))
        flat = [
            [[getattr(lv, f) for f in fields] for lv in tree.levels]
            for group in out["trees"] for tree in group
        ]
        pulled = jax.device_get(flat)
        host_trees: list[list[Tree]] = []
        i = 0
        for group in out["trees"]:
            hgroup = []
            for _ in group:
                t = Tree()
                for vals in pulled[i]:
                    t.levels.append(TreeLevel(*[np.asarray(v) for v in vals]))
                hgroup.append(t)
                i += 1
            host_trees.append(hgroup)
        out["trees"] = host_trees
    if "params" in out:  # flax pytree
        out["params"] = jax.device_get(out["params"])
    if "opt_state" in out and out["opt_state"] is not None:  # optax pytree
        out["opt_state"] = jax.device_get(out["opt_state"])
    for k, v in list(out.items()):
        if isinstance(v, jax.Array):
            out[k] = np.asarray(v)
    return out


_STRIP: dict[str, tuple[str, ...]] = {
    "glm": ("family_obj",),
    "deeplearning": ("apply_fn",),
}

_REBUILDERS: dict[str, Callable[[Model], None]] = {}


def _rebuild_glm(model: Model) -> None:
    from h2o3_tpu.models.glm_families import get_family

    p = model.params
    fam = model.output["family"]
    if fam in ("multinomial", "ordinal"):
        # these fits carry a binomial family_obj only for metric plumbing
        # (scoring goes through beta_multinomial_std / theta directly)
        model.output["family_obj"] = get_family("binomial")
        return
    model.output["family_obj"] = get_family(
        fam, p.link,
        float(p.tweedie_variance_power or 1.5),
        float(p.tweedie_link_power), float(p.theta),
    )


def _rebuild_deeplearning(model: Model) -> None:
    from h2o3_tpu.models.deeplearning import _MLP

    p = model.params
    params = model.output["params"]
    inner = params["params"] if "params" in params else params
    last = sorted(inner.keys(), key=lambda k: int(k.split("_")[-1]))[-1]
    n_out = int(np.asarray(inner[last]["bias"]).shape[0])
    dropout = tuple(p.hidden_dropout_ratios or (0.0,) * len(p.hidden))
    mlp = _MLP(hidden=tuple(p.hidden), n_out=n_out, activation=p.activation,
               dropout=dropout, input_dropout=p.input_dropout_ratio)
    model.output["apply_fn"] = jax.jit(lambda prm, xx: mlp.apply(prm, xx, train=False))


_REBUILDERS["glm"] = _rebuild_glm
_REBUILDERS["deeplearning"] = _rebuild_deeplearning


# ---------------------------------------------------------------------------
# save / load


def _portable_params(params):
    """A pickle-light copy of the params dataclass: live Frame/Model refs
    collapse to their DKV keys (the model must not embed the training data —
    a periodic snapshot would otherwise re-serialize the whole frame every
    scoring interval, and sharded device columns don't pickle at all on a
    multi-process cloud). Resume passes frames explicitly, like H2O."""
    import copy
    import dataclasses

    if params is None or not dataclasses.is_dataclass(params):
        return params
    params = copy.copy(params)
    for fname in ("training_frame", "validation_frame", "calibration_frame",
                  "checkpoint"):
        ref = getattr(params, fname, None)
        if ref is not None and not isinstance(ref, str):
            setattr(params, fname, getattr(ref, "key", None))
    return params


def _portable_submodel(m: Model) -> Model:
    """A pickle-clean shallow clone of a nested model (CV folds, ensemble
    bases): device pulls + jit-closure strip + params lightening, without
    mutating the live object."""
    import copy

    clone = copy.copy(m)
    clone.__dict__.pop("_h2o3_batch_scorer", None)  # locks don't pickle
    clone.__dict__.pop("serving_generation", None)
    out = _pull_tree_output(dict(m.output))
    for k in _STRIP.get(m.algo, ()):
        out.pop(k, None)
    clone.output = out
    clone.params = _portable_params(m.params)
    clone.cv_models = []  # folds of folds don't exist; don't nest
    return clone


def serialize_model(model: Model) -> bytes:
    """Model → portable byte string (the device→host pulls happen here).

    Split out of :func:`save_model` so a multi-process cloud can run the
    pulls — collectives when output arrays span processes — on EVERY rank
    while only the coordinator writes the file (cluster/spmd.py)."""
    state = dict(model.__dict__)
    # serving-plane state is process-local: the cached batch scorer holds
    # locks + device arrays, and the registry generation is assigned by the
    # process that loads the snapshot, not baked into it
    state.pop("_h2o3_batch_scorer", None)
    state.pop("serving_generation", None)
    out = _pull_tree_output(state.pop("output"))
    for k in _STRIP.get(model.algo, ()):
        out.pop(k, None)
    state["output"] = out
    state["params"] = _portable_params(state.get("params"))
    if state.get("cv_models"):
        # fold models carry the same jit closures as the main model (a CV'd
        # GLM save used to die on the family_obj lambda here)
        state["cv_models"] = [_portable_submodel(m) for m in state["cv_models"]]
    payload = {"cls_module": type(model).__module__,
               "cls_name": type(model).__qualname__,
               "algo": model.algo,
               "state": state}
    buf = io.BytesIO()
    buf.write(FORMAT_MAGIC)
    pickle.dump(payload, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def model_path_in_dir(dir_uri: str, model_key: str) -> tuple[PersistBackend, str]:
    """(backend, path) for a model file named after its key INSIDE a
    directory URI — the interval-checkpoint writer's path rule (the dir may
    not exist yet; FS open_write creates it)."""
    backend, p = _backend_for(dir_uri)
    if isinstance(backend, PersistFS):
        return backend, os.path.join(p, model_key)
    return backend, p.rstrip("/") + "/" + model_key


def resolve_model_path(path: str, model_key: str, force: bool = True):
    """(backend, final_path) for a model save; raises FileExistsError when
    ``force`` is off and the target exists. Shared by :func:`save_model` and
    the replicated spmd save command (which writes coordinator-side only).

    Existence/directory probes go through the backend SPI so ``s3://`` /
    ``gs://`` / ``hdfs://`` targets are checked on THEIR filesystem, not the
    coordinator's local disk."""
    backend, p = _backend_for(path)
    if path.endswith(("/", os.sep)) or backend.is_dir(p):
        if isinstance(backend, PersistFS):
            p = os.path.join(p, model_key)
        else:
            p = p.rstrip("/") + "/" + model_key
    if not force and backend.exists(p):
        raise FileExistsError(p)
    return backend, p


def write_model_bytes(data: bytes, backend, p: str, model_key: str) -> str:
    def attempt():
        faults.io_check("persist_write", p)
        with backend.open_write(p) as f:
            f.write(data)

    t0 = time.perf_counter()
    _with_retries(attempt, f"write model {model_key} -> {p}")
    _WRITE_SECONDS.observe(time.perf_counter() - t0, kind="model")
    Log.info(f"saved model {model_key} to {p}")
    return p


def save_model(model: Model, path: str, force: bool = True) -> str:
    """``h2o.save_model`` successor. ``path`` may be a directory (H2O
    convention: file named after the model key) or a full file path."""
    backend, p = resolve_model_path(path, model.key, force)
    return write_model_bytes(serialize_model(model), backend, p, model.key)


def load_model(path: str) -> Model:
    """``h2o.load_model`` successor: restores the model into the registry.

    Accepts final saves and in-training interval snapshots alike — a partial
    snapshot loads into a scoreable Model whose key can be passed as
    ``checkpoint=`` to continue training (docs/RECOVERY.md)."""
    backend, p = _backend_for(path)

    def attempt():
        faults.io_check("persist_read", p)
        with backend.open_read(p) as f:
            return f.read()

    blob = _with_retries(attempt, f"read model {p}")
    if blob[: len(FORMAT_MAGIC)] != FORMAT_MAGIC:
        raise ValueError(f"{path}: not an h2o3_tpu model file")
    try:
        payload = pickle.loads(blob[len(FORMAT_MAGIC):])
        cls_module = payload["cls_module"]
        cls_name = payload["cls_name"]
        state = payload["state"]
    except ValueError:
        raise
    except Exception as e:
        # a crash mid-write can't truncate an atomically published file, but
        # foreign/bit-rotted files still deserve a named error, not a bare
        # unpickling traceback
        raise ValueError(
            f"{path}: corrupt or truncated model file "
            f"({type(e).__name__}: {e})"
        ) from e

    import functools
    import importlib

    # qualname-aware lookup: nested model classes ("Outer.Inner") resolve by
    # walking the attribute chain, not just the first segment
    cls = functools.reduce(
        getattr, cls_name.split("."), importlib.import_module(cls_module)
    )
    model = cls.__new__(cls)
    model.__dict__.update(state)
    rebuild = _REBUILDERS.get(payload["algo"])
    if rebuild:
        rebuild(model)
    for cv in getattr(model, "cv_models", ()) or ():
        cv_rebuild = _REBUILDERS.get(cv.algo)
        if cv_rebuild:
            cv_rebuild(cv)
    DKV.put(model.key, model)
    Log.info(f"loaded model {model.key} from {p}")
    return model


def export_file(frame, path: str, force: bool = False, format: str | None = None) -> str:
    """``h2o.export_file`` successor — frame → CSV/Parquet through the
    Persist scheme dispatch (ref upstream water/api FramesHandler export +
    Persist SPI [UNVERIFIED], SURVEY.md §5.4)."""
    return export_df(frame.to_pandas(), path, force=force, format=format)


def export_df(df, path: str, force: bool = False, format: str | None = None) -> str:
    """Write an already-materialized pandas frame (the host pull — a
    collective on multi-process clouds — happens in the caller, so every
    rank can pull while only the coordinator writes; cluster/spmd.py)."""
    backend, p = _backend_for(path)
    try:
        if not force and backend.exists(p):
            raise FileExistsError(p)
    except NotImplementedError:  # probe-less custom backend: overwrite
        pass
    fmt = (format or "").lower() or ("parquet" if p.endswith((".parquet", ".pq")) else "csv")

    def attempt():
        faults.io_check("persist_write", p)
        with backend.open_write(p) as f:
            if fmt == "parquet":
                df.to_parquet(f, index=False)
            elif fmt == "csv":
                df.to_csv(f, index=False)
            else:
                raise ValueError(f"unsupported export format {fmt!r}")

    t0 = time.perf_counter()
    _with_retries(attempt, f"export {p}")
    _WRITE_SECONDS.observe(time.perf_counter() - t0, kind="export")
    return p
