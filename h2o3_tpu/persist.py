"""Binary model save/load + persistence SPI — successor of
``water.persist.Persist`` (URI-scheme byte store) and the ``/99/Models.bin``
save/load endpoints (``water.api.ModelsHandler``) [UNVERIFIED upstream
paths, SURVEY.md §2.1, §5.4].

H2O serializes the whole ``Model`` Iced graph with AutoBuffer; the Python-
native equivalent is pickle — with two twists handled here:
- device arrays (tree level records, betas, DL params) are pulled to host
  numpy on save in ONE batched transfer (a networked TPU charges ~100ms per
  transfer — per-array pulls would take minutes on a big forest);
- jax-traced closures (GLM family objects, the DL apply_fn) are stripped on
  save and rebuilt from their defining parameters on load.

Scheme dispatch mirrors the Persist SPI: ``file:`` (and bare paths) are
implemented; ``s3:``/``hdfs:``/``gs:`` raise cleanly until a backend is
registered (the SPI point is the registry, not any one cloud SDK).
"""

from __future__ import annotations

import io
import os
import pickle
import urllib.parse
from typing import BinaryIO, Callable

import jax
import numpy as np

from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.models.model_base import Model
from h2o3_tpu.utils.log import Log

FORMAT_MAGIC = b"H2O3TPU1"


# ---------------------------------------------------------------------------
# Persist SPI


class PersistBackend:
    def open_read(self, path: str) -> BinaryIO:
        raise NotImplementedError

    def open_write(self, path: str) -> BinaryIO:
        raise NotImplementedError


class PersistFS(PersistBackend):
    def open_read(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def open_write(self, path: str) -> BinaryIO:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        return open(path, "wb")


class _UploadOnClose(io.BytesIO):
    """Write buffer that publishes atomically on clean close.

    A with-block that raises marks the buffer aborted, so NO partial object
    is ever published; close() is idempotent like every other file object.
    """

    def __init__(self, publish):
        super().__init__()
        self._publish = publish
        self._done = False
        self._aborted = False

    def close(self) -> None:
        if not self._done and not self.closed:
            self._done = True
            if not self._aborted:
                self._publish(self.getvalue())
        super().close()

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self._aborted = True
        self.close()


class PersistS3(PersistBackend):
    """``s3://bucket/key`` via boto3 (gated: clean error when absent)."""

    def __init__(self):
        import boto3  # raises ImportError when the SDK is not in the image

        self._s3 = boto3.client("s3")

    def _split(self, uri: str) -> tuple[str, str]:
        p = urllib.parse.urlparse(uri)
        return p.netloc, p.path.lstrip("/")

    def open_read(self, path: str) -> BinaryIO:
        bucket, key = self._split(path)
        body = self._s3.get_object(Bucket=bucket, Key=key)["Body"].read()
        return io.BytesIO(body)

    def open_write(self, path: str) -> BinaryIO:
        bucket, key = self._split(path)
        return _UploadOnClose(
            lambda data: self._s3.put_object(Bucket=bucket, Key=key, Body=data)
        )


class PersistGS(PersistBackend):
    """``gs://bucket/key`` via google-cloud-storage (gated)."""

    def __init__(self):
        from google.cloud import storage

        self._client = storage.Client()

    def _blob(self, uri: str):
        p = urllib.parse.urlparse(uri)
        return self._client.bucket(p.netloc).blob(p.path.lstrip("/"))

    def open_read(self, path: str) -> BinaryIO:
        return io.BytesIO(self._blob(path).download_as_bytes())

    def open_write(self, path: str) -> BinaryIO:
        blob = self._blob(path)
        return _UploadOnClose(lambda data: blob.upload_from_string(data))


class PersistHDFS(PersistBackend):
    """``hdfs://namenode/path`` via pyarrow's HadoopFileSystem (gated)."""

    def __init__(self):
        from pyarrow import fs

        self._fs_mod = fs
        self._conns: dict[tuple[str, int], object] = {}

    def _fs_path(self, uri: str):
        p = urllib.parse.urlparse(uri)
        host = p.hostname or "default"
        port = p.port or 8020
        conn = self._conns.get((host, port))
        if conn is None:
            conn = self._fs_mod.HadoopFileSystem(host, port)
            self._conns[(host, port)] = conn
        return conn, p.path

    def open_read(self, path: str) -> BinaryIO:
        f, pth = self._fs_path(path)
        return f.open_input_stream(pth)

    def open_write(self, path: str) -> BinaryIO:
        f, pth = self._fs_path(path)
        return f.open_output_stream(pth)


_BACKENDS: dict[str, PersistBackend] = {"file": PersistFS(), "": PersistFS()}

# cloud schemes construct lazily on first touch: the SDK import happens then,
# and a missing SDK surfaces as a clear registration error, not at import
_LAZY_BACKENDS: dict[str, type] = {
    "s3": PersistS3,
    "gs": PersistGS,
    "hdfs": PersistHDFS,
}


def register_backend(scheme: str, backend: PersistBackend) -> None:
    _BACKENDS[scheme] = backend


def _backend_for(uri: str) -> tuple[PersistBackend, str]:
    parsed = urllib.parse.urlparse(uri)
    scheme = parsed.scheme if len(parsed.scheme) > 1 else ""  # windows-drive safe
    b = _BACKENDS.get(scheme)
    if b is None and scheme in _LAZY_BACKENDS:
        try:
            b = _LAZY_BACKENDS[scheme]()
        except ImportError as e:
            raise ValueError(
                f"persist scheme {scheme!r} needs its SDK ({e.name}) which is "
                "not installed in this image; register a backend with "
                "h2o3_tpu.persist.register_backend"
            ) from e
        _BACKENDS[scheme] = b
    if b is None:
        raise ValueError(
            f"no persist backend for scheme {scheme!r} "
            f"(registered: {sorted(k for k in _BACKENDS if k)}); "
            "register one with h2o3_tpu.persist.register_backend"
        )
    path = uri[len(scheme) + 1:].lstrip("/") if scheme == "file" else uri
    if scheme == "file":
        path = "/" + path if not path.startswith("/") else path
    return b, path


# ---------------------------------------------------------------------------
# device → host conversion of the whole model state, in one batched pull


def _pull_tree_output(out: dict) -> dict:
    out = dict(out)
    if "trees" in out:
        # collect every device array across the forest, fetch once
        import dataclasses as _dc

        from h2o3_tpu.models.tree.shared_tree import Tree, TreeLevel

        # derive from the dataclass so new record fields (node_w burned us
        # once: silently-zero TreeSHAP covers after reload) can't be dropped
        fields = tuple(f.name for f in _dc.fields(TreeLevel))
        flat = [
            [[getattr(lv, f) for f in fields] for lv in tree.levels]
            for group in out["trees"] for tree in group
        ]
        pulled = jax.device_get(flat)
        host_trees: list[list[Tree]] = []
        i = 0
        for group in out["trees"]:
            hgroup = []
            for _ in group:
                t = Tree()
                for vals in pulled[i]:
                    t.levels.append(TreeLevel(*[np.asarray(v) for v in vals]))
                hgroup.append(t)
                i += 1
            host_trees.append(hgroup)
        out["trees"] = host_trees
    if "params" in out:  # flax pytree
        out["params"] = jax.device_get(out["params"])
    for k, v in list(out.items()):
        if isinstance(v, jax.Array):
            out[k] = np.asarray(v)
    return out


_STRIP: dict[str, tuple[str, ...]] = {
    "glm": ("family_obj",),
    "deeplearning": ("apply_fn",),
}

_REBUILDERS: dict[str, Callable[[Model], None]] = {}


def _rebuild_glm(model: Model) -> None:
    from h2o3_tpu.models.glm_families import get_family

    p = model.params
    model.output["family_obj"] = get_family(
        model.output["family"], p.link,
        float(p.tweedie_variance_power or 1.5),
        float(p.tweedie_link_power), float(p.theta),
    )


def _rebuild_deeplearning(model: Model) -> None:
    from h2o3_tpu.models.deeplearning import _MLP

    p = model.params
    params = model.output["params"]
    inner = params["params"] if "params" in params else params
    last = sorted(inner.keys(), key=lambda k: int(k.split("_")[-1]))[-1]
    n_out = int(np.asarray(inner[last]["bias"]).shape[0])
    dropout = tuple(p.hidden_dropout_ratios or (0.0,) * len(p.hidden))
    mlp = _MLP(hidden=tuple(p.hidden), n_out=n_out, activation=p.activation,
               dropout=dropout, input_dropout=p.input_dropout_ratio)
    model.output["apply_fn"] = jax.jit(lambda prm, xx: mlp.apply(prm, xx, train=False))


_REBUILDERS["glm"] = _rebuild_glm
_REBUILDERS["deeplearning"] = _rebuild_deeplearning


# ---------------------------------------------------------------------------
# save / load


def serialize_model(model: Model) -> bytes:
    """Model → portable byte string (the device→host pulls happen here).

    Split out of :func:`save_model` so a multi-process cloud can run the
    pulls — collectives when output arrays span processes — on EVERY rank
    while only the coordinator writes the file (cluster/spmd.py)."""
    state = dict(model.__dict__)
    out = _pull_tree_output(state.pop("output"))
    for k in _STRIP.get(model.algo, ()):
        out.pop(k, None)
    state["output"] = out
    payload = {"cls_module": type(model).__module__,
               "cls_name": type(model).__qualname__,
               "algo": model.algo,
               "state": state}
    buf = io.BytesIO()
    buf.write(FORMAT_MAGIC)
    pickle.dump(payload, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return buf.getvalue()


def resolve_model_path(path: str, model_key: str, force: bool = True):
    """(backend, final_path) for a model save; raises FileExistsError when
    ``force`` is off and the target exists. Shared by :func:`save_model` and
    the replicated spmd save command (which writes coordinator-side only)."""
    backend, p = _backend_for(path)
    if os.path.isdir(p) or path.endswith(("/", os.sep)):
        p = os.path.join(p, model_key)
    if os.path.exists(p) and not force:
        raise FileExistsError(p)
    return backend, p


def write_model_bytes(data: bytes, backend, p: str, model_key: str) -> str:
    with backend.open_write(p) as f:
        f.write(data)
    Log.info(f"saved model {model_key} to {p}")
    return p


def save_model(model: Model, path: str, force: bool = True) -> str:
    """``h2o.save_model`` successor. ``path`` may be a directory (H2O
    convention: file named after the model key) or a full file path."""
    backend, p = resolve_model_path(path, model.key, force)
    return write_model_bytes(serialize_model(model), backend, p, model.key)


def load_model(path: str) -> Model:
    """``h2o.load_model`` successor: restores the model into the registry."""
    backend, p = _backend_for(path)
    with backend.open_read(p) as f:
        magic = f.read(len(FORMAT_MAGIC))
        if magic != FORMAT_MAGIC:
            raise ValueError(f"{path}: not an h2o3_tpu model file")
        payload = pickle.load(f)

    import importlib

    cls = getattr(importlib.import_module(payload["cls_module"]), payload["cls_name"].split(".")[0])
    model = cls.__new__(cls)
    model.__dict__.update(payload["state"])
    rebuild = _REBUILDERS.get(payload["algo"])
    if rebuild:
        rebuild(model)
    DKV.put(model.key, model)
    Log.info(f"loaded model {model.key} from {p}")
    return model


def export_file(frame, path: str, force: bool = False, format: str | None = None) -> str:
    """``h2o.export_file`` successor — frame → CSV/Parquet through the
    Persist scheme dispatch (ref upstream water/api FramesHandler export +
    Persist SPI [UNVERIFIED], SURVEY.md §5.4)."""
    return export_df(frame.to_pandas(), path, force=force, format=format)


def export_df(df, path: str, force: bool = False, format: str | None = None) -> str:
    """Write an already-materialized pandas frame (the host pull — a
    collective on multi-process clouds — happens in the caller, so every
    rank can pull while only the coordinator writes; cluster/spmd.py)."""
    backend, p = _backend_for(path)
    if isinstance(backend, PersistFS) and os.path.exists(p) and not force:
        raise FileExistsError(p)
    fmt = (format or "").lower() or ("parquet" if p.endswith((".parquet", ".pq")) else "csv")
    with backend.open_write(p) as f:
        if fmt == "parquet":
            df.to_parquet(f, index=False)
        elif fmt == "csv":
            df.to_csv(f, index=False)
        else:
            raise ValueError(f"unsupported export format {fmt!r}")
    return p
