"""Fleet model registry — watch-and-load distribution over shared storage
(the DKV-replication analog from PAPER.md §1: a model is just a replicated
KV entry any scoring node can serve).

The training side already exports ``serialize_model`` files — final saves,
AutoML winners, interval checkpoints — through the persist SPI. This module
closes the loop for the scoring fleet: every replica points
``H2O3_TPU_SERVE_WATCH_DIR`` at the shared model store (the RWX volume in
deploy/k8s.yaml), and a poll loop (``H2O3_TPU_SERVE_POLL_SECS``) picks up
new/changed files by mtime/size etag (``persist.probe`` — a stat, never a
read) and swaps them in with **generation-tagged atomic swap** semantics:

- each model key carries a monotonically increasing generation; a changed
  file loads into a NEW generation and replaces the registry entry under
  one lock — resolution is atomic;
- in-flight batches finish on the OLD generation: the batcher holds its
  model/scorer by reference, and the swap retires the old generation's
  batcher with drain semantics (serving/batcher.retire_model);
- a snapshot that refuses to load (corrupt, foreign, mid-rollout trash)
  is quarantined by etag and the old generation KEEPS SERVING
  (``serving_rollouts_total{event=failed}``);
- a generation that loads but then fails scoring trips the **rollout
  breaker** (``H2O3_TPU_SERVE_BAD_GEN_ERRORS`` consecutive scoring
  failures, the serving-plane sibling of the PR-10 per-model circuit
  breaker): the registry rolls back to the previous generation, quarantines
  the bad file's etag, and retires the bad model
  (``serving_rollouts_total{event=rolled_back}``).

``H2O3_TPU_SERVE_REGISTRY=0`` disables everything — resolution, watching,
rollback — restoring the PR-7 manual-load behavior bit-for-bit.
``GET /3/ServingRegistry`` (api/server.py) surfaces the entries plus the
residency tiers for the HPA and operators.
"""

from __future__ import annotations

import threading
import time

from h2o3_tpu.serving import ROLLOUTS
from h2o3_tpu.utils.log import Log


def _knob(name: str) -> str:
    from h2o3_tpu import config

    return config.get(name)


def enabled() -> bool:
    """'0' = off; '1' = on; 'auto' = on iff a watch dir is configured."""
    v = _knob("H2O3_TPU_SERVE_REGISTRY")
    if v == "0":
        return False
    if v == "1":
        return True
    return bool(_knob("H2O3_TPU_SERVE_WATCH_DIR"))


class _Generation:
    __slots__ = ("gen", "model", "etag", "path", "loaded_at")

    def __init__(self, gen, model, etag, path, loaded_at):
        self.gen = gen
        self.model = model
        self.etag = etag
        self.path = path
        self.loaded_at = loaded_at


class _KeyEntry:
    __slots__ = ("current", "prev", "failures")

    def __init__(self, current: _Generation):
        self.current = current
        self.prev: _Generation | None = None
        self.failures = 0


class ServingRegistry:
    """Generation-tagged model map + the watch-and-load poll loop."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: dict[str, _KeyEntry] = {}
        self._etags: dict[str, tuple] = {}  # path -> last loaded etag
        self._quarantine: dict[str, tuple] = {}  # path -> bad etag
        self._gen_seq = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._watch_error_logged = False

    # -- resolution (the scoring hot path) ----------------------------------
    def resolve(self, key: str):
        """Current-generation model for ``key``, or None (fall through to
        the DKV — the manual-load path)."""
        if not enabled():
            return None
        with self._lock:
            e = self._entries.get(key)
            return e.current.model if e is not None else None

    def generation_of(self, model) -> int | None:
        with self._lock:
            e = self._entries.get(getattr(model, "key", None))
            if e is not None and e.current.model is model:
                return e.current.gen
        return None

    # -- rollout breaker ----------------------------------------------------
    def note_score_ok(self, key: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.failures:
                e.failures = 0

    def note_score_failure(self, key: str, exc: Exception) -> None:
        """A registry-served model failed a (non-payload) scoring dispatch.
        Past the breaker threshold, roll the key back to its previous
        generation and quarantine the bad snapshot."""
        from h2o3_tpu import config

        thresh = config.get_int("H2O3_TPU_SERVE_BAD_GEN_ERRORS")
        if thresh <= 0 or not enabled():
            return
        retired = None
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return
            e.failures += 1
            if e.failures < thresh or e.prev is None:
                return
            bad = e.current
            self._quarantine[bad.path] = bad.etag
            self._gen_seq += 1
            e.current = _Generation(self._gen_seq, e.prev.model,
                                    e.prev.etag, e.prev.path, time.time())
            e.prev = None
            e.failures = 0
            retired = bad
        from h2o3_tpu.cluster.registry import DKV
        from h2o3_tpu.serving.batcher import retire_model

        DKV.put(key, e.current.model)
        retire_model(key, retired.model)
        ROLLOUTS.inc(event="rolled_back")
        Log.warn(
            f"serving registry rolled BACK model {key}: generation "
            f"{retired.gen} ({retired.path}) tripped the rollout breaker "
            f"({thresh} consecutive scoring failures: {exc!r}); generation "
            f"{e.current.gen} re-serves the previous snapshot and the bad "
            "etag is quarantined until the file changes")

    # -- loading / swapping -------------------------------------------------
    def load_path(self, path: str, etag: tuple | None = None) -> bool:
        """Load one snapshot file and swap it in as a new generation of its
        model key. Returns True on success; a failure quarantines the etag
        and keeps whatever was serving."""
        from h2o3_tpu import persist

        if etag is None:
            etag = persist.probe(path)
        try:
            model = persist.load_model(path)  # DKV.put + closure rebuilds
        except Exception as e:  # noqa: BLE001 — any bad file keeps serving
            if etag is not None:
                self._quarantine[path] = etag
                self._etags[path] = etag
            ROLLOUTS.inc(event="failed")
            Log.err(f"serving registry: snapshot {path} refused to load "
                    f"({e!r}); the previous generation keeps serving")
            return False
        retired = None
        with self._lock:
            self._etags[path] = etag
            self._quarantine.pop(path, None)
            self._gen_seq += 1
            gen = _Generation(self._gen_seq, model, etag, path, time.time())
            e = self._entries.get(model.key)
            if e is None:
                self._entries[model.key] = _KeyEntry(gen)
            else:
                retired = e.current
                e.prev = e.current
                e.current = gen
                e.failures = 0
        model.__dict__["serving_generation"] = gen.gen
        ROLLOUTS.inc(event="loaded")
        Log.info(f"serving registry: model {model.key} generation "
                 f"{gen.gen} loaded from {path}")
        if retired is not None and retired.model is not model:
            # in-flight batches on the old generation finish (drain
            # semantics), THEN its scorer/batcher/thread drop
            from h2o3_tpu.serving.batcher import retire_model

            retire_model(model.key, retired.model)
            ROLLOUTS.inc(event="retired")
        return True

    def poll_once(self) -> int:
        """One watch pass over the configured dir: load every file whose
        etag changed (skipping quarantined etags and in-flight temp files).
        Returns how many snapshots were (re)loaded."""
        watch = _knob("H2O3_TPU_SERVE_WATCH_DIR")
        if not watch or not enabled():
            return 0
        from h2o3_tpu import persist

        try:
            names = persist.list_dir(watch)
        except FileNotFoundError:
            return 0  # the store volume isn't mounted yet; keep polling
        except NotImplementedError:
            if not self._watch_error_logged:
                self._watch_error_logged = True
                Log.err(f"serving registry: persist scheme of {watch!r} "
                        "cannot list/probe — watching disabled (point the "
                        "watch dir at a file: path / mounted volume)")
            return 0
        loaded = 0
        for name in names:
            if name.startswith(".") or name.endswith(".tmp"):
                continue  # atomic-publish temp files mid-write
            path = watch.rstrip("/") + "/" + name
            etag = persist.probe(path)
            if etag is None:
                continue  # vanished between list and stat
            if self._etags.get(path) == etag:
                continue  # unchanged since last load
            if self._quarantine.get(path) == etag:
                continue  # known-bad snapshot; wait for the file to change
            if self.load_path(path, etag):
                loaded += 1
        return loaded

    # -- warm boot (ISSUE 14 satellite: ROADMAP 3c) -------------------------
    def warm_boot(self) -> int:
        """Pre-page the residency LRU with the watch dir's newest N models
        (``H2O3_TPU_SERVE_WARM_MODELS``) and precompile their smallest
        scoring shape bucket, so a fresh HPA replica serves its first
        request at speed instead of paying model load + device page-in +
        XLA compile on the request path. Runs once at watcher start,
        BEFORE the first regular poll (which then picks up the rest).
        Returns how many models were warmed."""
        from h2o3_tpu import config, persist

        n_warm = config.get_int("H2O3_TPU_SERVE_WARM_MODELS")
        watch = _knob("H2O3_TPU_SERVE_WATCH_DIR")
        if n_warm <= 0 or not watch or not enabled():
            return 0
        try:
            names = persist.list_dir(watch)
        except Exception:  # noqa: BLE001 — store not mounted yet: the
            return 0  # regular poll loop keeps trying
        cand = []
        for name in names:
            if name.startswith(".") or name.endswith(".tmp"):
                continue
            path = watch.rstrip("/") + "/" + name
            etag = persist.probe(path)
            if etag is not None:
                cand.append((etag, path))
        try:
            # FS etags are (mtime_ns, size): newest first. Object-store
            # etags are content hashes/generations — no time order exists;
            # the sort is then arbitrary-but-deterministic, which still
            # bounds warm-up to N models.
            cand.sort(key=lambda t: t[0], reverse=True)
        except TypeError:
            cand.sort(key=lambda t: t[1])
        warmed = 0
        for etag, path in cand[:n_warm]:
            if not self.load_path(path, etag):
                continue
            with self._lock:
                entry = next((e for e in self._entries.values()
                              if e.current.path == path), None)
            if entry is None:
                continue
            model = entry.current.model
            try:
                from h2o3_tpu.serving.scorer import scorer_for

                # one all-NA row through the compiled lane: builds (or
                # persistent-cache-loads) the smallest batch bucket's
                # program AND uploads the payload into device residency
                sc = scorer_for(model)
                feats = list(getattr(model, "output", {}).get("names") or ())
                cols, n = sc.prepare([{nm: None for nm in feats}])
                sc.score_table(cols, n)
                warmed += 1
                Log.info(f"serving registry: warmed model {model.key} "
                         f"(lane {sc.lane}) from {path}")
            except Exception as e:  # noqa: BLE001 — warm-up must never
                # block boot; the request path compiles lazily as before
                Log.warn(f"serving registry: warm-up of {path} failed "
                         f"({e!r}); the model still serves (lazy compile)")
        return warmed

    # -- the watcher thread -------------------------------------------------
    def install(self) -> bool:
        """Start the watch loop (idempotent). Returns whether a watcher is
        running after the call."""
        if not enabled() or not _knob("H2O3_TPU_SERVE_WATCH_DIR"):
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return True
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch_loop, name="h2o3-serve-watch", daemon=True)
            self._thread.start()
        return True

    def _watch_loop(self) -> None:
        from h2o3_tpu import config

        try:
            self.warm_boot()  # no-op under H2O3_TPU_SERVE_WARM_MODELS=0
        except Exception as e:  # noqa: BLE001 — the loop must survive
            Log.err(f"serving registry warm boot failed: {e!r}")
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                Log.err(f"serving registry watch pass failed: {e!r}")
            poll = max(config.get_float("H2O3_TPU_SERVE_POLL_SECS"), 0.05)
            self._stop.wait(timeout=poll)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def reset(self) -> None:
        """Test hook: forget everything (models stay in the DKV)."""
        self.stop()
        with self._lock:
            self._entries.clear()
            self._etags.clear()
            self._quarantine.clear()

    # -- observability ------------------------------------------------------
    def status(self) -> dict:
        from h2o3_tpu.serving.residency import MANAGER

        with self._lock:
            models = []
            for key, e in sorted(self._entries.items()):
                g = e.current
                sc = g.model.__dict__.get("_h2o3_batch_scorer")
                models.append({
                    "key": key,
                    "generation": g.gen,
                    "path": g.path,
                    "etag": list(g.etag) if g.etag else None,
                    "loaded_at": g.loaded_at,
                    "failures": e.failures,
                    "lane": sc.lane if sc is not None else None,
                    "residency": (MANAGER.tier_of(sc)
                                  if sc is not None else None),
                })
        return {
            "enabled": enabled(),
            "watch_dir": _knob("H2O3_TPU_SERVE_WATCH_DIR") or None,
            "poll_secs": float(_knob("H2O3_TPU_SERVE_POLL_SECS")),
            "watching": self._thread is not None and self._thread.is_alive(),
            "models": models,
            "residency": MANAGER.status(),
        }


REGISTRY = ServingRegistry()


def resolve(key: str):
    return REGISTRY.resolve(key)


def install() -> bool:
    return REGISTRY.install()
