"""High-throughput scoring tier — the serving-side successor of H2O's
in-cluster ``BigScore`` + external Steam/REST scoring deployments
[UNVERIFIED upstream analogs, SURVEY.md §2.3].

Training got fused and sharded (PR 1/5/6); this package makes *predict* a
device-speed problem too, the way the XGBoost-GPU design (arXiv:1806.11248)
treats inference: tree ensembles only score at hardware speed when requests
are batched into one dispatch. Three pieces:

- :mod:`scorer` — a compiled, shape-bucketed batch scorer per model: the
  whole forest replays as ONE jitted program (donated input buffer), with
  batch row counts rounded up a power-of-two ladder so every batch size in a
  bucket reuses one compiled program — and, through the persistent XLA
  compilation cache (cluster/cloud.py), across *processes*: a rebuilt or
  AutoML-winner model of the same shape bucket compiles zero new programs.
- :mod:`batcher` — a micro-batch coalescing queue per model: concurrent
  ``/3/Predictions/rows`` requests collect for up to
  ``H2O3_TPU_SCORE_BATCH_WINDOW_MS`` (or ``H2O3_TPU_SCORE_BATCH_MAX`` rows)
  and dispatch as one device call, results split back per request.
  ``WINDOW_MS=0`` is the per-request control lane (the load-test A/B).
- the REST surface (``POST /3/Predictions/rows`` in api/server.py): row
  payloads scored directly — no DKV frame round-trip — behind the PR-4
  admission gates with a per-route deadline (``H2O3_TPU_SCORE_DEADLINE_MS``).

The fleet serving plane (ISSUE 12) grows this into a registry-driven
multi-model tier:

- :mod:`registry` — a generation-tagged model registry with a
  watch-and-load loop over shared storage (``H2O3_TPU_SERVE_WATCH_DIR``):
  exported ``serialize_model`` files roll out to every replica within one
  poll, swap atomically (in-flight batches finish on the old generation),
  and bad generations quarantine or roll back (the rollout breaker).
- :mod:`residency` — LRU paging of scorer model payloads under
  ``H2O3_TPU_SERVE_HBM_BYTES``: device memory is a managed cache over the
  host-RAM mirrors, so one replica serves far more models than fit in HBM
  (byte-equal across page-out/page-in).
- :mod:`scorer` lanes beyond the GBM family: DRF/XRT (byte-equal),
  IsolationForest/ExtendedIsolationForest (byte-equal), GLM and
  DeepLearning (1e-6) — all arguments-not-constants, with the generic
  frame-path lane as the documented fallback.

``tools/load_test.py`` is the measured proof: open-loop Poisson arrivals,
offered-QPS sweep, artifact with p50/p99 + shed rate + batch-size
histogram; ``--fleet`` adds the Zipf-over-M-models oversubscription A/B.

Single-process only: the compiled scorer dispatches on local devices without
the SPMD command broadcast, which on a multi-process training cloud would
desync the ranks' collective order. The scoring tier scales OUT instead —
independent single-process replicas behind a load balancer (the HPA'd
``h2o3-tpu-score`` Deployment in deploy/k8s.yaml).
"""

from __future__ import annotations

from h2o3_tpu.utils import metrics as _mx

# -- serving metric families (docs/OBSERVABILITY.md has the runbook rows) ----
REQUESTS = _mx.counter(
    "serving_requests_total",
    "row-scoring requests through the scoring tier, by mode "
    "(batched/inline) and status (ok/shed/error)")
ROWS = _mx.counter(
    "serving_rows_total", "rows scored by the scoring tier")
BATCHES = _mx.counter(
    "serving_batches_total", "batched scoring dispatches")
SHED = _mx.counter(
    "serving_shed_total",
    "scoring requests shed by the tier, by reason: deadline (504 — "
    "saturated), queue_full (429), degraded (503 — the training cloud "
    "degraded while the request was queued/dispatching; failed fast "
    "instead of timing out), breaker_open (503 — the per-model circuit "
    "breaker is open after a cloud failure)")
BREAKER = _mx.counter(
    "serving_breaker_transitions_total",
    "per-model circuit-breaker transitions, by new state: 'open' on a "
    "cloud failure mid-dispatch (subsequent requests shed 503 instantly), "
    "'half_open' when the cloud reports healthy again (ONE probe request "
    "is admitted), 'closed' when the probe succeeds (traffic re-admitted)")
QUEUE_DEPTH = _mx.gauge(
    "serving_queue_depth", "rows waiting in the coalescing queue")
BATCH_OCCUPANCY = _mx.histogram(
    "serving_batch_occupancy",
    "requests coalesced into one scoring dispatch (mean > 1 under load is "
    "the tier doing its job)",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64, 96, 128))
BATCH_ROWS = _mx.histogram(
    "serving_batch_rows", "rows per scoring dispatch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096))
DISPATCH_SECONDS = _mx.histogram(
    "serving_dispatch_seconds",
    "device dispatch wall time of the batch scorer, by lane (tree/generic)")
SCORER_PROGRAMS = _mx.counter(
    "serving_scorer_programs_total",
    "batch-scorer program events, by event: 'compile' = a new "
    "(bucket-shaped) program was built, 'hit' = an existing one was reused. "
    "After warmup a healthy tier is ~all hits — the shape-bucket ladder "
    "collapsing batch sizes and rebuilt same-bucket models onto one program")
MODELS_RESIDENT = _mx.gauge(
    "serving_models_resident",
    "scorer model payloads currently resident, by tier (hbm = device "
    "arguments live in the H2O3_TPU_SERVE_HBM_BYTES LRU, host = demoted "
    "to the host-RAM mirror, page-in on next score)")
MODEL_BYTES = _mx.gauge(
    "serving_model_bytes",
    "bytes of scorer model payloads resident, by tier (hbm/host); the "
    "hbm series is bounded by H2O3_TPU_SERVE_HBM_BYTES (floor: the one "
    "model currently dispatching)")
MODEL_EVICTIONS = _mx.counter(
    "serving_model_evictions_total",
    "scorer model payloads pushed out of the device LRU, by kind: "
    "'demoted' = device arguments dropped to the host tier under HBM "
    "pressure (page-in restores them), 'released' = the scorer was retired "
    "entirely (model deleted / replaced by a new registry generation / "
    "garbage-collected)")
PAGE_IN_SECONDS = _mx.histogram(
    "serving_page_in_seconds",
    "wall time to re-upload a demoted model's scorer device arguments on "
    "its next score (the oversubscription tax; the HPA's signal that the "
    "working set outgrew the fleet)")
ROLLOUTS = _mx.counter(
    "serving_rollouts_total",
    "serving-registry model rollout events, by event: 'loaded' = a "
    "watched snapshot swapped in as a new generation, 'failed' = a "
    "snapshot refused to load (old generation keeps serving), "
    "'rolled_back' = a loaded generation tripped the rollout breaker "
    "(H2O3_TPU_SERVE_BAD_GEN_ERRORS consecutive scoring failures) and the "
    "previous generation was restored, 'retired' = a replaced generation "
    "finished draining and dropped its scorer/batcher")


class ShedError(Exception):
    """A scoring request the tier refused (queue full / deadline exceeded).
    The REST route maps ``status`` + ``retry_after`` onto the PR-4
    overload contract (429/503/504 + Retry-After)."""

    def __init__(self, status: int, msg: str, retry_after: str = "1"):
        super().__init__(msg)
        self.status = status
        self.retry_after = retry_after


def scorer_for(model):
    from h2o3_tpu.serving.scorer import scorer_for as _sf

    return _sf(model)


def score_rows(model, rows):
    """Score a row payload (list of row dicts, or a column table) through the
    coalescing batch scorer. Returns ``{"predict": ..., "<class>": ...}``
    column arrays — the EasyPredict layout, vectorized."""
    from h2o3_tpu.serving.batcher import batcher_for

    sc = scorer_for(model)
    cols, n = sc.prepare(rows)
    return batcher_for(model).submit(cols, n)


def retire_model(model_key: str, model=None) -> None:
    """Drop a model's serving state: its batcher (the dispatcher thread
    drains in-flight work, then exits), its scorer, and its device-resident
    payload. Called on model delete and on registry generation swaps —
    a replaced model must not keep a thread + HBM forever."""
    from h2o3_tpu.serving.batcher import retire_model as _rm

    _rm(model_key, model)
