"""Compiled, shape-bucketed batch scorers — one jitted program per model
*bucket*, not per model or per batch size, for EVERY algo family the fleet
serves (ISSUE 12 closes ROADMAP item 3c: no mainstream algo falls back to
the slow frame path).

Lanes (fallback matrix in docs/MIGRATION.md):

- **tree** (GBM/XGBoost and DRF/XRT): the forest is pre-stacked ONCE into
  host tensors grouped exactly like ``SharedTreeModel._replay_all_dev`` (by
  class, then by recorded depth, in insertion order — the grouping is
  load-bearing for bit-exactness) and the whole replay + head transform
  (link for the GBM family, tree-averaging for the DRF family) compiles
  into a single program. The stacked forest is a program *argument*, so two
  models of the same shape bucket hit the same compiled program.
- **iforest** (IsolationForest, numeric-feature models): the per-tree
  device walk (``_path_lengths``) scans over the stacked ``(T, L, N)``
  split arrays inside ONE program, accumulating path lengths in the frame
  path's tree order; the host tail (c(n) normalizer, 2^-E[h]/c) reuses the
  identical numpy expressions, so scores are byte-equal.
- **eif** (ExtendedIsolationForest): same shape, with per-level oblique
  hyperplane arrays stacked over trees (short trees pad with leaf levels —
  inert by the walk's ``done`` mask).
- **glm** (binomial/regression/multinomial GLMs): the DataInfo transform
  feeds ONE jitted link-transformed matvec (softmax matmul for
  multinomial) whose coefficient vector is an argument; parity 1e-6.
- **dl** (non-autoencoder DeepLearning): the stacked MLP forward + softmax
  as one jitted program keyed by architecture, parameters as arguments;
  parity 1e-6.
- **generic** (everything else — preprocessed/offset models, ordinal GLM,
  autoencoders, categorical-feature IF): the batch still coalesces into
  one ``model.predict`` pass over a temporary frame.

Model payloads (stacked forests, betas, MLP params) are built once as host
numpy pytrees and uploaded through the device-residency LRU
(:mod:`h2o3_tpu.serving.residency`, ``H2O3_TPU_SERVE_HBM_BYTES``): an idle
model costs host RAM, not HBM, and page-out/page-in round-trips bit-exactly.

Bit-exactness contract (pinned by tests/test_serving.py and
tests/test_serving_fleet.py): tree-family lanes are byte-equal to
``Model.predict`` through the frame path — same replay/walk ops in the
same order, no cross-row reductions anywhere; GLM/DL lanes pin 1e-6.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import CAT, Frame, Vec
from h2o3_tpu.serving import DISPATCH_SECONDS, SCORER_PROGRAMS

# ---------------------------------------------------------------------------
# payload adaptation (the adaptTestForTrain analog for row payloads)


def _rows_to_table(rows) -> dict[str, list]:
    """list-of-row-dicts | dict-of-columns -> {col: list}."""
    if isinstance(rows, dict):
        out = {str(k): (list(v) if isinstance(v, (list, tuple, np.ndarray))
                        else [v])
               for k, v in rows.items()}
        ns = {len(v) for v in out.values()}
        if len(ns) > 1:
            raise ValueError(f"ragged column table: lengths {sorted(ns)}")
        return out
    if isinstance(rows, (list, tuple)):
        if not rows:
            raise ValueError("rows is empty")
        if not all(isinstance(r, dict) for r in rows):
            raise ValueError("rows must be dicts of {column: value}")
        keys: list[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(str(k))
        return {k: [r.get(k) for r in rows] for k in keys}
    raise ValueError(f"cannot score rows of type {type(rows).__name__}")


def _coerce_numeric(vals, dtype=np.float32) -> np.ndarray:
    """Payload values -> float with NaN NAs (unparseable strings are NA, the
    parse-time coercion contract). f32 for the binned/stacked lanes; f64
    for lanes whose frame path goes through pandas (GLM/DL design)."""
    out = np.full(len(vals), np.nan, dtype)
    for i, v in enumerate(vals):
        if v is None or (isinstance(v, float) and v != v):
            continue
        if isinstance(v, bool):
            out[i] = 1.0 if v else 0.0
            continue
        if isinstance(v, (int, float, np.integer, np.floating)):
            out[i] = dtype(v)
            continue
        try:
            out[i] = dtype(float(str(v)))
        except (TypeError, ValueError):
            pass  # NA
    return out


def _coerce_cat(vals, domain: tuple) -> np.ndarray:
    """Payload values -> training-domain int32 codes; unseen/None -> -1
    (NA), matching ``_adapt_codes``' unseen-level policy. Numeric payloads
    against a string domain match on their canonical string form ("1" and
    1.0 both hit a "1" level)."""
    lut = {str(d): i for i, d in enumerate(domain or ())}
    out = np.full(len(vals), -1, np.int32)
    for i, v in enumerate(vals):
        if v is None or (isinstance(v, float) and v != v):
            continue
        code = lut.get(v if isinstance(v, str) else str(v), -1)
        if code < 0 and isinstance(v, (int, float, np.integer, np.floating)):
            f = float(v)
            if f.is_integer():
                code = lut.get(str(int(f)), -1)
        out[i] = code
    return out


def bucket_batch_rows(n: int, lo: int = 64) -> int:
    """Batch-row bucket: next power of two (min ``lo`` = one full 8-shard
    row block). Every batch size in a bucket reuses one compiled program —
    the serving twin of the PR-1 row ladder."""
    b = lo
    while b < n:
        b <<= 1
    return b


# ---------------------------------------------------------------------------
# compiled programs, one per lane *structure*; jit's own cache handles the
# shape axes (rows bucket, tree counts, node widths, design columns)


_PROG_CACHE: dict = {}
_SHAPES_SEEN: set = set()
_CACHE_LOCK = threading.Lock()


def _cached_program(struct_key, build):
    prog = _PROG_CACHE.get(struct_key)
    if prog is not None:
        return prog
    prog = build()
    with _CACHE_LOCK:
        _PROG_CACHE.setdefault(struct_key, prog)
    return _PROG_CACHE[struct_key]


def _note_shapes(shape_key) -> None:
    """compile-vs-hit accounting for the serving_scorer_programs_total
    counter (a proxy for jit's per-shape cache, shared across models)."""
    with _CACHE_LOCK:
        seen = shape_key in _SHAPES_SEEN
        _SHAPES_SEEN.add(shape_key)
    SCORER_PROGRAMS.inc(event="hit" if seen else "compile")


def _tree_program(struct_key):
    """One jitted callable per forest *structure*: (head kind, head mode,
    class count, per-class depth-group layout). ``bins`` is donated — it is
    freshly built per batch and dead after the dispatch. The head transform
    mirrors ``GBMModel._predict_raw_dev`` / ``DRFModel._predict_raw_dev``
    op-for-op (the byte-equality contract)."""

    def build():
        head_kind, mode, K = struct_key[0], struct_key[1], struct_key[2]
        from h2o3_tpu.models.tree.distributions import response_transform
        from h2o3_tpu.models.tree.shared_tree import _partition_update

        def run(bins, groups, head):
            outs = []
            for gk in groups:  # per class, by depth like _replay_all_dev
                pk = jnp.zeros(bins.shape[0], jnp.float32)
                for stacked in gk:

                    def body(p, recs):
                        nid = jnp.zeros(bins.shape[0], jnp.int32)
                        for rec in recs:  # unrolled over recorded levels
                            nid, p = _partition_update(
                                bins, nid, p, rec["split_col"],
                                rec["split_bin"], rec["is_cat"],
                                rec["cat_mask"], rec["na_left"],
                                rec["leaf_now"], rec["leaf_val"],
                                rec["child_base"],
                            )
                        return p, None

                    pk, _ = jax.lax.scan(body, pk, stacked)
                outs.append(pk)
            raw = jnp.stack(outs, axis=1) if K > 1 else outs[0]
            if head_kind == "drf":
                avg = raw / head  # head = ntrees (f32 scalar)
                if mode == "reg":
                    return avg
                if mode == "binom":
                    p1 = jnp.clip(avg, 0.0, 1.0)
                    return jnp.stack([1 - p1, p1], axis=1)
                P = jnp.clip(avg, 1e-9, None)
                return P / P.sum(axis=1, keepdims=True)
            # gbm family: head = init_f
            if mode == "multinomial":
                return jax.nn.softmax(raw + head[None, :], axis=1)
            f = raw + head
            mu = response_transform(mode, f)
            if mode == "bernoulli":
                return jnp.stack([1 - mu, mu], axis=1)
            return mu

        return jax.jit(run, donate_argnums=(0,))

    return _cached_program(struct_key, build)


def _iforest_program(struct_key):
    """Scan the frame path's per-tree walk (``_path_lengths``) over the
    stacked forest in insertion order — the accumulation order IS the
    frame path's eager tree loop, so the total is bit-identical."""

    def build():
        n_levels = struct_key[1]
        from h2o3_tpu.models.isolation_forest import _path_lengths

        def run(X, feat, thr, leaf):
            def body(total, tree):
                f, t, ll = tree
                return total + _path_lengths(X, f, t, ll, n_levels), None

            total, _ = jax.lax.scan(
                body, jnp.zeros(X.shape[0], jnp.float32), (feat, thr, leaf))
            return total

        return jax.jit(run)

    return _cached_program(struct_key, build)


def _eif_program(struct_key):
    def build():
        n_levels = struct_key[1]
        from h2o3_tpu.models.extended_isolation_forest import _eif_paths

        def run(X, normals, ds, is_leaf, lens):
            def body(total, tree):
                nr, d_, il, ln = tree
                return total + _eif_paths(X, nr, d_, il, ln, n_levels), None

            total, _ = jax.lax.scan(
                body, jnp.zeros(X.shape[0], jnp.float32),
                (normals, ds, is_leaf, lens))
            return total

        return jax.jit(run)

    return _cached_program(struct_key, build)


def _glm_program(struct_key):
    """Link-transformed matvec (softmax matmul for multinomial) with the
    coefficient vector as an ARGUMENT — one program per family/link config,
    shared by every model that shape-bucket-matches."""

    def build():
        (_, family, link, var_power, link_power, theta, multinomial,
         classifier) = struct_key
        from h2o3_tpu.models.glm import _HI
        from h2o3_tpu.models.glm_families import get_family

        fam = None if multinomial else get_family(
            family, link, var_power, link_power, theta)

        def run(X, beta):
            if multinomial:
                eta = jnp.einsum("np,pk->nk", X, beta, precision=_HI)
                return jax.nn.softmax(eta, axis=1)
            eta = jnp.einsum("np,p->n", X, beta, precision=_HI)
            mu = fam.link.inv(eta)
            if classifier:
                return jnp.stack([1 - mu, mu], axis=1)
            return mu

        return jax.jit(run)

    return _cached_program(struct_key, build)


def _dl_program(struct_key):
    """Stacked MLP forward (+ softmax head) with the parameter pytree as an
    ARGUMENT — one program per architecture."""

    def build():
        _, hidden, activation, n_out, pad, classifier = struct_key
        from h2o3_tpu.models.deeplearning import _MLP

        mlp = _MLP(hidden=tuple(hidden), n_out=n_out, activation=activation,
                   dropout=(0.0,) * len(hidden), input_dropout=0.0)

        def run(X, prm):
            if pad:
                X = jnp.pad(X, ((0, 0), (0, pad)))
            logits = mlp.apply(prm, X, train=False)
            if classifier:
                return jax.nn.softmax(logits, axis=1)
            return logits[:, 0]

        return jax.jit(run)

    return _cached_program(struct_key, build)


def _group_shapes(groups) -> tuple:
    return tuple(
        tuple(
            tuple(sorted((k, v.shape) for k, v in lvl.items()))
            for lvl in stacked
        )
        for gk in groups for stacked in gk
    )


# ---------------------------------------------------------------------------


class BatchScorer:
    """Per-model scorer. ``prepare`` adapts a payload to canonical column
    arrays (cheap host work, runs on the request thread); ``score_table``
    runs one device pass over a whole coalesced batch, holding the model's
    device payload through the residency LRU."""

    def __init__(self, model):
        self.model = model
        self.model_key = model.key
        self.lane = "generic"
        self._lock = threading.Lock()  # one dispatch at a time per model
        self._host_args = None  # numpy pytree; the pageable device payload
        out = model.output if isinstance(model.output, dict) else {}
        if model.preprocessors or getattr(
                model.params, "offset_column", None):
            return  # generic: per-algo preprocessing owns these paths
        from h2o3_tpu.models.deeplearning import DeepLearningModel
        from h2o3_tpu.models.extended_isolation_forest import (
            ExtendedIsolationForestModel,
        )
        from h2o3_tpu.models.glm import GLMModel
        from h2o3_tpu.models.isolation_forest import IsolationForestModel
        from h2o3_tpu.models.tree.gbm import GBMModel, SharedTreeModel

        if (isinstance(model, SharedTreeModel)
                and out.get("trees") and out.get("bin_spec") is not None
                and model.algo in ("gbm", "xgboost", "drf", "xrt")):
            self._init_tree(out, gbm_family=isinstance(model, GBMModel))
        elif (isinstance(model, IsolationForestModel) and out.get("trees")
                and out.get("feature_kinds") is not None
                and (all(k == "num" for k in out["feature_kinds"])
                     or out.get("feature_domains") is not None)):
            # categorical forests ride the lane when the model carries its
            # TRAINING-domain feature codes (ISSUE 14) — payload values
            # then encode through _coerce_cat byte-identically to the
            # frame path's training-domain remap; older snapshots without
            # feature_domains stay numeric-only (generic lane otherwise)
            self._init_iforest(out)
        elif (isinstance(model, ExtendedIsolationForestModel)
                and out.get("stacked_trees")):
            self._init_eif(out)
        elif (isinstance(model, GLMModel) and not out.get("ordinal")
                and out.get("datainfo") is not None
                and not any(c.pair for c in out["datainfo"].columns)):
            self._init_glm(out)
        elif (isinstance(model, DeepLearningModel)
                and not out.get("autoencoder")
                and out.get("datainfo") is not None
                and not any(c.pair for c in out["datainfo"].columns)):
            self._init_dl(out)
        if self._host_args is not None:
            from h2o3_tpu.serving.residency import MANAGER

            MANAGER.register(self)

    # -- lane constructors (host-tier payload stacking) ---------------------
    def _init_tree(self, out, gbm_family: bool) -> None:
        self.lane = "tree"
        self._spec = out["bin_spec"]
        self._K = out.get("n_tree_classes", 1)
        groups = self._stack_forest(out["trees"])
        if gbm_family:
            dist = out["distribution"]
            if dist == "multinomial":
                head = np.asarray(out["init_f"], np.float32)
            else:
                head = np.float32(out["init_f"])
            kind, mode = "gbm", dist
        else:
            m = self.model
            mode = ("reg" if not m.is_classifier
                    else ("binom" if self._K == 1 else "multi"))
            head = np.float32(max(out["ntrees_actual"], 1))
            kind = "drf"
        self._host_args = {"groups": groups, "head": head}
        self._struct = (
            kind, mode, self._K,
            tuple(tuple(len(s) for s in gk) for gk in groups),
            jax.default_backend(),
        )

    def _stack_forest(self, trees):
        """Stack per-(class, depth) groups in the SAME insertion order as
        ``SharedTreeModel._replay_all_dev`` — the accumulation order is part
        of the bit-exactness contract. Host numpy; the residency LRU owns
        the device copies."""
        from collections import defaultdict

        from h2o3_tpu.models.tree.gbm import SharedTreeModel

        fields = SharedTreeModel._REPLAY_FIELDS
        groups = []
        for k in range(self._K):
            by_depth = defaultdict(list)
            for group in trees:
                t = group[k]
                by_depth[len(t.levels)].append(t)
            gk = []
            for depth, ts in by_depth.items():
                vals = jax.device_get(
                    [
                        [
                            [getattr(t.levels[li], f) for f in fields]
                            for li in range(depth)
                        ]
                        for t in ts
                    ]
                )
                stacked = tuple(
                    {
                        f: np.stack([vals[ti][li][fi]
                                     for ti in range(len(ts))])
                        for fi, f in enumerate(fields)
                    }
                    for li in range(depth)
                )
                gk.append(stacked)
            groups.append(tuple(gk))
        return tuple(groups)

    def _init_iforest(self, out) -> None:
        trees = out["trees"]
        shapes = {np.asarray(f).shape for f, _t, _l in trees}
        if len(shapes) != 1:
            return  # ragged forest (shouldn't happen): generic lane
        self.lane = "iforest"
        self._names = list(out["names"])
        self._domains = list(
            out.get("feature_domains") or [None] * len(self._names))
        self._host_args = {
            "feat": np.stack([np.asarray(f, np.int32) for f, _, _ in trees]),
            "thr": np.stack([np.asarray(t, np.float32)
                             for _, t, _ in trees]),
            "leaf": np.stack([np.asarray(ll, np.float32)
                              for _, _, ll in trees]),
        }
        self._struct = ("iforest", int(shapes.pop()[0]),
                        jax.default_backend())

    def _init_eif(self, out) -> None:
        self.lane = "eif"
        self._names = list(out["names"])
        self._col_means = np.asarray(out["col_means"], np.float64)
        stacked = out["stacked_trees"]
        T = len(stacked)
        C = len(self._names)
        L = max(len(levels) for levels in stacked)
        normals, ds, is_leaf, lens = [], [], [], []
        for d in range(L):
            w = 1 << d
            nr = np.zeros((T, w, C), np.float32)
            dd = np.zeros((T, w), np.float32)
            il = np.ones((T, w), bool)  # pad levels are all-leaf (inert)
            ln = np.zeros((T, w), np.float32)
            for ti, levels in enumerate(stacked):
                if d < len(levels):
                    nr[ti], dd[ti], il[ti], ln[ti] = levels[d]
            normals.append(nr)
            ds.append(dd)
            is_leaf.append(il)
            lens.append(ln)
        self._host_args = {"normals": tuple(normals), "ds": tuple(ds),
                           "is_leaf": tuple(is_leaf), "lens": tuple(lens)}
        self._struct = ("eif", L, C, jax.default_backend())

    def _init_glm(self, out) -> None:
        self.lane = "glm"
        self._di = out["datainfo"]
        p = self.model.params
        multinomial = bool(out.get("multinomial"))
        beta = (out["beta_multinomial_std"] if multinomial
                else out["beta_std"])
        self._host_args = {"beta": np.asarray(beta, np.float32)}
        self._struct = (
            "glm", out["family"], p.link,
            float(p.tweedie_variance_power or 1.5),
            float(p.tweedie_link_power), float(p.theta),
            multinomial, self.model.is_classifier,
        )

    def _init_dl(self, out) -> None:
        self.lane = "dl"
        self._di = out["datainfo"]
        params = jax.device_get(out["params"])
        inner = params["params"] if "params" in params else params
        last = sorted(inner.keys(), key=lambda k: int(k.split("_")[-1]))[-1]
        n_out = int(np.asarray(inner[last]["bias"]).shape[0])
        hidden = tuple(out.get("hidden") or self.model.params.hidden)
        self._host_args = {"params": params}
        self._struct = (
            "dl", tuple(int(h) for h in hidden),
            self.model.params.activation, n_out,
            int(out.get("input_pad") or 0), self.model.is_classifier,
        )

    # -- payload -> canonical columns ---------------------------------------
    def prepare(self, rows) -> tuple[dict[str, np.ndarray], int]:
        table = _rows_to_table(rows)
        ns = {len(v) for v in table.values()}
        if not ns or max(ns) == 0:
            raise ValueError("rows is empty")
        n = ns.pop()
        if self.lane == "tree":
            spec = self._spec
            cols = {}
            for ci, name in enumerate(spec.names):
                vals = table.get(name)
                if vals is None:
                    vals = [None] * n  # absent column scores as all-NA
                if spec.is_cat[ci]:
                    dom = (spec.domains[ci] if spec.domains else None) or ()
                    cols[name] = _coerce_cat(vals, tuple(dom))
                else:
                    cols[name] = _coerce_numeric(vals)
            return cols, n
        if self.lane in ("iforest", "eif"):
            doms = (getattr(self, "_domains", None)
                    if self.lane == "iforest" else None)
            cols = {}
            for ci, name in enumerate(self._names):
                vals = table.get(name) or [None] * n
                dom = doms[ci] if doms else None
                # categorical features encode into TRAINING-domain codes
                # (unseen/None -> -1) — the same floats the frame path's
                # training-domain remap produces, so the lane stays
                # byte-equal on categorical frames too
                cols[name] = (
                    _coerce_cat(vals, tuple(dom)).astype(np.float32)
                    if dom else _coerce_numeric(vals))
            return cols, n
        if self.lane in ("glm", "dl"):
            # normalized to the DataInfo base columns so coalesced batches
            # always concatenate the same column set; the frame-adaptation
            # path (from_pandas kinds + _adapt_codes) does the rest
            cols = {}
            for c in self._di.columns:
                vals = table.get(c.name)
                if vals is None:
                    vals = [None] * n
                if c.kind == "num":
                    cols[c.name] = _coerce_numeric(vals, np.float64)
                else:  # cat / hash: raw values, coded against the frame
                    cols[c.name] = np.asarray(list(vals), dtype=object)
            return cols, n
        # generic lane: raw object columns; the model's own frame-adaptation
        # path (from_pandas kinds + per-algo adapt) does the rest
        return {k: np.asarray(v, dtype=object) for k, v in table.items()}, n

    # -- scoring ------------------------------------------------------------
    def score_table(self, cols: dict[str, np.ndarray], n: int) -> dict:
        from h2o3_tpu.utils import flightrec as _fr

        t0 = time.perf_counter()
        with _fr.dispatch("serving_batch", lane=self.lane,
                          model=self.model_key, rows=n):
            with self._lock:
                if self.lane == "generic":
                    out = self._score_generic(cols, n)
                else:
                    from h2o3_tpu.serving.residency import MANAGER

                    with MANAGER.hold(self) as dev:
                        out = getattr(self, "_score_" + self.lane)(
                            cols, n, dev)
        DISPATCH_SECONDS.observe(time.perf_counter() - t0, lane=self.lane)
        return out

    def _score_tree(self, cols, n: int, dev) -> dict:
        from h2o3_tpu.models.tree.binning import bin_frame

        spec = self._spec
        b = bucket_batch_rows(n)
        vecs, names = [], []
        for ci, name in enumerate(spec.names):
            arr = cols[name]
            if spec.is_cat[ci]:
                pad = np.full(b, -1, np.int32)
                pad[:n] = arr
                dom = (spec.domains[ci] if spec.domains else None) or ()
                vecs.append(Vec.from_numpy(pad, CAT, name=name,
                                           domain=tuple(dom)))
            else:
                pad = np.full(b, np.nan, np.float32)
                pad[:n] = arr
                vecs.append(Vec.from_numpy(pad, "real", name=name))
            names.append(name)
        fr = Frame(vecs, names)  # unregistered temporary
        bins = bin_frame(spec, fr)
        _note_shapes((self._struct, bins.shape,
                      _group_shapes(self._host_args["groups"])))
        prog = _tree_program(self._struct)
        raw = np.asarray(jax.device_get(
            prog(bins, dev["groups"], dev["head"])))[:n]
        if not self.model.is_classifier:
            return {"predict": raw.astype(np.float32, copy=False)}
        return self._format_probs(raw, n)

    def _score_iforest(self, cols, n: int, dev) -> dict:
        b = bucket_batch_rows(n)
        X = np.full((b, len(self._names)), np.nan, np.float32)
        for ci, name in enumerate(self._names):
            X[:n, ci] = cols[name]
        _note_shapes((self._struct, X.shape, self._host_args["feat"].shape))
        prog = _iforest_program(self._struct)
        total = np.asarray(jax.device_get(
            prog(jnp.asarray(X), dev["feat"], dev["thr"], dev["leaf"])))[:n]
        ntrees = len(self._host_args["feat"])
        # host tail mirrors IsolationForestModel._predict_raw op-for-op
        from h2o3_tpu.models.isolation_forest import _c

        mean_len = total / ntrees
        cn = _c(self.model.params.sample_size)
        score = np.power(2.0, -mean_len / max(cn, 1e-9))
        return {"predict": np.asarray(score, np.float32),
                "mean_length": np.asarray(mean_len, np.float32)}

    def _score_eif(self, cols, n: int, dev) -> dict:
        b = bucket_batch_rows(n)
        C = len(self._names)
        X64 = np.full((b, C), np.nan, np.float64)
        for ci, name in enumerate(self._names):
            X64[:n, ci] = cols[name].astype(np.float64)
        X = np.where(np.isnan(X64), self._col_means[None, :],
                     X64).astype(np.float32)
        _note_shapes((self._struct, X.shape))
        prog = _eif_program(self._struct)
        total = np.asarray(jax.device_get(prog(
            jnp.asarray(X), dev["normals"], dev["ds"], dev["is_leaf"],
            dev["lens"])))[:n]
        # host tail mirrors ExtendedIsolationForestModel._predict_raw
        from h2o3_tpu.models.extended_isolation_forest import _c

        ntrees = len(self._host_args["normals"][0])
        mean_len = total / max(ntrees, 1)
        score = 2.0 ** (-mean_len / max(_c(self.model.output["sample_size"]),
                                        1e-9))
        return {"anomaly_score": np.asarray(score, np.float32),
                "mean_length": np.asarray(mean_len, np.float32)}

    def _design_matrix(self, cols, n: int):
        """Payload columns -> the model's (padded-bucket, p) design matrix
        through the SAME DataInfo transform as the frame path."""
        import pandas as pd

        b = bucket_batch_rows(n)
        padded = {}
        for name, arr in cols.items():
            if arr.dtype == object:
                buf = np.full(b, None, dtype=object)
            else:
                buf = np.full(b, np.nan, arr.dtype)
            buf[:n] = arr
            padded[name] = buf
        fr = Frame.from_pandas(pd.DataFrame(padded))
        X, _ = self._di.transform(fr)
        return X

    def _score_glm(self, cols, n: int, dev) -> dict:
        X = self._design_matrix(cols, n)
        _note_shapes((self._struct, X.shape, dev["beta"].shape))
        prog = _glm_program(self._struct)
        raw = np.asarray(jax.device_get(prog(X, dev["beta"])))[:n]
        if not self.model.is_classifier:
            return {"predict": raw.astype(np.float32, copy=False)}
        return self._format_probs(raw, n)

    def _score_dl(self, cols, n: int, dev) -> dict:
        X = self._design_matrix(cols, n)
        _note_shapes((self._struct, X.shape))
        prog = _dl_program(self._struct)
        raw = np.asarray(jax.device_get(prog(X, dev["params"])))[:n]
        if not self.model.is_classifier:
            return {"predict": raw.astype(np.float32, copy=False)}
        return self._format_probs(raw, n)

    def _format_probs(self, raw: np.ndarray, n: int) -> dict:
        """Label + probability columns from raw predictions — the same host
        math as ``Model.predict`` (threshold, calibration), so the two
        surfaces cannot disagree."""
        m = self.model
        domain = m.output["response_domain"]
        probs = raw if raw.ndim > 1 else np.stack([1 - raw, raw], axis=1)
        if m.nclasses == 2:
            thr = 0.5
            if m.training_metrics is not None:
                thr = m.training_metrics._v.get("default_threshold", 0.5)
            idx = (probs[:, 1] >= thr).astype(np.int32)
        else:
            idx = probs.argmax(axis=1).astype(np.int32)
        out = {"predict": np.asarray(domain, dtype=object)[idx]}
        for k, d in enumerate(domain):
            out[str(d)] = probs[:, k]
        cal = m.output.get("calibration")
        if cal is not None and probs.shape[1] == 2:
            from h2o3_tpu.models.calibration import apply_calibration

            cp1 = apply_calibration(cal, probs[:, 1])
            out["cal_p0"] = 1.0 - cp1
            out["cal_p1"] = cp1
        return out

    def _score_generic(self, cols, n: int) -> dict:
        import pandas as pd

        df = pd.DataFrame({k: v for k, v in cols.items()})
        fr = Frame.from_pandas(df)
        pf = self.model.predict(fr)
        out = {}
        for name in pf.names:
            v = pf.vec(name)
            if v.is_categorical():
                codes = v.to_numpy()
                dom = np.asarray(v.domain, dtype=object)
                col = np.full(len(codes), None, dtype=object)
                ok = codes >= 0
                col[ok] = dom[codes[ok]]
                out[name] = col[:n]
            else:
                out[name] = v.to_numpy()[:n]
        return out


def scorer_for(model) -> BatchScorer:
    """The per-model scorer, cached on the model object (models are
    immutable after build; the cache — and the residency entry, via its
    weakref — dies with the model)."""
    sc = model.__dict__.get("_h2o3_batch_scorer")
    if sc is None:
        sc = BatchScorer(model)
        model.__dict__["_h2o3_batch_scorer"] = sc
    return sc
