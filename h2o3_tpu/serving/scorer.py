"""Compiled, shape-bucketed batch scorer — one jitted program per model
*bucket*, not per model or per batch size.

Two lanes:

- **tree** (GBM-family models): the forest is pre-stacked ONCE into device
  tensors grouped exactly like ``SharedTreeModel._replay_all_dev`` (by class,
  then by recorded depth, in insertion order — the grouping is load-bearing
  for bit-exactness), and the whole replay + link transform compiles into a
  single program. The stacked forest is a program *argument*, so two models
  of the same shape bucket (same ntrees/depth/bins/cols ladder rungs — e.g.
  an AutoML winner rebuilt on refreshed data) hit the same compiled program;
  with the persistent XLA cache (cluster/cloud.py) that holds across
  processes too. Batch row counts round up a power-of-two ladder
  (:func:`bucket_batch_rows`) so every batch size in a bucket reuses one
  program; padding rows carry only NA codes and their outputs are sliced
  off — per-row elementwise replay makes the pad inert by construction
  (same argument as the PR-1 shape buckets).
- **generic** (every other algo, preprocessed/offset models): the batch
  still coalesces into one ``model.predict`` pass over a temporary frame —
  batched, just not single-program.

Bit-exactness contract (pinned by tests/test_serving.py): the tree lane's
probabilities are byte-equal to ``Model.predict`` through the frame path —
same ``_partition_update`` ops in the same order, same link transform, and
no cross-row reductions anywhere in scoring.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import CAT, Frame, Vec
from h2o3_tpu.serving import DISPATCH_SECONDS, SCORER_PROGRAMS

# ---------------------------------------------------------------------------
# payload adaptation (the adaptTestForTrain analog for row payloads)


def _rows_to_table(rows) -> dict[str, list]:
    """list-of-row-dicts | dict-of-columns -> {col: list}."""
    if isinstance(rows, dict):
        out = {str(k): (list(v) if isinstance(v, (list, tuple, np.ndarray))
                        else [v])
               for k, v in rows.items()}
        ns = {len(v) for v in out.values()}
        if len(ns) > 1:
            raise ValueError(f"ragged column table: lengths {sorted(ns)}")
        return out
    if isinstance(rows, (list, tuple)):
        if not rows:
            raise ValueError("rows is empty")
        if not all(isinstance(r, dict) for r in rows):
            raise ValueError("rows must be dicts of {column: value}")
        keys: list[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(str(k))
        return {k: [r.get(k) for r in rows] for k in keys}
    raise ValueError(f"cannot score rows of type {type(rows).__name__}")


def _coerce_numeric(vals) -> np.ndarray:
    """Payload values -> f32 with NaN NAs (unparseable strings are NA, the
    parse-time coercion contract)."""
    out = np.full(len(vals), np.nan, np.float32)
    for i, v in enumerate(vals):
        if v is None or (isinstance(v, float) and v != v):
            continue
        if isinstance(v, bool):
            out[i] = 1.0 if v else 0.0
            continue
        if isinstance(v, (int, float, np.integer, np.floating)):
            out[i] = np.float32(v)
            continue
        try:
            out[i] = np.float32(float(str(v)))
        except (TypeError, ValueError):
            pass  # NA
    return out


def _coerce_cat(vals, domain: tuple) -> np.ndarray:
    """Payload values -> training-domain int32 codes; unseen/None -> -1
    (NA), matching ``_adapt_codes``' unseen-level policy. Numeric payloads
    against a string domain match on their canonical string form ("1" and
    1.0 both hit a "1" level)."""
    lut = {str(d): i for i, d in enumerate(domain or ())}
    out = np.full(len(vals), -1, np.int32)
    for i, v in enumerate(vals):
        if v is None or (isinstance(v, float) and v != v):
            continue
        code = lut.get(v if isinstance(v, str) else str(v), -1)
        if code < 0 and isinstance(v, (int, float, np.integer, np.floating)):
            f = float(v)
            if f.is_integer():
                code = lut.get(str(int(f)), -1)
        out[i] = code
    return out


def bucket_batch_rows(n: int, lo: int = 64) -> int:
    """Batch-row bucket: next power of two (min ``lo`` = one full 8-shard
    row block). Every batch size in a bucket reuses one compiled program —
    the serving twin of the PR-1 row ladder."""
    b = lo
    while b < n:
        b <<= 1
    return b


# ---------------------------------------------------------------------------
# the compiled tree-lane program


_PROG_CACHE: dict = {}
_SHAPES_SEEN: set = set()
_CACHE_LOCK = threading.Lock()


def _tree_program(struct_key):
    """One jitted callable per forest *structure* (distribution, class count,
    per-class depth-group layout); jit's own cache handles the shape axes
    (rows bucket, tree counts, node widths). ``bins`` is donated — it is
    freshly built per batch and dead after the dispatch."""
    prog = _PROG_CACHE.get(struct_key)
    if prog is not None:
        return prog
    dist, K = struct_key[0], struct_key[1]
    from h2o3_tpu.models.tree.distributions import response_transform
    from h2o3_tpu.models.tree.shared_tree import _partition_update

    def run(bins, groups, init_f):
        outs = []
        for gk in groups:  # per class, grouped by depth like _replay_all_dev
            pk = jnp.zeros(bins.shape[0], jnp.float32)
            for stacked in gk:

                def body(p, recs):
                    nid = jnp.zeros(bins.shape[0], jnp.int32)
                    for rec in recs:  # unrolled over the recorded levels
                        nid, p = _partition_update(
                            bins, nid, p, rec["split_col"], rec["split_bin"],
                            rec["is_cat"], rec["cat_mask"], rec["na_left"],
                            rec["leaf_now"], rec["leaf_val"],
                            rec["child_base"],
                        )
                    return p, None

                pk, _ = jax.lax.scan(body, pk, stacked)
            outs.append(pk)
        raw = jnp.stack(outs, axis=1) if K > 1 else outs[0]
        if dist == "multinomial":
            return jax.nn.softmax(raw + init_f[None, :], axis=1)
        f = raw + init_f
        mu = response_transform(dist, f)
        if dist == "bernoulli":
            return jnp.stack([1 - mu, mu], axis=1)
        return mu

    prog = jax.jit(run, donate_argnums=(0,))
    with _CACHE_LOCK:
        _PROG_CACHE.setdefault(struct_key, prog)
    return _PROG_CACHE[struct_key]


def _group_shapes(groups) -> tuple:
    return tuple(
        tuple(
            tuple(sorted((k, v.shape) for k, v in lvl.items()))
            for lvl in stacked
        )
        for gk in groups for stacked in gk
    )


# ---------------------------------------------------------------------------


class BatchScorer:
    """Per-model scorer. ``prepare`` adapts a payload to canonical column
    arrays (cheap host work, runs on the request thread); ``score_table``
    runs one device pass over a whole coalesced batch."""

    def __init__(self, model):
        self.model = model
        self.lane = "generic"
        self._lock = threading.Lock()  # one dispatch at a time per model
        out = model.output if isinstance(model.output, dict) else {}
        from h2o3_tpu.models.tree.gbm import GBMModel

        if (
            isinstance(model, GBMModel)
            and out.get("trees")
            and out.get("bin_spec") is not None
            and not model.preprocessors
            and not getattr(model.params, "offset_column", None)
        ):
            self.lane = "tree"
            self._spec = out["bin_spec"]
            self._dist = out["distribution"]
            self._K = out.get("n_tree_classes", 1)
            self._stack_forest(out["trees"])
            if self._dist == "multinomial":
                self._init_f = jnp.asarray(
                    np.asarray(out["init_f"], np.float32))
            else:
                self._init_f = jnp.asarray(np.float32(out["init_f"]))
            self._struct = (
                self._dist, self._K,
                tuple(tuple(len(s) for s in gk) for gk in self._groups_key),
                jax.default_backend(),
            )

    # -- forest stacking (once per model) -----------------------------------
    def _stack_forest(self, trees) -> None:
        """Stack per-(class, depth) groups in the SAME insertion order as
        ``SharedTreeModel._replay_all_dev`` — the accumulation order is part
        of the bit-exactness contract."""
        from collections import defaultdict

        from h2o3_tpu.models.tree.gbm import SharedTreeModel

        fields = SharedTreeModel._REPLAY_FIELDS
        groups = []
        for k in range(self._K):
            by_depth = defaultdict(list)
            for group in trees:
                t = group[k]
                by_depth[len(t.levels)].append(t)
            gk = []
            for depth, ts in by_depth.items():
                vals = jax.device_get(
                    [
                        [
                            [getattr(t.levels[li], f) for f in fields]
                            for li in range(depth)
                        ]
                        for t in ts
                    ]
                )
                stacked = tuple(
                    {
                        f: jnp.asarray(
                            np.stack([vals[ti][li][fi]
                                      for ti in range(len(ts))])
                        )
                        for fi, f in enumerate(fields)
                    }
                    for li in range(depth)
                )
                gk.append(stacked)
            groups.append(tuple(gk))
        self._groups = tuple(groups)
        self._groups_key = self._groups

    # -- payload -> canonical columns ---------------------------------------
    def prepare(self, rows) -> tuple[dict[str, np.ndarray], int]:
        table = _rows_to_table(rows)
        ns = {len(v) for v in table.values()}
        if not ns or max(ns) == 0:
            raise ValueError("rows is empty")
        n = ns.pop()
        if self.lane == "tree":
            spec = self._spec
            cols = {}
            for ci, name in enumerate(spec.names):
                vals = table.get(name)
                if vals is None:
                    vals = [None] * n  # absent column scores as all-NA
                if spec.is_cat[ci]:
                    dom = (spec.domains[ci] if spec.domains else None) or ()
                    cols[name] = _coerce_cat(vals, tuple(dom))
                else:
                    cols[name] = _coerce_numeric(vals)
            return cols, n
        # generic lane: raw object columns; the model's own frame-adaptation
        # path (from_pandas kinds + per-algo adapt) does the rest
        return {k: np.asarray(v, dtype=object) for k, v in table.items()}, n

    # -- scoring ------------------------------------------------------------
    def score_table(self, cols: dict[str, np.ndarray], n: int) -> dict:
        t0 = time.perf_counter()
        with self._lock:
            out = (self._score_tree(cols, n) if self.lane == "tree"
                   else self._score_generic(cols, n))
        DISPATCH_SECONDS.observe(time.perf_counter() - t0, lane=self.lane)
        return out

    def _score_tree(self, cols, n: int) -> dict:
        from h2o3_tpu.models.tree.binning import bin_frame

        spec = self._spec
        b = bucket_batch_rows(n)
        vecs, names = [], []
        for ci, name in enumerate(spec.names):
            arr = cols[name]
            if spec.is_cat[ci]:
                pad = np.full(b, -1, np.int32)
                pad[:n] = arr
                dom = (spec.domains[ci] if spec.domains else None) or ()
                vecs.append(Vec.from_numpy(pad, CAT, name=name,
                                           domain=tuple(dom)))
            else:
                pad = np.full(b, np.nan, np.float32)
                pad[:n] = arr
                vecs.append(Vec.from_numpy(pad, "real", name=name))
            names.append(name)
        fr = Frame(vecs, names)  # unregistered temporary
        bins = bin_frame(spec, fr)
        shape_key = (self._struct, bins.shape,
                     _group_shapes(self._groups_key))
        with _CACHE_LOCK:
            seen = shape_key in _SHAPES_SEEN
            _SHAPES_SEEN.add(shape_key)
        SCORER_PROGRAMS.inc(event="hit" if seen else "compile")
        prog = _tree_program(self._struct)
        raw = np.asarray(jax.device_get(prog(bins, self._groups,
                                             self._init_f)))[:n]
        return self._format_tree(raw, n)

    def _format_tree(self, raw: np.ndarray, n: int) -> dict:
        """Label + probability columns from raw predictions — the same host
        math as ``Model.predict`` (threshold, calibration), so the two
        surfaces cannot disagree."""
        m = self.model
        if not m.is_classifier:
            return {"predict": raw.astype(np.float32, copy=False)}
        domain = m.output["response_domain"]
        probs = raw if raw.ndim > 1 else np.stack([1 - raw, raw], axis=1)
        if m.nclasses == 2:
            thr = 0.5
            if m.training_metrics is not None:
                thr = m.training_metrics._v.get("default_threshold", 0.5)
            idx = (probs[:, 1] >= thr).astype(np.int32)
        else:
            idx = probs.argmax(axis=1).astype(np.int32)
        out = {"predict": np.asarray(domain, dtype=object)[idx]}
        for k, d in enumerate(domain):
            out[str(d)] = probs[:, k]
        cal = m.output.get("calibration")
        if cal is not None and probs.shape[1] == 2:
            from h2o3_tpu.models.calibration import apply_calibration

            cp1 = apply_calibration(cal, probs[:, 1])
            out["cal_p0"] = 1.0 - cp1
            out["cal_p1"] = cp1
        return out

    def _score_generic(self, cols, n: int) -> dict:
        import pandas as pd

        df = pd.DataFrame({k: v for k, v in cols.items()})
        fr = Frame.from_pandas(df)
        pf = self.model.predict(fr)
        out = {}
        for name in pf.names:
            v = pf.vec(name)
            if v.is_categorical():
                codes = v.to_numpy()
                dom = np.asarray(v.domain, dtype=object)
                col = np.full(len(codes), None, dtype=object)
                ok = codes >= 0
                col[ok] = dom[codes[ok]]
                out[name] = col[:n]
            else:
                out[name] = v.to_numpy()[:n]
        return out


def scorer_for(model) -> BatchScorer:
    """The per-model scorer, cached on the model object (models are
    immutable after build; the cache dies with the model)."""
    sc = model.__dict__.get("_h2o3_batch_scorer")
    if sc is None:
        sc = BatchScorer(model)
        model.__dict__["_h2o3_batch_scorer"] = sc
    return sc
