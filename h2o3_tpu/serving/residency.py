"""Device-residency paging for scorer model payloads — the serving twin of
the PR-11 ChunkStore window (frame/chunkstore.py): device memory is a
managed cache, not a ledger of everything ever scored.

Every compiled scorer lane keeps its model payload (stacked forest level
arrays, GLM coefficient vectors, DL parameter pytrees, IF/EIF stacked
trees) twice:

- a **host tier** numpy pytree, built once at scorer construction — the
  authoritative copy, cheap RAM;
- a **device tier** jax pytree, uploaded on demand through an LRU bounded
  by ``H2O3_TPU_SERVE_HBM_BYTES`` (0 = unbounded, the pre-fleet behavior).

A score acquires the device pytree via :meth:`ResidencyManager.hold`; a
miss pages the host copy in (``serving_page_in_seconds``), evicting the
least-recently-scored *other* models first — the ChunkStore pre-insert
pattern, so the budget bounds PEAK residency, with the documented floor of
the one model currently dispatching. Eviction is **demotion**: the device
arrays drop, the host pytree stays, and the next score re-uploads a
bit-identical copy (device_get → device_put round-trips exactly, so scores
are byte-equal across page-out/page-in — pinned by
tests/test_serving_fleet.py). Full **release** happens only when a model
is retired (deleted, replaced by a new registry generation) or its scorer
is garbage-collected — entries hold the scorer by weakref, so a dead model
returns its bytes instead of leaking them.

Observability: ``serving_models_resident{tier}`` / ``serving_model_bytes
{tier}`` gauges, ``serving_model_evictions_total{kind}`` and the page-in
histogram feed the HPA (deploy/k8s.yaml): sustained page-in traffic means
the fleet's working set outgrew its replicas.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from contextlib import contextmanager

from h2o3_tpu.serving import (
    MODEL_BYTES,
    MODEL_EVICTIONS,
    MODELS_RESIDENT,
    PAGE_IN_SECONDS,
)
from h2o3_tpu.utils import devmem as _dm
from h2o3_tpu.utils import flightrec as _fr


def budget_bytes() -> int:
    """H2O3_TPU_SERVE_HBM_BYTES (0 = unbounded)."""
    from h2o3_tpu import config

    return max(config.get_int("H2O3_TPU_SERVE_HBM_BYTES"), 0)


def _tree_bytes(tree) -> int:
    import jax

    return int(sum(leaf.nbytes for leaf in jax.tree_util.tree_leaves(tree)))


class _Entry:
    __slots__ = ("ref", "model_key", "host_bytes", "dev", "dev_bytes",
                 "in_use")

    def __init__(self, ref, model_key: str, host_bytes: int):
        self.ref = ref  # weakref to the owning BatchScorer
        self.model_key = model_key
        self.host_bytes = host_bytes
        self.dev = None  # device pytree while tier == hbm
        self.dev_bytes = 0
        self.in_use = 0  # dispatches currently holding the device pytree


class ResidencyManager:
    """LRU of scorer device payloads, keyed by scorer identity (two
    generations of one model key are distinct entries — an in-flight batch
    on the old generation keeps ITS payload until it finishes)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._entries: OrderedDict[int, _Entry] = OrderedDict()
        self.peak_hbm = 0
        self.evictions = 0
        self.page_ins = 0

    # -- registration -------------------------------------------------------
    def register(self, scorer) -> None:
        """Track a scorer whose lane carries a pageable device payload
        (``scorer._host_args`` is a numpy pytree). Idempotent."""
        host = getattr(scorer, "_host_args", None)
        if host is None:
            return
        sid = id(scorer)
        with self._lock:
            if sid in self._entries:
                return
            ref = weakref.ref(scorer, lambda _r, sid=sid: self._forget(sid))
            ent = _Entry(ref, scorer.model_key, _tree_bytes(host))
            self._entries[sid] = ent
            MODELS_RESIDENT.inc(1, tier="host")
            MODEL_BYTES.inc(ent.host_bytes, tier="host")

    def _forget(self, sid: int) -> None:
        """Weakref callback: the scorer (and its model) died — return the
        bytes without anyone having to call release()."""
        with self._lock:
            ent = self._entries.pop(sid, None)
            if ent is None:
                return
            self._drop_dev(ent, kind="released")
            MODELS_RESIDENT.inc(-1, tier="host")
            MODEL_BYTES.inc(-ent.host_bytes, tier="host")

    # -- the device LRU -----------------------------------------------------
    def _drop_dev(self, ent: _Entry, kind: str) -> None:
        if ent.dev is None:
            return
        ent.dev = None
        MODELS_RESIDENT.inc(-1, tier="hbm")
        MODEL_BYTES.inc(-ent.dev_bytes, tier="hbm")
        _dm.adjust("serving", -ent.dev_bytes)
        _fr.record("serve_evict", model=ent.model_key, reason=kind,
                   bytes=int(ent.dev_bytes))
        ent.dev_bytes = 0
        self.evictions += 1
        MODEL_EVICTIONS.inc(kind=kind)

    def _hbm_bytes(self) -> int:
        return sum(e.dev_bytes for e in self._entries.values())

    def _evict_to(self, target: int) -> None:
        """Demote LRU entries (oldest first) until the device tier fits
        ``target`` bytes; entries mid-dispatch are never touched."""
        for ent in list(self._entries.values()):
            if self._hbm_bytes() <= target:
                return
            if ent.dev is None or ent.in_use:
                continue
            self._drop_dev(ent, kind="demoted")

    @contextmanager
    def hold(self, scorer):
        """Yield the scorer's device pytree, paging it in if demoted, and
        pin it against eviction for the duration of the dispatch."""
        import jax
        import jax.numpy as jnp

        sid = id(scorer)
        with self._lock:
            ent = self._entries.get(sid)
            if ent is None:
                self.register(scorer)
                ent = self._entries[sid]
            if ent.dev is None:
                budget = budget_bytes()
                if budget:
                    # pre-insert eviction: the budget bounds PEAK residency
                    self._evict_to(max(budget - ent.host_bytes, 0))
                t0 = time.perf_counter()
                dev = jax.tree_util.tree_map(jnp.asarray, scorer._host_args)
                jax.block_until_ready(dev)
                PAGE_IN_SECONDS.observe(time.perf_counter() - t0)
                self.page_ins += 1
                ent.dev = dev
                ent.dev_bytes = _tree_bytes(dev)
                MODELS_RESIDENT.inc(1, tier="hbm")
                MODEL_BYTES.inc(ent.dev_bytes, tier="hbm")
                _dm.adjust("serving", ent.dev_bytes)
                _fr.record("serve_page_in", model=ent.model_key,
                           bytes=int(ent.dev_bytes))
                self.peak_hbm = max(self.peak_hbm, self._hbm_bytes())
            self._entries.move_to_end(sid)
            ent.in_use += 1
            dev = ent.dev
            budget = budget_bytes()
            if budget:
                # enforce on hits too: the budget may have shrunk, and a
                # pile of older residents must not outlive it (the current
                # entry is pinned by in_use and never evicted)
                self._evict_to(budget)
        try:
            yield dev
        finally:
            with self._lock:
                ent.in_use -= 1

    # -- lifecycle ----------------------------------------------------------
    def demote(self, scorer) -> None:
        """Drop a scorer's device payload (idle reaping); host tier stays."""
        with self._lock:
            ent = self._entries.get(id(scorer))
            if ent is not None and not ent.in_use:
                self._drop_dev(ent, kind="demoted")

    def release(self, scorer) -> None:
        """Forget a retired scorer entirely (both tiers de-accounted)."""
        if scorer is None:
            return
        with self._lock:
            ent = self._entries.pop(id(scorer), None)
            if ent is None:
                return
            self._drop_dev(ent, kind="released")
            MODELS_RESIDENT.inc(-1, tier="host")
            MODEL_BYTES.inc(-ent.host_bytes, tier="host")

    def status(self) -> dict:
        """Snapshot for ``GET /3/ServingRegistry`` and the fleet harness."""
        with self._lock:
            return {
                "hbm_budget_bytes": budget_bytes(),
                "hbm_bytes": self._hbm_bytes(),
                "hbm_peak_bytes": self.peak_hbm,
                "host_bytes": sum(e.host_bytes for e in
                                  self._entries.values()),
                "models_hbm": sum(1 for e in self._entries.values()
                                  if e.dev is not None),
                "models_tracked": len(self._entries),
                "evictions": self.evictions,
                "page_ins": self.page_ins,
            }

    def tier_of(self, scorer) -> str | None:
        with self._lock:
            ent = self._entries.get(id(scorer))
            if ent is None:
                return None
            return "hbm" if ent.dev is not None else "host"


MANAGER = ResidencyManager()
