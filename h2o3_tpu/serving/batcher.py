"""Micro-batch coalescing queue — the request-side half of the scoring tier.

Concurrent row-scoring requests for one model collect in a per-model queue;
a dispatcher thread waits up to ``H2O3_TPU_SCORE_BATCH_WINDOW_MS`` from the
first arrival (or until ``H2O3_TPU_SCORE_BATCH_MAX`` rows are waiting),
concatenates the payloads, scores them as ONE device dispatch through the
compiled :mod:`scorer`, and splits the results back per request. The window
is the latency the tier spends buying throughput: at light load a request
pays ~one window of queueing; at heavy load batches fill before the window
expires and the queue adds nothing.

Overload behavior follows the PR-4 admission contract: more than
``H2O3_TPU_SCORE_QUEUE_MAX`` rows waiting sheds new arrivals immediately
(429-shaped :class:`ShedError`), and a request that cannot be dispatched
within its ``H2O3_TPU_SCORE_DEADLINE_MS`` budget is dropped from the batch
and shed 504-shaped — a late answer to a scoring request is worthless, and
scoring it anyway would steal capacity from requests that can still make
their deadline.

``WINDOW_MS=0`` bypasses the queue entirely — one dispatch per request, the
measured control lane of the load-test A/B.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from h2o3_tpu.serving import (
    BATCH_OCCUPANCY,
    BATCH_ROWS,
    BATCHES,
    QUEUE_DEPTH,
    REQUESTS,
    ROWS,
    SHED,
    ShedError,
)
from h2o3_tpu.utils.log import Log

_IDLE_EXIT_S = 30.0  # dispatcher threads die after this much idle time


class _Pending:
    __slots__ = ("cols", "n", "deadline", "t0", "event", "result", "error")

    def __init__(self, cols, n, deadline):
        self.cols = cols
        self.n = n
        self.deadline = deadline
        self.t0 = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


def _knobs():
    from h2o3_tpu import config

    return (
        config.get_float("H2O3_TPU_SCORE_BATCH_WINDOW_MS") / 1e3,
        max(config.get_int("H2O3_TPU_SCORE_BATCH_MAX"), 1),
        config.get_float("H2O3_TPU_SCORE_DEADLINE_MS") / 1e3,
        config.get_int("H2O3_TPU_SCORE_QUEUE_MAX"),
    )


class ModelBatcher:
    """One coalescing queue + dispatcher thread per model."""

    def __init__(self, model, scorer):
        self.model = model
        self.scorer = scorer
        self._cond = threading.Condition()
        self._queue: list[_Pending] = []
        self._rows_queued = 0
        self._thread: threading.Thread | None = None

    # -- request side -------------------------------------------------------
    def submit(self, cols, n: int):
        window, max_rows, deadline_s, qmax = _knobs()
        deadline = (time.monotonic() + deadline_s) if deadline_s > 0 else None
        if window <= 0 or max_rows <= 1:
            # per-request control lane: no queue, one dispatch per request
            try:
                out = self.scorer.score_table(cols, n)
            except Exception:
                REQUESTS.inc(mode="inline", status="error")
                raise
            REQUESTS.inc(mode="inline", status="ok")
            ROWS.inc(n)
            return out
        p = _Pending(cols, n, deadline)
        with self._cond:
            # an empty queue always admits (even a request larger than the
            # bound — it dispatches alone); the bound sheds pile-up, not size
            if qmax > 0 and self._rows_queued and self._rows_queued + n > qmax:
                SHED.inc(reason="queue_full")
                REQUESTS.inc(mode="batched", status="shed")
                raise ShedError(
                    429, f"scoring queue full ({self._rows_queued} rows "
                         f">= H2O3_TPU_SCORE_QUEUE_MAX={qmax}); retry "
                         "with backoff")
            self._queue.append(p)
            self._rows_queued += n
            QUEUE_DEPTH.set(self._rows_queued)
            self._ensure_thread()
            self._cond.notify_all()
        # +1s grace over the request deadline: the dispatcher sheds expired
        # entries itself — this outer wait only bounds a wedged dispatcher
        ok = p.event.wait((deadline - time.monotonic() + 1.0)
                          if deadline else None)
        if not ok and not p.event.is_set():
            SHED.inc(reason="deadline")
            REQUESTS.inc(mode="batched", status="shed")
            raise ShedError(
                504, "scoring request missed its deadline in the queue "
                     "(H2O3_TPU_SCORE_DEADLINE_MS); the tier is saturated — "
                     "retry with backoff")
        if p.error is not None:
            REQUESTS.inc(mode="batched", status=(
                "shed" if isinstance(p.error, ShedError) else "error"))
            raise p.error
        REQUESTS.inc(mode="batched", status="ok")
        ROWS.inc(n)
        return p.result

    # -- dispatcher side ----------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name=f"h2o3-score-{self.model.key}",
                daemon=True)
            self._thread.start()

    def _take_batch(self) -> list[_Pending] | None:
        """Block for work, honor the window, pop up to max_rows. Returns
        None when idle long enough to retire the thread."""
        window, max_rows, _, _ = _knobs()
        with self._cond:
            idle_t0 = time.monotonic()
            while not self._queue:
                if not self._cond.wait(timeout=1.0) and (
                    time.monotonic() - idle_t0 > _IDLE_EXIT_S
                ):
                    self._thread = None
                    return None
            batch_deadline = self._queue[0].t0 + window
            while (
                self._rows_queued < max_rows
                and (left := batch_deadline - time.monotonic()) > 0
            ):
                self._cond.wait(timeout=left)
            take: list[_Pending] = []
            rows = 0
            while self._queue and (
                not take or rows + self._queue[0].n <= max_rows
            ):
                p = self._queue.pop(0)
                take.append(p)
                rows += p.n
            self._rows_queued -= rows
            QUEUE_DEPTH.set(self._rows_queued)
            return take

    def _loop(self) -> None:
        while True:
            take = self._take_batch()
            if take is None:
                return
            now = time.monotonic()
            live: list[_Pending] = []
            for p in take:
                if p.deadline is not None and now > p.deadline:
                    SHED.inc(reason="deadline")
                    p.error = ShedError(
                        504, "scoring request missed its deadline in the "
                             "queue (H2O3_TPU_SCORE_DEADLINE_MS); the tier "
                             "is saturated — retry with backoff")
                    p.event.set()
                else:
                    live.append(p)
            if not live:
                continue
            try:
                names = list(live[0].cols)
                cat_cols = {
                    name: np.concatenate([p.cols[name] for p in live])
                    for name in names
                }
                total = sum(p.n for p in live)
                out = self.scorer.score_table(cat_cols, total)
                BATCHES.inc()
                BATCH_OCCUPANCY.observe(len(live))
                BATCH_ROWS.observe(total)
                off = 0
                for p in live:
                    p.result = {k: v[off:off + p.n] for k, v in out.items()}
                    off += p.n
                    p.event.set()
            except Exception as e:  # noqa: BLE001 — per-request surfacing
                Log.err(f"batch scorer dispatch failed: {e!r}")
                for p in live:
                    if not p.event.is_set():
                        p.error = e
                        p.event.set()


_BATCHERS: dict[str, ModelBatcher] = {}
_BLOCK = threading.Lock()


def batcher_for(model) -> ModelBatcher:
    from h2o3_tpu.serving.scorer import scorer_for

    with _BLOCK:
        b = _BATCHERS.get(model.key)
        if b is None or b.model is not model:  # rebuilt model under same key
            b = _BATCHERS[model.key] = ModelBatcher(model, scorer_for(model))
        return b
