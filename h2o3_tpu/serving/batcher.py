"""Micro-batch coalescing queue — the request-side half of the scoring tier.

Concurrent row-scoring requests for one model collect in a per-model queue;
a dispatcher thread waits up to ``H2O3_TPU_SCORE_BATCH_WINDOW_MS`` from the
first arrival (or until ``H2O3_TPU_SCORE_BATCH_MAX`` rows are waiting),
concatenates the payloads, scores them as ONE device dispatch through the
compiled :mod:`scorer`, and splits the results back per request. The window
is the latency the tier spends buying throughput: at light load a request
pays ~one window of queueing; at heavy load batches fill before the window
expires and the queue adds nothing.

Overload behavior follows the PR-4 admission contract: more than
``H2O3_TPU_SCORE_QUEUE_MAX`` rows waiting sheds new arrivals immediately
(429-shaped :class:`ShedError`), and a request that cannot be dispatched
within its ``H2O3_TPU_SCORE_DEADLINE_MS`` budget is dropped from the batch
and shed 504-shaped — a late answer to a scoring request is worthless, and
scoring it anyway would steal capacity from requests that can still make
their deadline.

Cloud degradation (the ISSUE-10 serving half): when the training cloud
trips its fail-stop latch mid-dispatch (``cluster/cloud.mark_degraded`` —
a wedged collective, a dead member), every queued and in-flight request
fails FAST with a 503-shaped :class:`ShedError` + Retry-After instead of
timing out at ``_DEADLINE_MS`` one by one, and a per-model circuit breaker
opens: new arrivals shed instantly while the cloud is down, a single probe
request is admitted once the cloud reports healthy again (half-open — the
supervised ``recover()`` reform or an operator ``clear_degraded``), and a
successful probe closes the breaker. The scoring tier rides through a
training-cloud incident without burning its deadline budget on a dead mesh.

``WINDOW_MS=0`` bypasses the queue entirely — one dispatch per request, the
measured control lane of the load-test A/B.

Fleet behavior (ISSUE 12): every dispatch — batched or inline — passes the
round-robin :class:`_FairGate`, so one hot model cannot starve other
models' queues past their deadlines; a batcher idle past
``H2O3_TPU_SCORE_IDLE_SECS`` reaps its dispatcher thread, drops out of the
per-model cache and demotes its scorer's device payload
(serving/residency.py), and :func:`retire_model` (model delete, registry
generation swap) drains in-flight work then releases everything.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from h2o3_tpu.serving import (
    BATCH_OCCUPANCY,
    BATCH_ROWS,
    BATCHES,
    BREAKER,
    QUEUE_DEPTH,
    REQUESTS,
    ROWS,
    SHED,
    ShedError,
)
from h2o3_tpu.utils import flightrec as _fr
from h2o3_tpu.utils import jobacct as _jobacct
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log

_DEGRADE_POLL_S = 0.05  # waiter latch-poll cadence (the "shed budget")


def _idle_exit_s() -> float:
    """H2O3_TPU_SCORE_IDLE_SECS: a dispatcher this long without work retires
    its thread AND reaps the whole batcher + the scorer's device payload —
    an idle model must not park a thread and pin HBM forever (the fleet's
    unbounded-cache fix)."""
    from h2o3_tpu import config

    return max(config.get_float("H2O3_TPU_SCORE_IDLE_SECS"), 0.1)


class _FairGate:
    """Round-robin dispatch turnstile across models with queued work.

    Device dispatches from every model's batcher (and the window=0 inline
    lane) pass through here; when more than one model is waiting, grants
    rotate model-by-model — a hot model's continuous batch stream cannot
    starve a cold model past its deadline, because after each dispatch the
    served model goes to the BACK of the rotation. Uncontended, the gate is
    one lock acquire.

    A holder that wedges mid-dispatch (a dead collective — the same
    failure the batcher's abandon/respawn logic covers) is ABANDONED after
    ``_STALL_S``: a waiter revokes its turn so one model's corpse cannot
    block the whole fleet, and the corpse's eventual release is ignored
    via a ticket mismatch. Rotation slots are consumed at acquire time, so
    abandoned holders leave no residue in the queue."""

    _STALL_S = 2.0

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._waiters: dict[str, int] = {}  # key -> threads WAITING
        self._order: list[str] = []  # distinct waiting keys, service order
        self._active: str | None = None
        self._ticket = 0  # tenure id of the active holder
        self._active_t0 = 0.0

    def acquire(self, key: str) -> int:
        with self._cond:
            self._waiters[key] = self._waiters.get(key, 0) + 1
            if self._waiters[key] == 1:
                self._order.append(key)
            while self._active is not None or self._order[0] != key:
                if (self._active is not None and
                        time.monotonic() - self._active_t0 > self._STALL_S):
                    self._active = None  # abandoned; late release no-ops
                    self._cond.notify_all()
                    continue
                self._cond.wait(timeout=0.2)
            # take the turn: consume this key's rotation slot
            self._waiters[key] -= 1
            self._order.pop(0)
            if self._waiters[key] > 0:  # same-key waiters: back of the line
                self._order.append(key)
            else:
                del self._waiters[key]
            self._ticket += 1
            self._active = key
            self._active_t0 = time.monotonic()
            return self._ticket

    def release(self, key: str, ticket: int) -> None:
        with self._cond:
            if self._active == key and self._ticket == ticket:
                self._active = None
            self._cond.notify_all()


_FAIR = _FairGate()


def _cloud_down() -> str | None:
    """The fail-stop latch, read lazily (no import cycle at module load)."""
    from h2o3_tpu.cluster import cloud

    return cloud.degraded_reason()


def _is_cloud_failure(exc: Exception) -> bool:
    from h2o3_tpu.cluster import recovery

    return recovery.is_cloud_failure(exc)


def _degraded_error() -> ShedError:
    return ShedError(
        503, "scoring unavailable: the training cloud is degraded "
             f"(fail-stop: {_cloud_down()}); failed fast instead of "
             "waiting out the request deadline — retry after recovery",
        retry_after="5")


class _Breaker:
    """Per-model circuit breaker over the cloud's fail-stop latch.

    closed → (cloud failure) → open → (latch released: supervised recover()
    or operator clear_degraded) → half_open (ONE probe admitted) →
    (probe ok) → closed / (probe fails) → open again.
    """

    def __init__(self, model_key: str):
        self.key = model_key
        self.state = "closed"
        self.probing = False
        self._lock = threading.Lock()

    def admit(self) -> str:
        """Gate a new request: returns 'ok' or 'probe', or raises the
        503-shaped ShedError when the breaker (or the latch) says no."""
        down = _cloud_down()
        with self._lock:
            if self.state == "closed":
                if down is None:
                    return "ok"
                self._open_locked()  # degraded on arrival: open + shed
            if self.state == "open":
                if down is not None:
                    SHED.inc(reason="breaker_open")
                    REQUESTS.inc(mode="batched", status="shed")
                    raise ShedError(
                        503, "scoring circuit breaker open for model "
                             f"{self.key}: the training cloud is degraded "
                             f"({down}); retry after recovery",
                        retry_after="5")
                # latch released (recover()/clear_degraded): half-open
                self.state = "half_open"
                self.probing = False
                BREAKER.inc(state="half_open")
                Log.info(f"scoring breaker half-open for {self.key} "
                         "(cloud healthy again; admitting one probe)")
            # half_open: exactly one probe in flight, others shed
            if self.probing:
                SHED.inc(reason="breaker_open")
                REQUESTS.inc(mode="batched", status="shed")
                raise ShedError(
                    503, f"scoring circuit breaker half-open for model "
                         f"{self.key}: a probe is already in flight",
                    retry_after="1")
            self.probing = True
            return "probe"

    def _open_locked(self) -> None:
        if self.state != "open":
            self.state = "open"
            self.probing = False
            BREAKER.inc(state="open")
            Log.warn(f"scoring breaker OPEN for {self.key} (cloud failure)")

    def record(self, ok: bool, probe: bool) -> None:
        """Outcome of an admitted request: a successful probe closes the
        breaker; a cloud failure (from any request) opens it."""
        with self._lock:
            if probe:
                self.probing = False
            if not ok:
                self._open_locked()
            elif self.state != "closed" and probe:
                self.state = "closed"
                BREAKER.inc(state="closed")
                Log.info(f"scoring breaker closed for {self.key} "
                         "(probe succeeded; traffic re-admitted)")


class _Pending:
    __slots__ = ("cols", "n", "deadline", "t0", "event", "result", "error",
                 "trace", "parent")

    def __init__(self, cols, n, deadline):
        self.cols = cols
        self.n = n
        self.deadline = deadline
        self.t0 = time.monotonic()
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None
        # the submitter's trace context, carried across the queue: the
        # dispatcher thread runs in no trace, so the request's span tree is
        # stitched from these at dispatch time (queue_wait ring events)
        self.trace = _mx.current_trace()
        self.parent = _mx.current_span()


def _knobs():
    from h2o3_tpu import config

    return (
        config.get_float("H2O3_TPU_SCORE_BATCH_WINDOW_MS") / 1e3,
        max(config.get_int("H2O3_TPU_SCORE_BATCH_MAX"), 1),
        config.get_float("H2O3_TPU_SCORE_DEADLINE_MS") / 1e3,
        config.get_int("H2O3_TPU_SCORE_QUEUE_MAX"),
    )


class ModelBatcher:
    """One coalescing queue + dispatcher thread per model."""

    def __init__(self, model, scorer):
        self.model = model
        self.scorer = scorer
        self._cond = threading.Condition()
        self._queue: list[_Pending] = []
        self._rows_queued = 0
        self._thread: threading.Thread | None = None
        self._breaker = _Breaker(model.key)
        self._retiring = False  # drain in-flight work, then drop everything

    # -- request side -------------------------------------------------------
    def submit(self, cols, n: int):
        window, max_rows, deadline_s, qmax = _knobs()
        deadline = (time.monotonic() + deadline_s) if deadline_s > 0 else None
        admit = self._breaker.admit()  # raises 503-shaped when open
        probe = admit == "probe"
        if window <= 0 or max_rows <= 1:
            # per-request control lane: no queue, one dispatch per request
            # (still through the fair gate — a hot inline model must not
            # starve other models' dispatchers either)
            try:
                tk = _FAIR.acquire(self.model.key)
                try:
                    out = self.scorer.score_table(cols, n)
                finally:
                    _FAIR.release(self.model.key, tk)
            except Exception as e:
                self._breaker.record(ok=not _is_cloud_failure(e), probe=probe)
                REQUESTS.inc(mode="inline", status="error")
                raise
            self._breaker.record(ok=True, probe=probe)
            REQUESTS.inc(mode="inline", status="ok")
            ROWS.inc(n)
            return out
        p = _Pending(cols, n, deadline)
        with self._cond:
            # an empty queue always admits (even a request larger than the
            # bound — it dispatches alone); the bound sheds pile-up, not size
            if qmax > 0 and self._rows_queued and self._rows_queued + n > qmax:
                if probe:
                    self._breaker.record(ok=True, probe=True)  # not a verdict
                SHED.inc(reason="queue_full")
                REQUESTS.inc(mode="batched", status="shed")
                raise ShedError(
                    429, f"scoring queue full ({self._rows_queued} rows "
                         f">= H2O3_TPU_SCORE_QUEUE_MAX={qmax}); retry "
                         "with backoff")
            self._queue.append(p)
            self._rows_queued += n
            QUEUE_DEPTH.set(self._rows_queued)
            self._ensure_thread()
            self._cond.notify_all()
        # wait in short slices, polling the fail-stop latch: when the cloud
        # degrades while we queue (or while the dispatcher is wedged inside
        # a dead collective) the request fails 503 within the shed budget
        # (~_DEGRADE_POLL_S) instead of burning its whole _DEADLINE_MS.
        # +1s grace over the request deadline: the dispatcher sheds expired
        # entries itself — the outer bound only covers a wedged dispatcher
        limit = (deadline - time.monotonic() + 1.0) if deadline else None
        t_end = (time.monotonic() + limit) if limit is not None else None
        timed_out = False
        while not p.event.is_set():
            remaining = (t_end - time.monotonic()) if t_end is not None else None
            if remaining is not None and remaining <= 0:
                timed_out = True
                break
            slice_ = _DEGRADE_POLL_S if remaining is None else min(
                _DEGRADE_POLL_S, remaining)
            if p.event.wait(slice_):
                break
            if _cloud_down() is not None:
                self._abandon(p)
                self._breaker.record(ok=False, probe=probe)
                SHED.inc(reason="degraded")
                REQUESTS.inc(mode="batched", status="shed")
                raise _degraded_error()
        if timed_out and not p.event.is_set():
            if probe:
                self._breaker.record(ok=True, probe=True)  # not a verdict
            SHED.inc(reason="deadline")
            REQUESTS.inc(mode="batched", status="shed")
            raise ShedError(
                504, "scoring request missed its deadline in the queue "
                     "(H2O3_TPU_SCORE_DEADLINE_MS); the tier is saturated — "
                     "retry with backoff")
        if p.error is not None:
            self._breaker.record(
                ok=not _is_cloud_failure(p.error), probe=probe)
            REQUESTS.inc(mode="batched", status=(
                "shed" if isinstance(p.error, ShedError) else "error"))
            raise p.error
        self._breaker.record(ok=True, probe=probe)
        REQUESTS.inc(mode="batched", status="ok")
        ROWS.inc(n)
        return p.result

    def _abandon(self, p: _Pending) -> None:
        """Remove a still-queued request its waiter is giving up on (cloud
        degraded); if the dispatcher already popped it, the discarded result
        is harmless. Also forgets a dispatcher thread that may be wedged
        inside a dead collective so the next submit gets a fresh one."""
        with self._cond:
            if p in self._queue:
                self._queue.remove(p)
                self._rows_queued -= p.n
                QUEUE_DEPTH.set(self._rows_queued)
            self._thread = None

    # -- dispatcher side ----------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name=f"h2o3-score-{self.model.key}",
                daemon=True)
            self._thread.start()

    def _take_batch(self) -> list[_Pending] | None:
        """Block for work, honor the window, pop up to max_rows. Returns
        None when idle long enough (H2O3_TPU_SCORE_IDLE_SECS) to retire the
        thread, or when the batcher was retired and the queue drained."""
        window, max_rows, _, _ = _knobs()
        with self._cond:
            idle_t0 = time.monotonic()
            while not self._queue:
                if self._retiring:
                    self._thread = None
                    return None
                idle_s = _idle_exit_s()
                if not self._cond.wait(timeout=min(1.0, idle_s)) and (
                    time.monotonic() - idle_t0 > idle_s
                ):
                    self._thread = None
                    return None
            batch_deadline = self._queue[0].t0 + window
            while (
                self._rows_queued < max_rows
                and (left := batch_deadline - time.monotonic()) > 0
            ):
                self._cond.wait(timeout=left)
            take: list[_Pending] = []
            rows = 0
            while self._queue and (
                not take or rows + self._queue[0].n <= max_rows
            ):
                p = self._queue.pop(0)
                take.append(p)
                rows += p.n
            self._rows_queued -= rows
            QUEUE_DEPTH.set(self._rows_queued)
            return take

    def _loop(self) -> None:
        while True:
            take = self._take_batch()
            if take is None:
                self._reap()
                return
            if _cloud_down() is not None:
                # the cloud degraded while this batch coalesced: fail the
                # whole batch fast (503 + Retry-After) and open the breaker
                # instead of dispatching into a dead mesh
                self._breaker.record(ok=False, probe=False)
                for p in take:
                    SHED.inc(reason="degraded")
                    p.error = _degraded_error()
                    p.event.set()
                continue
            now = time.monotonic()
            live: list[_Pending] = []
            for p in take:
                if p.deadline is not None and now > p.deadline:
                    SHED.inc(reason="deadline")
                    p.error = ShedError(
                        504, "scoring request missed its deadline in the "
                             "queue (H2O3_TPU_SCORE_DEADLINE_MS); the tier "
                             "is saturated — retry with backoff")
                    p.event.set()
                else:
                    live.append(p)
            if not live:
                continue
            try:
                names = list(live[0].cols)
                cat_cols = {
                    name: np.concatenate([p.cols[name] for p in live])
                    for name in names
                }
                total = sum(p.n for p in live)
                # span-tree stitching (ISSUE 18): the coalesced dispatch is
                # ONE span shared by every member request, so it cannot live
                # in any single request's trace. Each request instead gets a
                # queue_wait span in its OWN trace (submit → here), carrying
                # batch_span as the cross-reference to the shared dispatch;
                # the batch span id is pushed around score_table so the
                # serving_batch dispatch (and its page-in) parent under it.
                bspan = _mx.next_span_id()
                t_disp = time.monotonic()
                for p in live:
                    wait_s = t_disp - p.t0
                    _fr.record("queue_wait", trace=p.trace, parent=p.parent,
                               span=_mx.next_span_id(), batch_span=bspan,
                               dur_ms=round(wait_s * 1e3, 3), rows=p.n,
                               model=self.model.key)
                    _jobacct.on_queue_wait(p.trace, wait_s)
                tk = _FAIR.acquire(self.model.key)
                stok = _mx.push_span(bspan)
                try:
                    out = self.scorer.score_table(cat_cols, total)
                finally:
                    _mx.pop_span(stok)
                    _FAIR.release(self.model.key, tk)
                BATCHES.inc()
                BATCH_OCCUPANCY.observe(len(live))
                BATCH_ROWS.observe(total)
                off = 0
                for p in live:
                    p.result = {k: v[off:off + p.n] for k, v in out.items()}
                    off += p.n
                    p.event.set()
            except Exception as e:  # noqa: BLE001 — per-request surfacing
                Log.err(f"batch scorer dispatch failed: {e!r}")
                if _is_cloud_failure(e):
                    # mid-dispatch cloud death: open the breaker and shed
                    # the batch 503-shaped (retryable after recovery)
                    # instead of surfacing a raw runtime error per request
                    self._breaker.record(ok=False, probe=False)
                    err: Exception = ShedError(
                        503, "scoring dispatch died of a training-cloud "
                             f"failure ({e!r}); retry after recovery",
                        retry_after="5")
                else:
                    err = e
                for p in live:
                    if not p.event.is_set():
                        if err is not e:
                            SHED.inc(reason="degraded")
                        p.error = err
                        p.event.set()


    # -- lifecycle ----------------------------------------------------------
    def _reap(self) -> None:
        """The dispatcher retired (idle past H2O3_TPU_SCORE_IDLE_SECS, or an
        explicit retire()): drop this batcher from the per-model cache and
        release device memory. Idle reaping DEMOTES the scorer (host mirror
        stays; the next request pages back in); a retire() releases it
        entirely (the model is gone or replaced)."""
        from h2o3_tpu.serving.residency import MANAGER

        with _BLOCK:
            with self._cond:
                if self._queue or (self._thread is not None
                                   and self._thread.is_alive()):
                    return  # new work raced the idle exit; stay cached
                retiring = self._retiring
            if _BATCHERS.get(self.model.key) is self:
                del _BATCHERS[self.model.key]
        if retiring:
            MANAGER.release(self.scorer)
            self.model.__dict__.pop("_h2o3_batch_scorer", None)
        else:
            MANAGER.demote(self.scorer)

    def retire(self) -> None:
        """Drain in-flight work, then drop the thread, the batcher and the
        scorer's residency. New requests never reach a retired batcher —
        batcher_for() already stopped handing it out."""
        with self._cond:
            self._retiring = True
            alive = self._thread is not None and self._thread.is_alive()
            self._cond.notify_all()
        if not alive:
            self._reap()  # no dispatcher to do it


_BATCHERS: dict[str, ModelBatcher] = {}
_BLOCK = threading.Lock()


def batcher_for(model) -> ModelBatcher:
    from h2o3_tpu.serving.scorer import scorer_for

    with _BLOCK:
        b = _BATCHERS.get(model.key)
        if b is None or b.model is not model:  # rebuilt model under same key
            b = _BATCHERS[model.key] = ModelBatcher(model, scorer_for(model))
        return b


def retire_model(model_key: str, model=None) -> None:
    """Drop a model's serving state (batcher + dispatcher thread + scorer
    residency). With ``model`` given, only that exact object's batcher is
    retired — a registry generation swap must not tear down the NEW
    generation that already took over the key."""
    with _BLOCK:
        b = _BATCHERS.get(model_key)
        if b is not None and model is not None and b.model is not model:
            b = None  # the key moved on to a newer generation; leave it
        elif b is not None:
            del _BATCHERS[model_key]
    if b is not None:
        b.retire()
    elif model is not None:
        # no live batcher, but the model may still hold a scorer + HBM
        from h2o3_tpu.serving.residency import MANAGER

        sc = model.__dict__.pop("_h2o3_batch_scorer", None)
        if sc is not None:
            MANAGER.release(sc)
