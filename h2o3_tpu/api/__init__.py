"""REST API — successor of ``water.api.RequestServer`` / ``*Handler`` /
``schemas3`` [UNVERIFIED upstream paths, SURVEY.md §2.1 L6]."""

from h2o3_tpu.api.server import H2OServer, start_server

__all__ = ["H2OServer", "start_server"]
