"""REST server — successor of ``water.api.RequestServer`` (route table),
``water.api.*Handler`` (endpoint logic) and the ``schemas3`` JSON mapping
[UNVERIFIED upstream paths, SURVEY.md §2.1, §3].

H2O serves a versioned HTTP surface (`/3/...`, `/99/...`) from every node via
Jetty; clients (Python/R/Flow) are pure REST consumers. Here the control
plane is one coordinator process, so a stdlib ThreadingHTTPServer is the
idiomatic replacement (fastapi/uvicorn are not in the image — and the
request volume is control-plane only; data never moves over REST except
file upload/download).

Routes follow H2O's v3 names and JSON shapes closely enough that a client
written against H2O's wire format finds the same fields
(`__meta.schema_type`, `frames[]`, `models[]`, `job.status`...), without
chasing exact schema-class parity (the reflective Schema/TypeMap machinery
is JVM-specific; a dict is the Python-native schema).

Long work (model builds, parses) runs as Jobs in threads; handlers return a
job key immediately and ``/3/Jobs/{key}`` polls — H2O's exact contract.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils import metrics as _metrics
from h2o3_tpu.utils.log import Log

import itertools as _itertools

# per-request trace ids minted at ingress (when the client sends no
# X-Request-Id): "rest-<n>" — the attribution key ring events and ledger
# entries produced by the handler carry, echoed back as X-H2O3-Trace
_REQ_IDS = _itertools.count(1)

# per-route REST telemetry (labels use the route PATTERN, not the raw path —
# bounded cardinality whatever clients request)
_REST_REQUESTS = _metrics.counter(
    "rest_requests_total", "REST requests handled, by method/route/status")
_REST_SECONDS = _metrics.histogram(
    "rest_request_seconds", "REST handler latency, by method/route")
_REST_IN_FLIGHT = _metrics.gauge(
    "rest_requests_in_flight", "REST requests currently executing")
_REST_REJECTED = _metrics.counter(
    "rest_rejected_total",
    "requests shed by admission control (429/503 + Retry-After), "
    "by method/route/reason")
_JOB_QUEUE_DEPTH = _metrics.gauge(
    "rest_job_queue_depth",
    "live (pending+running) REST-created jobs — the admission queue the "
    "H2O3_TPU_MAX_QUEUED_JOBS bound applies to")
_G_DRAINING = _metrics.gauge(
    "rest_draining", "1 while the server is draining (no mutating admits)")
_DRAIN_SECONDS = _metrics.gauge(
    "rest_drain_seconds", "wall seconds the last graceful drain took")
_IDEM_REPLAYS = _metrics.counter(
    "rest_idempotent_replays_total",
    "POSTs answered from the Idempotency-Key response cache (a client "
    "retry that would otherwise have double-run the mutation)")
_PRED_EVICTED = _metrics.counter(
    "rest_prediction_frames_evicted_total",
    "generated /3/Predictions result frames evicted by the "
    "H2O3_TPU_PREDICTIONS_RETAIN bound (serving load no longer grows "
    "the DKV without bound)")


# ---------------------------------------------------------------------------
# admission control + drain state (tentpole: overload-safe serving).
# Process-global on purpose: handlers are module-level and the REST server
# is a process singleton (start_server) — a second H2OServer in one process
# shares the gate, which is the correct bound (one process, one mesh).

_DRAINING = False  # begin_drain() flips it; stop() clears it on exit

_GATE_LOCK = threading.Lock()
_INFLIGHT_MUTATING = 0  # mutating requests currently executing (gate slots)

_JOBS_LOCK = threading.Lock()
_REST_JOBS: list[Job] = []  # jobs created by REST routes (drain + queue bound)


def _retry_after(fallback: str) -> str:
    """Retry-After for a shed response: the overload plane's reservation-
    queue estimate (mean measured hold time x queue depth — honest, not a
    constant) when the plane is on; the historical hardcoded value under
    ``H2O3_TPU_OVERLOAD=0`` (bit-for-bit pin)."""
    from h2o3_tpu.utils import overload as _ov

    if not _ov.enabled():
        return fallback
    return str(max(int(round(_ov.retry_after_estimate())), 1))


def _admission_enter(method: str, route: str) -> bool:
    """Admission gate for mutating requests. Returns True when a bounded
    in-flight slot was taken (release with :func:`_admission_exit`); raises
    ``ApiError`` 429/503 + ``Retry-After`` when the request must be shed.
    GETs (health probes, job polls, metrics scrapes) always pass — an
    overloaded or draining cloud must stay observable.

    Beyond the request-count bounds, the ISSUE-19 **memory gate**: while
    measured ``devmem.headroom()`` sits below
    ``H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES`` every mutating request is shed
    503 (reason ``memory``) — requests, unlike the per-job footprint check
    in ``build_model``, carry no size estimate, so the gate is a
    whole-server pressure valve."""
    if method == "GET":
        return False
    if route in (r"/3/Shutdown", r"/3/Recover"):
        return False  # drain/shutdown/recover ops must land under overload
    if _DRAINING:
        _REST_REJECTED.inc(method=method, route=route or "/", reason="draining")
        raise ApiError(
            503, "server is draining: no new mutating work is admitted "
                 "(running jobs are flushing checkpoints; retry against "
                 "another coordinator or after restart)",
            headers={"Retry-After": _retry_after("5")}, reason="draining")
    from h2o3_tpu import config

    min_head = config.get_int("H2O3_TPU_ADMIT_MIN_HEADROOM_BYTES")
    if min_head > 0:
        from h2o3_tpu.utils import devmem as _dm
        from h2o3_tpu.utils import overload as _ov

        if _ov.enabled():
            head = _dm.headroom()
            if head is not None and head < min_head:
                _REST_REJECTED.inc(
                    method=method, route=route or "/", reason="memory")
                raise ApiError(
                    503, f"insufficient device memory: measured headroom "
                         f"{int(head)} B < H2O3_TPU_ADMIT_MIN_HEADROOM_"
                         f"BYTES={min_head}; retry after reserved HBM frees",
                    headers={"Retry-After": _retry_after("5")},
                    reason="memory")
    cap = config.get_int("H2O3_TPU_MAX_INFLIGHT")
    if cap <= 0:
        return False
    global _INFLIGHT_MUTATING
    with _GATE_LOCK:
        if _INFLIGHT_MUTATING >= cap:
            full = _INFLIGHT_MUTATING
        else:
            _INFLIGHT_MUTATING += 1
            return True
    _REST_REJECTED.inc(method=method, route=route or "/", reason="inflight_full")
    raise ApiError(
        429, f"too many in-flight mutating requests ({full} >= "
             f"H2O3_TPU_MAX_INFLIGHT={cap}); retry with backoff",
        headers={"Retry-After": _retry_after("1")}, reason="inflight_full")


def _admission_exit() -> None:
    global _INFLIGHT_MUTATING
    with _GATE_LOCK:
        _INFLIGHT_MUTATING = max(0, _INFLIGHT_MUTATING - 1)


def _start_job(work, description: str, cancellable: bool = True) -> Job:
    """The one place REST routes create Jobs: applies the bounded pending-job
    queue (503 + Retry-After when full or draining), the default job
    deadline knob, and registers the job for graceful drain."""
    from h2o3_tpu import config

    if _DRAINING:
        _REST_REJECTED.inc(method="POST", route="<job>", reason="draining")
        raise ApiError(503, "server is draining: not accepting new jobs",
                       headers={"Retry-After": _retry_after("5")},
                       reason="draining")
    cap = config.get_int("H2O3_TPU_MAX_QUEUED_JOBS")
    job = Job(work, description)
    if not cancellable:
        job.cancellable = False
    deadline = config.get_float("H2O3_TPU_JOB_DEADLINE_SECS")
    if deadline > 0:
        # enforced between iterations via the soft-deadline plumbing:
        # iterative builders truncate gracefully, keeping the partial model
        job.soft_deadline = time.time() + deadline
    # prune + count + append under one lock hold: a check-then-act gap here
    # would let concurrent creates all pass the cap check and exceed it
    with _JOBS_LOCK:
        _REST_JOBS[:] = [
            j for j in _REST_JOBS if j.status in (Job.PENDING, Job.RUNNING)
        ]
        depth = len(_REST_JOBS)
        admitted = not (cap > 0 and depth >= cap)
        if admitted:
            _REST_JOBS.append(job)
            depth += 1
    _JOB_QUEUE_DEPTH.set(depth)
    if not admitted:
        DKV.remove(job.key)  # never started; don't leak it into /3/Jobs
        _REST_REJECTED.inc(method="POST", route="<job>", reason="job_queue_full")
        raise ApiError(
            503, f"job queue full ({depth} live jobs >= "
                 f"H2O3_TPU_MAX_QUEUED_JOBS={cap}); retry with backoff",
            headers={"Retry-After": _retry_after("2")},
            reason="job_queue_full")
    job.start()
    return job


def _handler_deadline() -> float | None:
    from h2o3_tpu import config

    v = config.get_float("H2O3_TPU_HANDLER_DEADLINE_SECS")
    return v if v > 0 else None


def _join_for_handler(job: Job):
    """Synchronous-route join bounded by the handler deadline: past it the
    route answers 504 with the job key (the job keeps running — poll
    /3/Jobs) instead of pinning the handler thread forever."""
    try:
        return job.join(timeout=_handler_deadline())
    except TimeoutError:
        raise ApiError(
            504, f"handler deadline exceeded; job {job.key} is still "
                 f"running — poll /3/Jobs/{job.key}",
            headers={"Retry-After": "5"})


# ---------------------------------------------------------------------------
# Idempotency-Key dedupe: a client retrying a POST (after a timeout, a 429,
# a dropped connection) sends the same Idempotency-Key; the server replays
# the first response instead of double-running the mutation (double-training
# a model, double-parsing a frame). Completed responses are cached in a
# bounded LRU; an in-flight duplicate gets 409 + Retry-After.

_IDEM_LOCK = threading.Lock()
_IDEM_PENDING = object()
_IDEM_CACHE: "dict[str, object]" = {}  # key -> (status, payload) | _IDEM_PENDING
_IDEM_MAX = 256


def _idem_begin(key: str):
    """Claim the key. Returns a cached (status, payload) to replay, the
    _IDEM_PENDING sentinel when another thread is mid-flight, or None when
    this request now owns the key."""
    with _IDEM_LOCK:
        hit = _IDEM_CACHE.get(key)
        if hit is not None:
            return hit
        while len(_IDEM_CACHE) >= _IDEM_MAX:
            # Evict completed entries only: popping a _IDEM_PENDING key would
            # let its retry re-run the mutation concurrently. Pending entries
            # are bounded by the in-flight admission gate, so letting them
            # exceed _IDEM_MAX is safe.
            victim = next((k for k, v in _IDEM_CACHE.items()
                           if v is not _IDEM_PENDING), None)
            if victim is None:
                break
            _IDEM_CACHE.pop(victim)
        _IDEM_CACHE[key] = _IDEM_PENDING
        return None


# Statuses the client retries with the SAME key (admission shed, queue full,
# draining, in-flight dup): caching them would replay the rejection forever,
# so they release the key like 5xx and the retry re-attempts.
_IDEM_TRANSIENT = frozenset({409, 429, 503})


def _idem_finish(key: str, status: int, payload: dict | None) -> None:
    """Publish the outcome: deterministic 2xx/4xx responses are cached for
    replay; 5xx, transient shed statuses (409/429/503), and non-JSON
    outcomes release the key so a retry re-attempts."""
    with _IDEM_LOCK:
        if (payload is not None and status < 500
                and status not in _IDEM_TRANSIENT):
            _IDEM_CACHE[key] = (status, payload)
        else:
            _IDEM_CACHE.pop(key, None)

_ALGOS = ("gbm", "xgboost", "glm", "drf", "xrt", "deeplearning", "kmeans", "pca", "svd",
          "naivebayes", "isolationforest", "stackedensemble",
          "isotonicregression", "decisiontree", "adaboost",
          "extendedisolationforest", "targetencoder", "glrm", "coxph",
          "word2vec", "rulefit", "upliftdrf", "gam", "modelselection",
          "anovaglm", "aggregator", "infogram", "psvm", "hglm")


def _builder_cls(algo: str):
    from h2o3_tpu import models as M

    return {
        "gbm": M.GBM, "xgboost": M.XGBoost, "glm": M.GLM, "drf": M.DRF, "xrt": M.XRT,
        "deeplearning": M.DeepLearning, "kmeans": M.KMeans, "pca": M.PCA,
        "svd": M.SVD, "naivebayes": M.NaiveBayes,
        "isolationforest": M.IsolationForest,
        "stackedensemble": M.StackedEnsemble,
        "isotonicregression": M.IsotonicRegression,
        "decisiontree": M.DT, "adaboost": M.AdaBoost,
        "extendedisolationforest": M.ExtendedIsolationForest,
        "targetencoder": M.TargetEncoder, "glrm": M.GLRM, "coxph": M.CoxPH,
        "word2vec": M.Word2Vec, "rulefit": M.RuleFit,
        "upliftdrf": M.UpliftDRF, "gam": M.GAM,
        "modelselection": M.ModelSelection, "anovaglm": M.ANOVAGLM,
        "aggregator": M.Aggregator, "infogram": M.Infogram, "psvm": M.PSVM,
        "hglm": M.HGLM,
    }[algo]


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        v = float(o)
        return v if np.isfinite(v) else None
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, float) and not np.isfinite(o):
        return None
    return str(o)


class ApiError(Exception):
    def __init__(self, status: int, msg: str, headers: dict | None = None,
                 reason: str | None = None):
        super().__init__(msg)
        self.status = status
        self.headers = headers or {}
        # machine-readable shed/reject reason ("memory", "draining", ...)
        # surfaced in the error body so clients can branch without parsing
        # the message text
        self.reason = reason


# ---------------------------------------------------------------------------
# bounded retention of generated prediction frames (serving-load DKV fix):
# every /3/Predictions call with a server-generated dest used to leak one
# Frame into the DKV forever. Only GENERATED keys are tracked — a client
# that names its predictions_frame owns its lifecycle.

import collections as _collections

_PRED_LOCK = threading.Lock()
_PRED_FRAMES: "_collections.deque[str]" = _collections.deque()


def _retain_prediction_frame(dest: str) -> None:
    from h2o3_tpu import config
    from h2o3_tpu.cluster import spmd

    cap = config.get_int("H2O3_TPU_PREDICTIONS_RETAIN")
    if cap <= 0:
        return
    evict: list[str] = []
    with _PRED_LOCK:
        _PRED_FRAMES.append(dest)
        while len(_PRED_FRAMES) > cap:
            evict.append(_PRED_FRAMES.popleft())
    for k in evict:
        try:
            spmd.run("remove", key=k)  # replicated: every rank's DKV agrees
            _PRED_EVICTED.inc()
        except Exception as e:  # noqa: BLE001 — eviction must not fail predict
            Log.warn(f"prediction-frame eviction of {k} failed: {e!r}")


# ---------------------------------------------------------------------------
# endpoint logic ("Handlers")


def _frame_schema(fr: Frame, key: str) -> dict:
    from h2o3_tpu.cluster import spmd

    cols = []
    for name in fr.names:
        v = fr.vec(name)
        # per-column device stats dispatch device programs; on a multi-process
        # cloud a REST thread doing that unreplicated deadlocks the ranks
        # (and checking in_replicated() here would race a concurrent build
        # job's flag) — serve only CACHED stats there (a replicated
        # frame_summary populates the cache on every rank)
        st = {}
        if hasattr(v, "stats") and (
            not spmd.multi_process() or getattr(v, "_stats", None) is not None
        ):
            st = v.stats()
        cols.append({
            "label": name,
            "type": {"real": "real", "int": "int", "enum": "enum",
                     "string": "string", "time": "time"}.get(v.kind, v.kind),
            "domain": list(v.domain) if v.domain else None,
            "missing_count": int(st.get("naCnt", 0)) if st else 0,
            "mean": st.get("mean"), "sigma": st.get("sigma"),
            "min": st.get("min"), "max": st.get("max"),
        })
    return {
        "__meta": {"schema_type": "Frame"},
        "frame_id": {"name": key},
        "rows": fr.nrow, "columns": cols, "column_count": fr.ncol,
    }


def _model_schema(m) -> dict:
    return {
        "__meta": {"schema_type": "Model"},
        "model_id": {"name": m.key},
        "algo": m.algo,
        "response_column_name": m.params.response_column,
        "output": {
            "model_category": (
                "Binomial" if m.is_classifier and m.nclasses == 2
                else "Multinomial" if m.is_classifier
                else "Regression"
            ),
            "training_metrics": m.training_metrics.to_dict() if m.training_metrics else None,
            "validation_metrics": m.validation_metrics.to_dict() if m.validation_metrics else None,
            "cross_validation_metrics": m.cross_validation_metrics.to_dict()
            if m.cross_validation_metrics else None,
            "variable_importances": m.varimp() if hasattr(m, "varimp") else None,
            "model_summary": m.model_summary() if hasattr(m, "model_summary") else None,
            "scoring_history": m.scoring_history,
        },
        "run_time_ms": m.run_time_ms,
    }


class Endpoints:
    """One method per route; the RequestServer below dispatches here."""

    # -- Flow UI (GET / and /flow) ------------------------------------------
    def flow_page(self, params):
        from h2o3_tpu.api.flow import FLOW_HTML

        return {"__binary__": FLOW_HTML.encode(), "content_type": "text/html"}

    # -- cloud / misc -----------------------------------------------------
    def cloud(self, params):
        from h2o3_tpu.cluster.cloud import cluster_info

        info = cluster_info()
        # surface the REAL per-device probe (cluster_info walks local devices
        # and marks any that fail the memory-stats probe unhealthy) — a fake
        # always-True here would hide a dead device from operators
        # node table covers the LOCALLY probed devices (multi-host peers
        # can't be memory-probed from here; cloud_size still counts all) —
        # an empty probe list stays empty rather than faking a healthy node
        nodes = [
            {"h2o": f"device_{n.get('id', i)}", "healthy": bool(n.get("healthy", True)),
             **({"mem_in_use": n["mem_in_use"]} if n.get("mem_in_use") is not None else {})}
            for i, n in enumerate(info.get("nodes", []))
        ]
        return {
            "__meta": {"schema_type": "Cloud"},
            "version": info.get("version", "0.1.0"),
            "cloud_name": info.get("cloud_name", "h2o3_tpu"),
            "cloud_size": info.get("cloud_size", 1),
            "cloud_healthy": bool(info.get("cloud_healthy", True)),
            # fail-stop latch reason (cluster_info sets it after a dead-member
            # collective failure) — the diagnostic operators need
            **({"degraded": info["degraded"]} if info.get("degraded") else {}),
            # cloud formation epoch: ticks on every supervised recover()
            # reform (cluster/recovery.py; the spmd generation fence)
            "generation": info.get("generation", 0),
            "nodes": nodes,
        }

    def ping(self, params):
        return {"__meta": {"schema_type": "Ping"}, "ok": True}

    def typeahead_files(self, params):
        """``GET /3/Typeahead/files`` [UNVERIFIED upstream
        water/api/TypeaheadHandler]: server-side path completion for the
        Flow import box. Only lists directories/files under the requested
        prefix's parent; no file CONTENT is exposed (same trust level as
        /3/ImportFiles, which already accepts arbitrary server paths)."""
        import glob as _glob
        import os as _os

        src = str(params.get("src") or "")
        try:
            limit = max(int(params.get("limit", 20) or 20), 1)
        except (ValueError, TypeError):
            raise ApiError(400, "limit must be an integer")
        matches: list[str] = []
        if src:
            pat = _glob.escape(src) + "*"
            try:
                for p in sorted(_glob.glob(pat))[:limit]:
                    matches.append(p + "/" if _os.path.isdir(p) else p)
            except OSError:
                pass
        return {"__meta": {"schema_type": "Typeahead"}, "src": src,
                "matches": matches}

    def metadata_schemas(self, params):
        """``GET /3/Metadata/schemas`` [UNVERIFIED upstream
        water/api/MetadataHandler]: schema listing for API discovery —
        here the params dataclasses ARE the schemas, so this walks the
        builder registry (the same source the bindings codegen renders)."""
        import dataclasses

        schemas = []
        for algo in _ALGOS:
            cls = _builder_cls(algo)
            fields = [
                {"name": f.name,
                 "type": getattr(f.type, "__name__", str(f.type))}
                for f in dataclasses.fields(cls.PARAMS_CLS)
            ]
            schemas.append({"name": f"{cls.__name__}ParametersV3",
                            "algo": algo, "fields": fields})
        return {"__meta": {"schema_type": "Metadata"}, "schemas": schemas,
                "routes": [
                    {"http_method": m, "url_pattern": p}
                    for m, p, _ in _ROUTES
                ]}

    def about(self, params):
        from h2o3_tpu import __version__

        return {"__meta": {"schema_type": "About"},
                "entries": [{"name": "Build version", "value": __version__},
                            {"name": "Backend", "value": "jax/XLA TPU"}]}

    # -- ingest -----------------------------------------------------------
    def import_files(self, params):
        path = params.get("path")
        if not path:
            raise ApiError(400, "path is required")
        return {"__meta": {"schema_type": "ImportFiles"},
                "files": [path], "destination_frames": [path], "fails": [], "dels": []}

    def parse_setup(self, params):
        from h2o3_tpu.frame.parse import parse_setup

        srcs = params.get("source_frames")
        if isinstance(srcs, str):
            srcs = json.loads(srcs) if srcs.startswith("[") else [srcs]
        setup = parse_setup(srcs[0])
        return {"__meta": {"schema_type": "ParseSetup"},
                "source_frames": srcs, **setup}

    def parse(self, params):
        from h2o3_tpu.frame.parse import parse

        srcs = params.get("source_frames")
        if isinstance(srcs, str):
            srcs = json.loads(srcs) if srcs.startswith("[") else [srcs]
        dest = params.get("destination_frame")
        if not dest:
            # h2o derives the key from the file name (foo.csv -> foo.hex)
            import os as _os

            base = _os.path.basename(str(srcs[0]))
            dest = base.rsplit(".", 1)[0] + ".hex"
        setup = {"source_frames": srcs}
        for k in ("separator", "column_types", "column_names"):
            if params.get(k) is not None:
                setup[k] = params[k] if not isinstance(params[k], str) or not params[k].startswith(("[", "{")) else json.loads(params[k])
        if str(params.get("sharded", "")).lower() in ("1", "true"):
            setup["sharded"] = True  # per-rank row-range ingest (parse_sharded)
        from h2o3_tpu.cluster import spmd

        job = _start_job(lambda j: spmd.run("parse", setup=setup, dest=dest),
                         f"Parse {srcs[0]}")
        return {"__meta": {"schema_type": "Parse"}, "job": _job_schema(job),
                "destination_frame": {"name": dest}}

    # -- frames -----------------------------------------------------------
    def frames_list(self, params):
        out = []
        for k in DKV.keys():
            v = DKV.get(k)
            if isinstance(v, Frame):
                out.append({"frame_id": {"name": k}, "rows": v.nrow, "column_count": v.ncol})
        return {"__meta": {"schema_type": "Frames"}, "frames": out}

    def frame_get(self, params, key):
        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise ApiError(404, f"Frame {key} not found")
        return {"__meta": {"schema_type": "Frames"}, "frames": [_frame_schema(fr, key)]}

    def frame_summary(self, params, key):
        from h2o3_tpu.cluster import spmd

        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise ApiError(404, f"Frame {key} not found")
        # replicated: every rank computes (and caches) the rollup stats, so
        # the per-column pulls are collectives entered by all ranks together
        summary = spmd.run("frame_summary", key=key)
        return {"__meta": {"schema_type": "FrameSummary"},
                "frames": [_frame_schema(fr, key)],
                "summary": json.loads(summary.to_json())}

    def frame_delete(self, params, key):
        from h2o3_tpu.cluster import spmd

        spmd.run("remove", key=key)  # replicated: every rank's DKV must agree
        return {"__meta": {"schema_type": "Frames"}, "frames": []}

    def download_dataset(self, params):
        """``/3/DownloadDataset?frame_id=…`` — frame rows as CSV (the route
        h2o clients use to materialize frames locally)."""
        from h2o3_tpu.cluster import spmd

        key = params.get("frame_id")
        key = key["name"] if isinstance(key, dict) else key
        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise ApiError(404, f"Frame {key} not found")
        csv = spmd.run("frame_pull", key=key).to_csv(index=False)
        return {"__binary__": csv.encode(), "content_type": "text/csv",
                "filename": f"{key}.csv"}

    def frame_export(self, params, key):
        """``/3/Frames/{id}/export`` — CSV/Parquet to a server-side path."""
        from h2o3_tpu.cluster import spmd

        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise ApiError(404, f"Frame {key} not found")
        path = params.get("path")
        if not path:
            raise ApiError(400, "path parameter is required")
        force = str(params.get("force", "false")).lower() in ("1", "true")
        spmd.run("frame_export", key=key, path=path, force=force,
                 format=params.get("format"))
        return {"__meta": {"schema_type": "Frames"}, "path": path}

    # -- jobs -------------------------------------------------------------
    def jobs_list(self, params):
        jobs = [j for j in DKV.values_of_type(Job)]
        return {"__meta": {"schema_type": "Jobs"}, "jobs": [_job_schema(j) for j in jobs]}

    def job_get(self, params, key):
        j = DKV.get(key)
        if not isinstance(j, Job):
            raise ApiError(404, f"Job {key} not found")
        return {"__meta": {"schema_type": "Jobs"}, "jobs": [_job_schema(j)]}

    def job_cancel(self, params, key):
        j = DKV.get(key)
        if not isinstance(j, Job):
            raise ApiError(404, f"Job {key} not found")
        if not getattr(j, "cancellable", True):
            raise ApiError(
                400, "this job replicates device work across a multi-process "
                     "cloud and cannot be cancelled mid-run (aborting one "
                     "rank's collective sequence would desync the cloud)"
            )
        j.cancel()
        return {"__meta": {"schema_type": "Jobs"}, "jobs": [_job_schema(j)]}

    # -- model builders ---------------------------------------------------
    def model_builders(self, params):
        return {"__meta": {"schema_type": "ModelBuilders"},
                "model_builders": {a: {"algo": a, "visibility": "Stable"} for a in _ALGOS}}

    def model_builder_get(self, params, algo):
        """``GET /3/ModelBuilders/{algo}`` — the parameter schema (upstream
        returns the reflective Schema metadata here; the params dataclass is
        our single schema source, SURVEY §5.6). Flow's build forms render
        from this."""
        import dataclasses

        if algo not in _ALGOS:
            raise ApiError(404, f"unknown algo {algo!r}")
        cls = _builder_cls(algo)
        fields = []
        for f in dataclasses.fields(cls.PARAMS_CLS):
            default = f.default
            if default is dataclasses.MISSING:  # incl. default_factory fields
                default = None
            if isinstance(default, float) and (default != default or default in (float("inf"), float("-inf"))):
                default = None
            fields.append({
                "name": f.name,
                "type": getattr(f.type, "__name__", str(f.type)),
                "default_value": default if isinstance(default, (int, float, str, bool, type(None))) else str(default),
            })
        aliases = dict(getattr(cls, "PARAM_ALIASES", {}) or {})
        return {"__meta": {"schema_type": "ModelBuilders"},
                "model_builders": {algo: {"algo": algo, "visibility": "Stable",
                                          "parameters": fields,
                                          "aliases": aliases}}}

    def build_model(self, params, algo):
        if algo not in _ALGOS:
            raise ApiError(404, f"unknown algo {algo!r}")
        cls = _builder_cls(algo)
        kwargs, x, y, train_key, valid_key = self._parse_build_params(cls, params)
        if train_key is None:
            raise ApiError(400, "training_frame is required")
        cls(**kwargs)  # validate params NOW so bad requests fail fast
        from h2o3_tpu.cluster import recovery, spmd
        from h2o3_tpu.utils import overload as _ov

        dest = DKV.make_key(algo)  # coordinator-chosen, carried to followers
        ckdir = kwargs.get("export_checkpoints_dir")

        # memory-aware admission (ISSUE 19): the build's estimated device
        # footprint against measured headroom net of live reservations —
        # fits resident (reservation for the full footprint), streams
        # (reservation for the window share; ChunkStore.plan picks the
        # geometry), or sheds 503 with the reservation-queue Retry-After
        admitted = False
        fr = DKV.get(train_key)
        if fr is not None and hasattr(fr, "npad"):
            try:
                est = _ov.estimate_build_bytes(fr, algo)
                mode = _ov.admit(dest, est, algo=algo)
            except _ov.Shed as e:
                _REST_REJECTED.inc(method="POST", route="<job>",
                                   reason="memory")
                raise ApiError(
                    503, str(e),
                    headers={"Retry-After":
                             str(max(int(round(e.retry_after)), 1))},
                    reason="memory") from None
            admitted = mode != "off"

        def _work(j):
            # checkpointed builds run under the recovery supervisor: a cloud
            # failure (dead member, watchdog trip) re-forms the cloud and
            # relaunches from the latest interval snapshot instead of dying
            # at the operator (cluster/recovery.py; H2O3_TPU_RECOVERY=0
            # restores the plain fail-stop launch — run_supervised then
            # propagates the first failure untouched)
            def _launch(ckpt):
                kw = dict(kwargs, checkpoint=ckpt) if ckpt else kwargs
                return spmd.run(
                    "build", algo=algo, kwargs=kw, x=x, y=y,
                    train=train_key, valid=valid_key, dest=dest,
                )

            def _run():
                return recovery.run_supervised(
                    _launch, ckdir=ckdir, algo=algo,
                    description=f"{algo} build", job=j)

            if not admitted:
                return _run()
            # job_scope: plan_window excludes this job's own reservation
            # (a resident admission must not push itself to the streamed
            # lane) and the reservation releases on exit either way
            with _ov.job_scope(dest):
                return _run()

        try:
            job = _start_job(_work, f"{algo} build")
        except BaseException:
            if admitted:
                _ov.finish(dest)  # never started: return the reservation
            raise
        return {"__meta": {"schema_type": "ModelBuilder"},
                "job": _job_schema(job), "algo": algo,
                "messages": [], "error_count": 0}

    def _parse_build_params(self, cls, params):
        """Shared param parsing for model and grid builds."""
        import dataclasses

        valid = {f.name for f in dataclasses.fields(cls.PARAMS_CLS)}
        # builder-declared param aliases (e.g. XGBoost's eta -> learn_rate)
        # resolve to their canonical field before coercion
        aliases = dict(getattr(cls, "PARAM_ALIASES", {}) or {})
        kwargs = {}
        x = y = train_key = valid_key = None
        for k, v in params.items():
            if k in ("training_frame", "validation_frame"):
                name = v["name"] if isinstance(v, dict) else str(v)
                if k == "training_frame":
                    train_key = name
                else:
                    valid_key = name
            elif k == "response_column":
                y = v
            elif k in ("x", "ignored_columns") and v is not None:
                vv = json.loads(v) if isinstance(v, str) and v.startswith("[") else v
                if k == "x":
                    x = vv
                else:
                    kwargs["ignored_columns"] = tuple(vv)
            elif k == "model_id":
                continue  # keys are server-assigned
            elif k in valid or k in aliases:
                # aliases keep their name (the builder translates and owns
                # conflict/semantics, e.g. max_delta_step's 0=unlimited);
                # coercion borrows the canonical field's type
                kwargs[k] = _coerce_param(cls.PARAMS_CLS, aliases.get(k, k), v)
        return kwargs, x, y, train_key, valid_key

    # -- grids (hex.grid.GridSearch REST surface, /99/Grid*) ---------------
    def grid_build(self, params, algo):
        if algo not in _ALGOS:
            raise ApiError(404, f"unknown algo {algo!r}")
        cls = _builder_cls(algo)
        hyper = params.get("hyper_parameters")
        if hyper is None:
            raise ApiError(400, "hyper_parameters is required")
        if isinstance(hyper, str):
            hyper = json.loads(hyper)
        criteria = params.get("search_criteria")
        if isinstance(criteria, str):
            criteria = json.loads(criteria)
        grid_id = params.get("grid_id")
        par = params.get("parallelism")
        parallelism = int(par) if par not in (None, "") else 1
        base = {
            k: v for k, v in params.items()
            if k not in ("hyper_parameters", "search_criteria", "grid_id",
                         "parallelism")
        }
        kwargs, x, y, train_key, valid_key = self._parse_build_params(cls, base)
        if train_key is None:
            raise ApiError(400, "training_frame is required")

        from h2o3_tpu.cluster import spmd

        if not spmd.multi_process():
            from h2o3_tpu.models.grid import GridSearch

            gs = GridSearch(cls, hyper, search_criteria=criteria,
                            grid_id=grid_id, parallelism=parallelism, **kwargs)
            job = _start_job(
                lambda j: gs._drive(j, x, y, DKV.get(train_key),
                                    DKV.get(valid_key) if valid_key else None, {}),
                f"grid over {algo}",
            )
            gs.job = job
            return {"__meta": {"schema_type": "GridSearchV99"},
                    "job": _job_schema(job), "grid_id": {"name": gs.grid.key}}
        # multi-process: the whole grid runs as ONE replicated command; every
        # rank's deterministic key sequence (registry.make_key) keeps the
        # grid's model keys aligned without carrying them individually
        grid_id = grid_id or DKV.make_key("grid")
        # placeholder so GET /99/Grids/{id} resolves between this response
        # and the replicated command constructing the real grid
        from h2o3_tpu.models.grid import Grid as _Grid

        _Grid(grid_id, cls, sorted(hyper))
        job = _start_job(
            lambda j: spmd.run(
                "grid", algo=algo, hyper=hyper, criteria=criteria,
                grid_id=grid_id, parallelism=parallelism, kwargs=kwargs,
                x=x, y=y, train=train_key, valid=valid_key,
            ),
            f"grid over {algo}",
            cancellable=False,  # replicated collective sequence (see spmd)
        )
        return {"__meta": {"schema_type": "GridSearchV99"},
                "job": _job_schema(job), "grid_id": {"name": grid_id}}

    def grids_list(self, params):
        from h2o3_tpu.models.grid import Grid

        gs = list(DKV.values_of_type(Grid))
        return {"__meta": {"schema_type": "Grids"},
                "grids": [{"grid_id": {"name": g.key},
                           "model_count": len(g.models)} for g in gs]}

    def grid_get(self, params, key):
        from h2o3_tpu.models.grid import Grid

        g = DKV.get(key)
        if not isinstance(g, Grid):
            raise ApiError(404, f"Grid {key} not found")
        tab = g.sorted_metric_table(params.get("sort_by"))
        # model_ids sorted to MATCH the metric table (H2O's Grid schema
        # orders them together; [0] must be the leader)
        ordered = [r["model_id"] for r in tab] or g.model_ids
        return {"__meta": {"schema_type": "Grids"},
                "grids": [{
                    "grid_id": {"name": g.key},
                    "hyper_names": g.hyper_names,
                    "model_ids": [{"name": k} for k in ordered],
                    "summary_table": tab,
                    "failure_details": [msg for _, msg in g.failures],
                }]}

    # -- metrics (the /3/Metrics registry + per-job traces) -----------------
    def metrics_get(self, params):
        """``GET /3/Metrics`` — the whole registry. Default is Prometheus
        text exposition (scrape-ready); ``?format=json`` returns the same
        families as structured JSON. ``?scope=pod`` federates every rank's
        registry into one view (counters sum, histograms merge, gauges keep
        per-rank series under a ``rank`` label) — on a multi-process cloud
        the snapshot gather is a collective, dispatched as the replicated
        ``metrics_pod`` command, so it serializes behind running device
        work like any other command."""
        # materialize lazily-imported subsystems' metric families so a scrape
        # right after boot still covers persist/cloud/mrtask (families
        # register at module import; routes import these modules lazily)
        import h2o3_tpu.persist  # noqa: F401
        import h2o3_tpu.serving  # noqa: F401
        from h2o3_tpu.cluster import cloud  # noqa: F401
        from h2o3_tpu.parallel import mrtask  # noqa: F401

        as_json = str(params.get("format", "")).lower() == "json"
        if str(params.get("scope", "")).lower() == "pod":
            from h2o3_tpu.cluster import federation, spmd

            merged = (spmd.run("metrics_pod") if spmd.multi_process()
                      else federation.pod_snapshot())
            if as_json:
                return {"__meta": {"schema_type": "Metrics"},
                        "scope": "pod", "families": merged}
            return {"__binary__": _metrics.render_snapshot(merged).encode(),
                    "content_type":
                        "text/plain; version=0.0.4; charset=utf-8"}
        if as_json:
            return {"__meta": {"schema_type": "Metrics"},
                    "families": _metrics.REGISTRY.snapshot()}
        return {"__binary__": _metrics.REGISTRY.to_prometheus().encode(),
                "content_type": "text/plain; version=0.0.4; charset=utf-8"}

    def job_trace(self, params, key):
        """``GET /3/Jobs/{key}/trace`` — the job's span tree as Chrome-trace
        JSON (load in Perfetto / chrome://tracing)."""
        j = DKV.get(key)
        if not isinstance(j, Job):
            raise ApiError(404, f"Job {key} not found")
        return _metrics.chrome_trace(key)

    def flight_recorder(self, params):
        """``GET /3/FlightRecorder?n=&kind=`` — the always-on dispatch ring
        (utils/flightrec.py) plus the devmem attribution snapshot and the
        last incident-bundle path: the live half of what an incident
        bundle freezes. ``n`` bounds the returned events (default 512),
        ``kind`` filters (dispatch_start/dispatch_end/chunk_fetch/...).
        ``?format=trace`` instead renders the ring's span trees as
        Chrome/Perfetto trace JSON (one lane per trace id; ``?trace=``
        narrows to one job/request trace) — save it and open in
        https://ui.perfetto.dev or chrome://tracing."""
        from h2o3_tpu.utils import devmem, flightrec

        try:
            n = int(params.get("n", 512))
        except (TypeError, ValueError):
            raise ApiError(400, "n must be an integer")
        if str(params.get("format", "")).lower() == "trace":
            return flightrec.trace_export(
                trace=params.get("trace") or None, n=max(n, 0) or None)
        kind = params.get("kind") or None
        return {
            "__meta": {"schema_type": "FlightRecorder"},
            "ring": flightrec.ring_status(),
            "events": flightrec.events(n=max(n, 0) or None, kind=kind),
            "last_incident": flightrec.last_incident(),
            "incident_dir": flightrec.incident_dir(),
            "devmem": devmem.status(),
        }

    # -- timeline (water.TimeLine /3/Timeline successor) --------------------
    def timeline(self, params):
        from h2o3_tpu.utils import telemetry

        return {"__meta": {"schema_type": "TimelineV3"},
                **telemetry.timeline(int(params.get("n", 200)))}

    def profiler(self, params):
        """``GET /3/Profiler`` — stack snapshot of every thread (upstream's
        JProfile/JStack on-demand sampling, SURVEY §5.1). ``depth`` trims
        frames per thread like upstream's depth parameter."""
        import sys
        import traceback

        depth = max(1, int(params.get("depth", 20)))  # -0 slices keep ALL
        names = {t.ident: t.name for t in threading.enumerate()}
        stacks = []
        for ident, frame in sys._current_frames().items():
            entries = traceback.format_stack(frame)[-depth:]
            stacks.append({
                "thread": names.get(ident, str(ident)),
                "stack": [e.rstrip() for e in entries],
            })
        return {"__meta": {"schema_type": "ProfilerV3"},
                "nodes": [{"node_name": "coordinator", "profile": stacks}]}

    # -- logs (water.util.Log REST surface) --------------------------------
    def logs_get(self, params, node, name):
        tail = int(params.get("tail", 1000))
        kept = Log.tail(tail)
        return {"__meta": {"schema_type": "LogsV3"},
                "log": "\n".join(kept), "name": name, "node": node}

    def logs_tail(self, params):
        """``GET /3/Logs?n=&level=`` — the in-memory ring buffer tail, with
        an optional minimum level (FATAL/ERRR/WARN/INFO/DEBUG/TRACE). The
        plain-path twin of the upstream nodes/files route above."""
        try:
            n = int(params.get("n", 100))
        except (TypeError, ValueError):
            raise ApiError(400, "n must be an integer")
        try:
            lines = Log.tail(n, level=params.get("level"))
        except ValueError as e:  # unknown level name
            raise ApiError(400, str(e))
        return {"__meta": {"schema_type": "LogsV3"},
                "log": "\n".join(lines), "lines": lines,
                "count": len(lines)}

    # -- mojo download (GET /3/Models/{id}/mojo) ----------------------------
    def model_save_bin(self, params, key):
        """``POST /99/Models.bin/{model}?dir=`` — binary save (upstream
        ``water.api.ModelsHandler`` save route)."""
        from h2o3_tpu.cluster import spmd

        m = _get_model(key)
        d = params.get("dir") or "."
        path = spmd.run("model_save", key=m.key, dir=d,
                        force=str(params.get("force", "1")).lower() in ("1", "true"))
        return {"__meta": {"schema_type": "Models"}, "dir": path,
                "models": [{"model_id": {"name": m.key}}]}

    def model_load_bin(self, params):
        """``POST /99/Models.bin?dir=`` — binary load."""
        from h2o3_tpu.cluster import spmd

        d = params.get("dir")
        if not d:
            raise ApiError(400, "dir is required")
        m = spmd.run("model_load", dir=d)
        return {"__meta": {"schema_type": "Models"},
                "models": [_model_schema(m)]}

    @staticmethod
    def _export_download(model, exporter, suffix: str, content_type: str) -> dict:
        """Shared artifact-download plumbing for the mojo/pojo routes:
        export to a temp file, read, clean up; unsupported-algo ValueError
        maps to 400 in exactly one place."""
        import os as _os
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=suffix, delete=False) as f:
            path = f.name
        try:
            exporter(model, path)
            with open(path, "rb") as f:
                data = f.read()
        except ValueError as e:  # unsupported algo for this artifact
            raise ApiError(400, str(e))
        finally:
            _os.unlink(path)
        return {"__binary__": data, "content_type": content_type,
                "filename": f"{model.key}{suffix}"}

    def model_mojo(self, params, key):
        import h2o3_tpu.models.export as _exp

        return self._export_download(
            _get_model(key), _exp.export_mojo, ".zip", "application/zip")

    def model_pojo(self, params, key):
        """``GET /3/Models/{id}/pojo`` — the POJO-download analog: one
        self-contained numpy scoring script (upstream emits one Java class)."""
        import h2o3_tpu.models.export as _exp

        return self._export_download(
            _get_model(key), _exp.export_pojo, ".py", "text/x-python")

    # -- models -----------------------------------------------------------
    def models_list(self, params):
        from h2o3_tpu.models.model_base import Model

        ms = list(DKV.values_of_type(Model))
        return {"__meta": {"schema_type": "Models"},
                "models": [{"model_id": {"name": m.key}, "algo": m.algo} for m in ms]}

    def model_get(self, params, key):
        m = _get_model(key)
        return {"__meta": {"schema_type": "Models"}, "models": [_model_schema(m)]}

    def model_delete(self, params, key):
        from h2o3_tpu.cluster import spmd

        from h2o3_tpu.models.model_base import Model

        m = DKV.get(key)
        m = m if isinstance(m, Model) else None
        spmd.run("remove", key=key)  # replicated: every rank's DKV must agree
        if m is not None:
            # a deleted model must not keep a dispatcher thread + HBM
            from h2o3_tpu import serving

            serving.retire_model(key, m)
        return {"__meta": {"schema_type": "Models"}, "models": []}

    def serving_registry(self, params):
        """``GET /3/ServingRegistry`` — the fleet serving plane's state:
        registry entries (key, generation, snapshot path/etag, scorer lane,
        residency tier) plus the device-residency LRU totals the HPA
        scrapes. Serves (with enabled=false) even when
        H2O3_TPU_SERVE_REGISTRY=0 so operators can see the switch state."""
        from h2o3_tpu.serving import registry as _sreg

        out = _sreg.REGISTRY.status()
        out["__meta"] = {"schema_type": "ServingRegistry"}
        return out

    # -- predictions ------------------------------------------------------
    def predict(self, params, model_key, frame_key):
        m = _get_model(model_key)
        fr = DKV.get(frame_key)
        if not isinstance(fr, Frame):
            raise ApiError(404, f"Frame {frame_key} not found")
        generated_dest = not params.get("predictions_frame")
        dest = params.get("predictions_frame") or DKV.make_key("prediction")

        def _flag(name):
            v = params.get(name)
            return v if isinstance(v, bool) else str(v).lower() in ("1", "true")

        # upstream predict options (water/api/ModelMetricsHandler PredictV3):
        # SHAP contributions / terminal-leaf assignment instead of predictions
        option = ""
        if _flag("predict_contributions"):
            option = "contributions"
        elif _flag("leaf_node_assignment") or _flag("predict_leaf_node_assignment"):
            option = "leaf_assignment"
        elif _flag("reconstruction_error"):
            option = "reconstruction_error"
        if option and not hasattr(m, {
            "contributions": "predict_contributions",
            "leaf_assignment": "predict_leaf_node_assignment",
            "reconstruction_error": "anomaly",
        }[option]):
            raise ApiError(400, f"{m.algo} does not support {option}")
        from h2o3_tpu.cluster import spmd

        try:
            pred = spmd.run(
                "predict", model_key=model_key, frame_key=frame_key, dest=dest,
                option=option,
                leaf_type=str(params.get("leaf_node_assignment_type") or "Path"),
            )
        except ValueError as e:
            # user-input errors from the option paths (multinomial
            # contributions, bad leaf type) are 400s, not server faults
            raise ApiError(400, str(e))
        if generated_dest:
            _retain_prediction_frame(dest)
        return {"__meta": {"schema_type": "Predictions"},
                "predictions_frame": {"name": dest},
                "model_metrics": []}

    def predict_rows(self, params):
        """``POST /3/Predictions/rows`` — the low-latency scoring route: row
        payloads in, predictions out, no DKV frame round-trip. Requests are
        coalesced into batched device dispatches by the scoring tier
        (h2o3_tpu/serving; H2O3_TPU_SCORE_* knobs) and run behind the
        admission gates with a per-route deadline. Body (JSON)::

            {"model": "<model key>",
             "rows": [{"col": value, ...}, ...]}   # or a column table

        Returns ``predictions`` as column arrays in the EasyPredict layout
        (``predict`` + per-class probabilities + ``cal_p*`` when the model
        is calibrated)."""
        model_key = params.get("model") or params.get("model_id")
        if isinstance(model_key, dict):
            model_key = model_key.get("name")
        if not model_key:
            raise ApiError(400, "model is required")
        model_key = str(model_key)
        # fleet resolution: the serving registry's current generation wins
        # (watch-and-load rollouts without operator action); disabled or
        # unknown keys fall through to the DKV (the PR-7 manual-load path)
        from h2o3_tpu.serving import registry as _sreg

        m = _sreg.resolve(model_key)
        from_registry = m is not None
        if m is None:
            m = _get_model(model_key)
        rows = params.get("rows")
        if isinstance(rows, str):
            try:
                rows = json.loads(rows)
            except ValueError as e:
                raise ApiError(400, f"bad rows payload: {e}")
        if not rows:
            raise ApiError(
                400, "rows is required (a list of {column: value} dicts or "
                     "a {column: [values]} table)")
        from h2o3_tpu.cluster import spmd

        if spmd.multi_process():
            # the compiled scorer dispatches locally, outside the replicated
            # command stream — on a multi-host training cloud that would
            # desync the ranks' collective order. Scoring scales OUT via
            # single-process replicas (deploy/k8s.yaml h2o3-tpu-score).
            raise ApiError(
                501, "/3/Predictions/rows serves from single-process "
                     "scoring replicas, not a multi-process training cloud "
                     "— see the h2o3-tpu-score Deployment in deploy/k8s.yaml")
        from h2o3_tpu import serving

        try:
            with _metrics.span("serving.predict_rows"):
                out = serving.score_rows(m, rows)
        except serving.ShedError as e:
            raise ApiError(e.status, str(e),
                           headers={"Retry-After": e.retry_after})
        except (ValueError, KeyError, TypeError) as e:
            raise ApiError(400, str(e))  # payload errors never trip rollback
        except Exception as e:
            if from_registry:
                # the rollout breaker: a freshly rolled-out generation that
                # cannot score rolls back to the previous one
                _sreg.REGISTRY.note_score_failure(model_key, e)
            raise
        if from_registry:
            _sreg.REGISTRY.note_score_ok(model_key)
        n = len(next(iter(out.values()))) if out else 0
        return {"__meta": {"schema_type": "PredictionsRows"},
                "model_id": {"name": m.key},
                "rows": n,
                "predictions": out}

    def model_metrics(self, params, model_key, frame_key):
        m = _get_model(model_key)
        fr = DKV.get(frame_key)
        if not isinstance(fr, Frame):
            raise ApiError(404, f"Frame {frame_key} not found")
        mm = m.model_performance(fr)
        return {"__meta": {"schema_type": "ModelMetrics"},
                "model_metrics": [mm.to_dict()]}

    def make_metrics(self, params, pred_key, act_key):
        """``POST /3/ModelMetrics/predictions_frame/{p}/actuals_frame/{a}``
        [UNVERIFIED upstream water/api/ModelMetricsMaker route]: metrics
        from raw prediction/actual frames, no model."""
        from h2o3_tpu.models.metrics import make_metrics

        pred = DKV.get(pred_key)
        act = DKV.get(act_key)
        if not isinstance(pred, Frame) or not isinstance(act, Frame):
            raise ApiError(404, "predictions or actuals frame not found")
        domain = params.get("domain")
        try:
            if isinstance(domain, str) and domain:
                domain = (json.loads(domain) if domain.startswith("[")
                          else [domain])
        except ValueError as e:
            raise ApiError(400, f"bad domain: {e}")
        # single-column actuals; a multi-col predictions frame is multinomial
        act_vec = act.vec(0) if act.ncol == 1 else act.vec(
            params.get("actuals_column") or act.names[0])
        if pred.ncol > 1:
            # the standard /3/Predictions output carries a categorical
            # "predict" column ahead of the per-class probabilities — using
            # its CODES as a probability column would silently corrupt the
            # metrics, so it is dropped; with a domain, the class-label
            # columns are picked (binomial: P(positive) = last label)
            use = [n for n in pred.names if n != "predict"]
            if domain and all(str(d) in pred.names for d in domain):
                use = [str(d) for d in domain]
            if not use:
                raise ApiError(400, "predictions frame has no probability columns")
            if len(use) == 1:
                pred_in = pred.vec(use[0])
            elif domain and len(domain) == 2:
                # P(positive class): the domain-named column when the frame
                # has one, else the LAST probability column (p0/p1 layouts)
                pos = str(domain[-1])
                pred_in = pred.vec(pos if pos in pred.names else use[-1])
            else:
                pred_in = Frame([pred.vec(n) for n in use], use, register=False)
        else:
            pred_in = pred.vec(0)
        try:
            mm = make_metrics(
                pred_in, act_vec,
                domain=tuple(domain) if domain else None,
                distribution=str(params.get("distribution") or "gaussian"),
            )
        except (ValueError, AssertionError) as e:
            raise ApiError(400, str(e))
        return {"__meta": {"schema_type": "ModelMetricsMaker"},
                "model_metrics": [mm.to_dict()]}

    def partial_dependence(self, params):
        """``POST /3/PartialDependence`` [UNVERIFIED upstream
        water/api/PartialDependenceHandler]: PD tables for the given
        columns, computed synchronously (tables returned inline)."""
        from h2o3_tpu.explain import partial_dependence

        model_key = params.get("model_id") or params.get("model")
        if isinstance(model_key, dict):
            model_key = model_key.get("name")
        m = _get_model(str(model_key))
        frame_key = self._resolve_frame_key(params, "frame_id", "source_frame")
        fr = DKV.get(frame_key)
        if params.get("col_pairs_2dpdp"):
            raise ApiError(400, "2-D partial dependence is not supported; pass cols")
        try:
            cols = params.get("cols")
            if isinstance(cols, str):
                cols = json.loads(cols) if cols.startswith("[") else [cols]
        except ValueError as e:
            raise ApiError(400, f"bad cols: {e}")
        if not cols or not all(isinstance(c, str) for c in cols):
            raise ApiError(400, "cols must be a list of column names")
        try:
            nbins = int(params.get("nbins", 20))
            tables = [partial_dependence(m, fr, c, nbins=nbins) for c in cols]
        except (ValueError, KeyError) as e:
            raise ApiError(400, f"bad PartialDependence request: {e}")
        return {"__meta": {"schema_type": "PartialDependence"},
                "partial_dependence_data": tables, "cols": list(cols)}

    # -- automl -----------------------------------------------------------
    def automl_build(self, params):
        from h2o3_tpu.automl import AutoML

        spec = params.get("build_control", {})
        if isinstance(spec, str):
            spec = json.loads(spec)
        input_spec = params.get("input_spec", {})
        if isinstance(input_spec, str):
            input_spec = json.loads(input_spec)
        build_models = params.get("build_models", {})
        if isinstance(build_models, str):
            build_models = json.loads(build_models)

        kwargs = {}
        sc = spec.get("stopping_criteria", {})
        for src, dst in (("max_models", "max_models"),
                         ("max_runtime_secs", "max_runtime_secs"),
                         ("seed", "seed")):
            if sc.get(src) is not None:
                kwargs[dst] = sc[src]
        if spec.get("nfolds") is not None:
            kwargs["nfolds"] = spec["nfolds"]
        if spec.get("project_name"):
            kwargs["project_name"] = spec["project_name"]
        if spec.get("export_checkpoints_dir"):
            # crash recovery over REST (docs/RECOVERY.md); rejected by
            # _exec_automl on multi-process clouds like the grid analog
            kwargs["export_checkpoints_dir"] = spec["export_checkpoints_dir"]
        for src in ("include_algos", "exclude_algos"):
            if build_models.get(src):
                kwargs[src] = build_models[src]

        train_key = (input_spec.get("training_frame") or {})
        train_key = train_key.get("name") if isinstance(train_key, dict) else train_key
        y = (input_spec.get("response_column") or {})
        y = y.get("column_name") if isinstance(y, dict) else y
        if not train_key or not y:
            raise ApiError(400, "input_spec.training_frame and response_column required")

        from h2o3_tpu.cluster import spmd

        if not spmd.multi_process():
            from h2o3_tpu.cluster import recovery

            aml = AutoML(**kwargs)
            aml_key = aml.key

            def _aml_work(j, first=aml):
                # checkpointed AutoML self-heals through its step manifest: a
                # relaunch with the same spec + dir recovers finished steps
                # (and the poison-step guard skips a step that keeps
                # crashing), so the supervisor's "checkpoint" is the
                # manifest itself — each attempt gets a FRESH AutoML bound
                # to the original key the client is polling
                holder = {"aml": first}

                def _launch(_ckpt):
                    if holder["aml"] is None:
                        fresh = AutoML(**kwargs)
                        DKV.remove(fresh.key)
                        fresh.key = aml_key
                        DKV.put(aml_key, fresh)
                        holder["aml"] = fresh
                    a, holder["aml"] = holder["aml"], None
                    return a.train(y=y, training_frame=train_key)

                return recovery.run_supervised(
                    _launch,
                    ckdir=kwargs.get("export_checkpoints_dir"),
                    description="AutoML build", job=j)

            job = _start_job(_aml_work, "AutoML build")
            return {"__meta": {"schema_type": "AutoMLBuilder"},
                    "job": _job_schema(job),
                    "automl_id": {"name": aml_key}}
        dest = DKV.make_key("automl")
        # placeholder for the response→command registration window
        placeholder = AutoML(**kwargs)
        DKV.remove(placeholder.key)
        placeholder.key = dest
        DKV.put(dest, placeholder)
        job = _start_job(
            lambda j: spmd.run("automl", kwargs=kwargs, y=y, train=train_key,
                               dest=dest),
            "AutoML build",
            cancellable=False,  # replicated collective sequence (see spmd)
        )
        return {"__meta": {"schema_type": "AutoMLBuilder"},
                "job": _job_schema(job),
                "automl_id": {"name": dest}}

    def automl_get(self, params, key):
        aml = DKV.get(key)
        if aml is None or not hasattr(aml, "leaderboard"):
            raise ApiError(404, f"AutoML {key} not found")
        lb = aml.leaderboard
        return {"__meta": {"schema_type": "AutoML"},
                "automl_id": {"name": aml.key},
                "leaderboard_table": lb.as_table() if lb else [],
                "leader": {"name": lb.leader.key} if lb and lb.leader else None,
                "event_log": aml.event_log}

    # -- frame utilities (SplitFrame / CreateFrame handlers) ----------------

    @staticmethod
    def _resolve_frame_key(params, *names):
        """Unwrap a frame reference ({'name': k} or str) from the first of
        ``names`` present; 404 unless it resolves to a registered Frame."""
        key = None
        for n in names:
            key = params.get(n)
            if key:
                break
        if isinstance(key, dict):
            key = key.get("name")
        if not key or not isinstance(DKV.get(key), Frame):
            raise ApiError(404, f"Frame {key!r} not found")
        return key

    @staticmethod
    def _resolve_dest(params, default_prefix: str):
        dest = params.get("dest") or params.get("destination_frame")
        if isinstance(dest, dict):
            dest = dest.get("name")
        return dest or DKV.make_key(default_prefix)


    def split_frame(self, params):
        """``POST /3/SplitFrame`` [UNVERIFIED upstream
        water/api/SplitFrameHandler]: random row split into ratio parts."""
        from h2o3_tpu.cluster import spmd

        frame_key = self._resolve_frame_key(params, "dataset", "frame_id")
        try:
            ratios = params.get("ratios")
            if isinstance(ratios, str):
                ratios = json.loads(ratios)
            if isinstance(ratios, (int, float)):
                ratios = [ratios]
            if not ratios:
                raise ApiError(400, "ratios is required")
            ratios = [float(r) for r in ratios]
            seed = params.get("seed")
            seed = 1234 if seed in (None, "") else int(seed)
        except (ValueError, TypeError) as e:
            raise ApiError(400, f"bad SplitFrame parameters: {e}")
        if any(r <= 0 for r in ratios) or sum(ratios) > 1.0 + 1e-9:
            raise ApiError(400, "ratios must be positive and sum to <= 1")
        dests = params.get("destination_frames")
        if isinstance(dests, str):
            dests = json.loads(dests)
        n_parts = len(ratios) + (1 if sum(ratios) < 1.0 - 1e-9 else 0)
        if not dests:
            dests = [DKV.make_key("split") for _ in range(n_parts)]
        dests = [d["name"] if isinstance(d, dict) else str(d) for d in dests]
        if len(dests) != n_parts:
            raise ApiError(
                400, f"destination_frames must name all {n_parts} parts "
                f"(ratios summing < 1 add a remainder part); got {len(dests)}")
        job = _start_job(
            lambda j: spmd.run("split_frame", frame_key=frame_key,
                               ratios=ratios, dests=dests, seed=seed),
            "SplitFrame",
        )
        try:
            _join_for_handler(job)
        except RuntimeError as e:
            raise ApiError(400, str(e))
        return {"__meta": {"schema_type": "SplitFrame"},
                "job": _job_schema(job),
                "destination_frames": [{"name": d} for d in dests]}

    def create_frame(self, params):
        """``POST /3/CreateFrame`` [UNVERIFIED upstream
        water/api/CreateFrameHandler]: synthetic random frame."""
        from h2o3_tpu.cluster import spmd

        dest = self._resolve_dest(params, "created_frame")
        spec = {k: params[k] for k in (
            "rows", "cols", "seed", "categorical_fraction",
            "integer_fraction", "binary_fraction", "missing_fraction",
            "factors", "real_range", "integer_range", "has_response",
            "response_factors",
        ) if k in params}
        try:
            for k, v in list(spec.items()):
                if isinstance(v, str):
                    spec[k] = (json.loads(v.lower())
                               if v.lower() in ("true", "false") else float(v))
            if int(spec.get("seed", -1)) < 0:
                # unseeded: the COORDINATOR draws the seed so every rank of a
                # multi-process cloud generates identical data (the spmd
                # replicated-determinism contract)
                import random

                spec["seed"] = random.randrange(1 << 31)
        except (ValueError, TypeError) as e:
            raise ApiError(400, f"bad CreateFrame parameters: {e}")
        job = _start_job(lambda j: spmd.run("create_frame", dest=dest, spec=spec),
                         "CreateFrame")
        try:
            _join_for_handler(job)
        except RuntimeError as e:
            raise ApiError(400, str(e))
        fr = DKV.get(dest)
        return {"__meta": {"schema_type": "CreateFrame"},
                "job": _job_schema(job),
                "destination_frame": {"name": dest},
                "rows": fr.nrow, "cols": len(fr.names)}

    def interaction(self, params):
        """``POST /3/Interaction`` [UNVERIFIED upstream
        water/api/InteractionHandler]: factor-interaction columns."""
        from h2o3_tpu.cluster import spmd

        frame_key = self._resolve_frame_key(params, "source_frame", "frame_id")
        try:
            factors = params.get("factor_columns") or params.get("factors")
            if isinstance(factors, str):
                factors = (json.loads(factors) if factors.startswith("[")
                           else [factors])
        except ValueError as e:
            raise ApiError(400, f"bad factor_columns: {e}")
        if not factors or len(factors) < 2:
            raise ApiError(400, "factor_columns needs at least two columns")
        dest = self._resolve_dest(params, "interaction")
        try:
            pairwise = str(params.get("pairwise", "false")).lower() in ("1", "true")
            max_factors = int(params.get("max_factors", 100))
            min_occurrence = int(params.get("min_occurrence", 1))
        except (ValueError, TypeError) as e:
            raise ApiError(400, f"bad Interaction parameters: {e}")
        job = _start_job(
            lambda j: spmd.run(
                "interaction", frame_key=frame_key, dest=dest,
                factors=list(factors), pairwise=pairwise,
                max_factors=max_factors, min_occurrence=min_occurrence,
            ),
            "Interaction",
        )
        try:
            _join_for_handler(job)
        except RuntimeError as e:
            raise ApiError(400, str(e))
        fr = DKV.get(dest)
        return {"__meta": {"schema_type": "Interaction"},
                "job": _job_schema(job),
                "destination_frame": {"name": dest},
                "cols": len(fr.names)}

    # -- node persistent storage (Flow notebook save/load) -----------------
    # Successor of ``/3/NodePersistentStorage`` [UNVERIFIED upstream path
    # water/api/NodePersistentStorageHandler.java, SURVEY.md §2.3]: Flow
    # stores saved notebooks as named string blobs under a category.

    @staticmethod
    def _nps_path(category: str, name: str | None = None):
        import os

        from h2o3_tpu import config

        safe = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._ -]{0,120}$")
        for part in (category,) + ((name,) if name is not None else ()):
            if not safe.match(part or ""):
                raise ApiError(400, f"invalid storage name {part!r}")
        root = config.get("H2O3_TPU_NPS_DIR") or os.path.join(
            os.path.expanduser("~"), ".h2o3tpu", "nps"
        )
        p = os.path.join(root, category)
        return os.path.join(p, name) if name is not None else p

    def nps_configured(self, params):
        return {"__meta": {"schema_type": "NodePersistentStorage"},
                "configured": True}

    def nps_list(self, params, category):
        import os

        d = self._nps_path(category)
        entries = []
        if os.path.isdir(d):
            for n in sorted(os.listdir(d)):
                if n.endswith(".tmp"):  # interrupted atomic-write leftover
                    continue
                st = os.stat(os.path.join(d, n))
                entries.append({"category": category, "name": n,
                                "size": st.st_size,
                                "timestamp_millis": int(st.st_mtime * 1000)})
        return {"__meta": {"schema_type": "NodePersistentStorage"},
                "category": category, "entries": entries}

    def nps_get(self, params, category, name):
        import os

        p = self._nps_path(category, name)
        if not os.path.isfile(p):
            raise ApiError(404, f"no saved {category}/{name}")
        with open(p, encoding="utf-8") as f:
            return {"__meta": {"schema_type": "NodePersistentStorage"},
                    "category": category, "name": name, "value": f.read()}

    def nps_put(self, params, category, name):
        import os

        value = params.get("value")
        if value is None:
            raise ApiError(400, "value is required")
        p = self._nps_path(category, name)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(value))
        os.replace(tmp, p)
        return {"__meta": {"schema_type": "NodePersistentStorage"},
                "category": category, "name": name}

    def nps_delete(self, params, category, name):
        import os

        p = self._nps_path(category, name)
        if os.path.isfile(p):
            os.remove(p)
        return {"__meta": {"schema_type": "NodePersistentStorage"},
                "category": category, "name": name}

    # -- rapids (frame expression eval) -----------------------------------
    def rapids(self, params):
        from h2o3_tpu.api.rapids import RapidsError
        from h2o3_tpu.cluster import spmd

        ast = params.get("ast")
        if not ast:
            raise ApiError(400, "ast is required")
        try:
            result = spmd.run("rapids", ast=ast, session=params.get("session_id"))
        except RapidsError as e:
            raise ApiError(400, str(e))
        return {"__meta": {"schema_type": "Rapids"}, **result}

    # -- shutdown / drain (water.api.ShutdownHandler successor) -------------
    def shutdown(self, params):
        """``POST /3/Shutdown?drain=true`` — stop the coordinator. With
        ``drain``: stop admitting mutating work immediately, wait (bounded
        by H2O3_TPU_DRAIN_TIMEOUT_SECS) for running jobs to truncate and
        flush their latest checkpoints, shut down followers, then close the
        listener. Without: close immediately (the old hard stop). The k8s
        ``preStop`` hook POSTs this route so a pod rotation drains instead
        of killing in-flight training (deploy/k8s.yaml)."""
        drain = str(params.get("drain", "")).lower() in ("1", "true")
        srv = _SERVER
        if srv is None:
            raise ApiError(503, "no process-wide server to shut down "
                                "(was it started via start_server?)")
        if drain:
            srv.begin_drain()  # synchronous: admission closes NOW
        threading.Thread(
            target=srv.stop, kwargs={"drain": drain},
            name="h2o3-shutdown", daemon=True,
        ).start()
        return {"__meta": {"schema_type": "Shutdown"}, "drain": drain,
                "draining": _DRAINING}

    def recover(self, params):
        """``POST /3/Recover`` — the supervised reform, over the wire: when
        the degraded latch is set, re-form the cloud (degraded → recovering
        → healthy; ``cloud_generation`` ticks, fencing every pre-reform
        command out) and report the new state. Idempotent: a healthy cloud
        just reports its current generation. 409 when recovery is disabled
        (``H2O3_TPU_RECOVERY=0`` keeps the latch strictly one-way over REST
        too — ``clear_degraded`` stays a code-level operator hatch)."""
        from h2o3_tpu.cluster import cloud, recovery

        was = cloud.degraded_reason()
        if was is not None:
            if not recovery.enabled():
                raise ApiError(
                    409, "supervised recovery is disabled "
                         "(H2O3_TPU_RECOVERY=0): the degraded latch is "
                         "one-way — restart the cloud and recover models "
                         "from checkpoints")
            recovery.reform(f"REST /3/Recover (was: {was})")
        return {"__meta": {"schema_type": "Recover"},
                "recovered": was is not None,
                **({"was_degraded": was} if was else {}),
                "generation": cloud.generation(),
                "cloud_healthy": cloud.degraded_reason() is None}


def _get_model(key):
    from h2o3_tpu.models.model_base import Model

    m = DKV.get(key)
    if not isinstance(m, Model):
        raise ApiError(404, f"Model {key} not found")
    return m


def _job_schema(j: Job) -> dict:
    from h2o3_tpu.utils import jobacct as _jobacct

    span_summary = _metrics.trace_summary(j.key)
    ledger = _jobacct.snapshot(j.key)
    return {
        "key": {"name": j.key},
        "description": j.description,
        "status": j.status,
        "progress": j.progress,
        "exception": j.exception,
        # wall-clock reporting: started_at is epoch seconds; duration_ms is
        # live while RUNNING and frozen at end_time once terminal (stable
        # across polls); span_summary rolls the job's trace up per phase
        "started_at": j.start_time,
        "duration_ms": j.duration_ms,
        # the job's deadline (epoch secs): enforced between iterations via
        # the soft-deadline plumbing (builders truncate gracefully) — the
        # client reads it to budget its own polling
        **({"deadline": j.soft_deadline} if j.soft_deadline else {}),
        **({"span_summary": span_summary} if span_summary else {}),
        # the per-job resource ledger (utils/jobacct.py): device-seconds,
        # dispatch counts by site, collective bytes by lane, window bytes
        # and queue waits attributed to THIS job's trace — the budget
        # signal a fleet scheduler reads off /3/Jobs
        **({"ledger": ledger} if ledger else {}),
        "dest": {"name": getattr(getattr(j, "result", None), "key", "")} if j.result is not None else None,
        # crash-recovery pointer (latest interval checkpoint) — present when
        # the build ran with export_checkpoints_dir, so a FAILED job tells
        # the operator exactly what to resume from (docs/RECOVERY.md)
        **({"recovery": j.recovery} if getattr(j, "recovery", None) else {}),
        # supervised-recovery restarts this job survived (reform + resume
        # from its latest snapshot, cluster/recovery.py)
        **({"restarts": j.restarts} if getattr(j, "restarts", 0) else {}),
    }


def _coerce_param(params_cls, name: str, v):
    """Coerce wire strings to the dataclass field's type (H2O's Schema
    fill-from-parms step)."""
    import dataclasses
    import typing

    if not isinstance(v, str):
        return v
    fld = {f.name: f for f in dataclasses.fields(params_cls)}[name]
    t = fld.type
    if v.startswith(("[", "{")):
        return json.loads(v)
    base = str(t)
    if "bool" in base:
        return v.lower() in ("1", "true", "yes")
    if "int" in base:
        try:
            return int(v)
        except ValueError:
            return float(v)
    if "float" in base:
        return float(v)
    return v


# ---------------------------------------------------------------------------
# the RequestServer: route table + HTTP plumbing

_EP = Endpoints()

# (method, regex) -> endpoint; group captures become positional args
_ROUTES: list[tuple[str, re.Pattern, object]] = [
    ("GET", r"", _EP.flow_page),
    ("GET", r"/flow(?:/index\.html)?", _EP.flow_page),
    ("GET", r"/3/Cloud", _EP.cloud),
    ("GET", r"/3/Ping", _EP.ping),
    ("GET", r"/3/Typeahead/files", _EP.typeahead_files),
    ("GET", r"/3/Metadata/schemas", _EP.metadata_schemas),
    ("GET", r"/3/About", _EP.about),
    ("GET", r"/3/ImportFiles", _EP.import_files),
    ("POST", r"/3/ImportFiles", _EP.import_files),
    ("POST", r"/3/ParseSetup", _EP.parse_setup),
    ("POST", r"/3/Parse", _EP.parse),
    ("GET", r"/3/Frames", _EP.frames_list),
    ("GET", r"/3/DownloadDataset", _EP.download_dataset),
    ("POST", r"/3/Frames/([^/]+)/export", _EP.frame_export),
    ("GET", r"/3/Frames/([^/]+)/summary", _EP.frame_summary),
    ("GET", r"/3/Frames/([^/]+)", _EP.frame_get),
    ("DELETE", r"/3/Frames/([^/]+)", _EP.frame_delete),
    ("GET", r"/3/Jobs", _EP.jobs_list),
    ("GET", r"/3/Jobs/([^/]+)/trace", _EP.job_trace),
    ("GET", r"/3/Jobs/([^/]+)", _EP.job_get),
    ("POST", r"/3/Jobs/([^/]+)/cancel", _EP.job_cancel),
    ("GET", r"/3/ModelBuilders", _EP.model_builders),
    ("GET", r"/3/ModelBuilders/([^/]+)", _EP.model_builder_get),
    ("POST", r"/3/ModelBuilders/([^/]+)", _EP.build_model),
    ("POST", r"/99/Grid/([^/]+)", _EP.grid_build),
    ("GET", r"/99/Grids", _EP.grids_list),
    ("GET", r"/99/Grids/([^/]+)", _EP.grid_get),
    ("GET", r"/3/Logs/nodes/([^/]+)/files/([^/]+)", _EP.logs_get),
    ("GET", r"/3/Logs", _EP.logs_tail),
    ("GET", r"/3/Metrics", _EP.metrics_get),
    ("GET", r"/3/FlightRecorder", _EP.flight_recorder),
    ("GET", r"/3/Timeline", _EP.timeline),
    ("GET", r"/3/Profiler", _EP.profiler),
    ("GET", r"/3/Models", _EP.models_list),
    ("POST", r"/99/Models\.bin/([^/]+)", _EP.model_save_bin),
    ("POST", r"/99/Models\.bin", _EP.model_load_bin),
    ("GET", r"/3/Models/([^/]+)/mojo", _EP.model_mojo),
    ("GET", r"/3/Models/([^/]+)/pojo", _EP.model_pojo),
    ("GET", r"/3/Models/([^/]+)", _EP.model_get),
    ("DELETE", r"/3/Models/([^/]+)", _EP.model_delete),
    ("GET", r"/3/ServingRegistry", _EP.serving_registry),
    ("POST", r"/3/Predictions/rows", _EP.predict_rows),
    ("POST", r"/3/Predictions/models/([^/]+)/frames/([^/]+)", _EP.predict),
    ("POST", r"/3/ModelMetrics/models/([^/]+)/frames/([^/]+)", _EP.model_metrics),
    ("POST", r"/3/ModelMetrics/predictions_frame/([^/]+)/actuals_frame/([^/]+)",
     _EP.make_metrics),
    ("POST", r"/3/PartialDependence", _EP.partial_dependence),
    ("POST", r"/99/Rapids", _EP.rapids),
    ("POST", r"/3/SplitFrame", _EP.split_frame),
    ("POST", r"/3/CreateFrame", _EP.create_frame),
    ("POST", r"/3/Interaction", _EP.interaction),
    ("GET", r"/3/NodePersistentStorage/configured", _EP.nps_configured),
    ("GET", r"/3/NodePersistentStorage/([^/]+)", _EP.nps_list),
    ("GET", r"/3/NodePersistentStorage/([^/]+)/([^/]+)", _EP.nps_get),
    ("POST", r"/3/NodePersistentStorage/([^/]+)/([^/]+)", _EP.nps_put),
    ("DELETE", r"/3/NodePersistentStorage/([^/]+)/([^/]+)", _EP.nps_delete),
    ("POST", r"/99/AutoMLBuilder", _EP.automl_build),
    ("GET", r"/99/AutoML/([^/]+)", _EP.automl_get),
    ("POST", r"/3/Shutdown", _EP.shutdown),
    ("POST", r"/3/Recover", _EP.recover),
]
# raw pattern rides along as the bounded-cardinality metrics route label
_COMPILED = [(m, p, re.compile("^" + p + "/?$"), h) for m, p, h in _ROUTES]


class _Handler(BaseHTTPRequestHandler):
    server_version = "h2o3_tpu"

    def log_message(self, fmt, *args):  # route HTTP logs into our logger
        Log.debug(f"REST {self.address_string()} {fmt % args}")

    def _params(self) -> dict:
        parsed = urllib.parse.urlparse(self.path)
        params = {k: v[0] if len(v) == 1 else v
                  for k, v in urllib.parse.parse_qs(parsed.query).items()}
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            body = self.rfile.read(length)
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                params.update(json.loads(body))
            else:  # h2o clients POST form-encoded
                params.update({k: v[0] if len(v) == 1 else v
                               for k, v in urllib.parse.parse_qs(body.decode()).items()})
        return params

    def _blocked_cross_origin(self, method: str) -> bool:
        """CSRF / DNS-rebinding guard for state-changing requests.

        The API is unauthenticated (like upstream's default), so a malicious
        page in an operator's browser could otherwise drive the coordinator:
        no-preflight form POSTs (CSRF) or a rebound DNS name (the browser
        sends the attacker's hostname in Host). Policy for non-GET requests
        that carry browser markers (Origin / Referer / Sec-Fetch-* — fetch()
        cannot strip these forbidden headers, and rebound-page requests
        always carry them):
        - Host must be an IP literal, localhost, this machine's hostname, or
          listed in H2O3_TPU_ALLOWED_HOSTS ("*" disables the guard);
        - a present Origin header must match the Host (same-origin).
        Requests WITHOUT browser markers (python/R/curl clients — including
        ones reaching the coordinator via a DNS name) pass untouched; a
        browser-based Flow session behind a DNS name needs the hostname in
        H2O3_TPU_ALLOWED_HOSTS.
        """
        if method == "GET":
            return False
        browserish = any(
            self.headers.get(h)
            for h in ("Origin", "Referer", "Sec-Fetch-Site", "Sec-Fetch-Mode")
        )
        if not browserish:
            return False
        from h2o3_tpu import config

        allowed = config.get("H2O3_TPU_ALLOWED_HOSTS")
        if allowed.strip() == "*":
            return False
        host_hdr = (self.headers.get("Host") or "").strip()
        hostname = urllib.parse.urlsplit(f"//{host_hdr}").hostname or ""
        ok_host = False
        if hostname:
            import ipaddress
            import socket

            try:
                ipaddress.ip_address(hostname)
                ok_host = True
            except ValueError:
                extra = {h.strip().lower() for h in allowed.split(",") if h.strip()}
                ok_host = hostname.lower() in (
                    {"localhost", socket.gethostname().lower()} | extra
                )
        origin = (self.headers.get("Origin") or "").strip()
        ok_origin = True
        if origin and origin.lower() != "null":
            ok_origin = urllib.parse.urlsplit(origin).netloc.lower() == host_hdr.lower()
        elif origin:  # Origin: null (sandboxed iframe / file://) — untrusted
            ok_origin = False
        if ok_host and ok_origin:
            return False
        self._reply(403, {
            "__meta": {"schema_type": "Error"},
            "msg": (
                f"cross-origin request rejected (Host={host_hdr!r}, "
                f"Origin={origin!r}); set H2O3_TPU_ALLOWED_HOSTS to allow"
            ),
            "http_status": 403,
        })
        return True

    def _auth_rejected(self) -> bool:
        """Opt-in shared-token auth — the ``-hash_login`` analog (SURVEY
        §5.6 upstream auth flags). Off unless H2O3_TPU_AUTH_TOKEN is set;
        when on, every route requires ``Authorization: Bearer <token>`` or
        HTTP Basic with the token as password (any username — matching the
        one-credential spirit of a hash_login file with a single entry).
        Comparisons are constant-time."""
        from h2o3_tpu import config

        token = config.get("H2O3_TPU_AUTH_TOKEN")
        if not token:
            return False
        import base64
        import hmac

        hdr = (self.headers.get("Authorization") or "").strip()
        ok = False
        if hdr.startswith("Bearer "):
            try:
                # bytes on both sides: compare_digest raises TypeError on
                # non-ASCII str (http.server decodes headers as latin-1),
                # and this guard runs OUTSIDE the route try/except
                ok = hmac.compare_digest(
                    hdr[7:].strip().encode("utf-8", "surrogateescape"),
                    token.encode(),
                )
            except Exception:  # noqa: BLE001 — malformed header == no auth
                ok = False
        elif hdr.startswith("Basic "):
            try:
                userpass = base64.b64decode(hdr[6:].strip()).decode()
                pw = userpass.split(":", 1)[1] if ":" in userpass else ""
                ok = hmac.compare_digest(pw, token)
            except Exception:  # noqa: BLE001 — malformed header == no auth
                ok = False
        if ok:
            return False
        self._reply(
            401,
            {
                "__meta": {"schema_type": "Error"},
                "msg": "authentication required (H2O3_TPU_AUTH_TOKEN is set; "
                       "send Authorization: Bearer <token> or Basic with the "
                       "token as password)",
                "http_status": 401,
            },
            extra_headers={"WWW-Authenticate": 'Basic realm="h2o3_tpu"'},
        )
        return True

    def _dispatch(self, method: str):
        if self._auth_rejected():
            return
        if self._blocked_cross_origin(method):
            return
        path = urllib.parse.urlparse(self.path).path
        if method == "POST" and path.rstrip("/") == "/3/PostFile":
            # raw-body file upload (h2o.upload_file to a remote coordinator)
            gate = False
            try:
                gate = _admission_enter(method, "/3/PostFile")
                self._post_file()
            except ApiError as e:
                self._reply(e.status, {"__meta": {"schema_type": "Error"},
                                       "msg": str(e), "http_status": e.status},
                            extra_headers=e.headers)
            except Exception as e:  # noqa: BLE001 — REST boundary
                self._reply(500, {"__meta": {"schema_type": "Error"},
                                  "msg": repr(e), "http_status": 500})
            finally:
                if gate:
                    _admission_exit()
            return
        for m, route, pat, handler in _COMPILED:
            if m != method:
                continue
            match = pat.match(path)
            if match:
                status = 200
                _REST_IN_FLIGHT.inc()
                t0 = time.perf_counter()
                gate = False
                idem = (self.headers.get("Idempotency-Key")
                        if method == "POST" else None)
                idem_owned = False
                try:
                    if idem:
                        hit = _idem_begin(idem)
                        if hit is _IDEM_PENDING:
                            raise ApiError(
                                409, "a request with this Idempotency-Key "
                                     "is still in flight; retry shortly",
                                headers={"Retry-After": "1"})
                        if hit is not None:
                            status, payload = hit
                            _IDEM_REPLAYS.inc(route=route or "/")
                            self._reply(status, payload, extra_headers={
                                "Idempotency-Replayed": "true"})
                            return
                        idem_owned = True
                    gate = _admission_enter(method, route)
                    from h2o3_tpu.utils import faults

                    faults.slow_check("rest")  # chaos: slow-handler injection
                    params = self._params()
                    args = [urllib.parse.unquote(g) for g in match.groups()]
                    # every request runs under its own trace id (client-
                    # supplied X-Request-Id wins, for cross-system
                    # correlation): ring events and ledger entries produced
                    # by the handler — a scorer dispatch, a batcher queue
                    # wait — attribute to THIS request, and the id is echoed
                    # back as X-H2O3-Trace so the caller can pull its span
                    # tree from /3/FlightRecorder?format=trace. Jobs
                    # launched by the handler shadow it with their own
                    # job-key trace (metrics.trace kind rules).
                    rid = (self.headers.get("X-Request-Id")
                           or f"rest-{next(_REQ_IDS)}")[:120]
                    self._trace_id = rid
                    with _metrics.trace(rid, kind="request"), _metrics.span(
                        "rest.request", route=route or "/", method=method
                    ):
                        out = handler(params, *args)
                    # the idempotency outcome publishes BEFORE the response
                    # bytes leave: the moment the client sees the reply it
                    # may retry with the same key, and a retry racing a
                    # post-reply release/cache would 409 (observed: a shed
                    # 503's key still _IDEM_PENDING when the retry landed)
                    if isinstance(out, dict) and "__binary__" in out:
                        if idem_owned:  # binary bodies are not replayable
                            _idem_finish(idem, 200, None)
                            idem_owned = False
                        self._reply_binary(out)
                    else:
                        if idem_owned:
                            _idem_finish(idem, 200, out)
                            idem_owned = False
                        self._reply(200, out)
                except ApiError as e:
                    status = e.status
                    body = {"__meta": {"schema_type": "Error"},
                            "error_url": path, "msg": str(e),
                            "http_status": e.status,
                            **({"reason": e.reason} if e.reason else {})}
                    if idem_owned:
                        # deterministic 4xx outcomes get cached for replay;
                        # 5xx and transient shed statuses (429/503) release
                        # the key so a retry re-attempts (_idem_finish) —
                        # published before the reply, see above
                        _idem_finish(idem, e.status, body)
                        idem_owned = False
                    self._reply(e.status, body, extra_headers=e.headers)
                except Exception as e:  # noqa: BLE001 — REST boundary
                    status = 500
                    Log.err(f"REST {method} {path} failed: {e!r}")
                    if idem_owned:  # release before the reply (retry race)
                        _idem_finish(idem, 500, None)
                        idem_owned = False
                    self._reply(500, {"__meta": {"schema_type": "Error"},
                                      "error_url": path, "msg": repr(e),
                                      "http_status": 500})
                finally:
                    if idem_owned:  # still claimed: release, never wedge the key
                        _idem_finish(idem, 500, None)
                    if gate:
                        _admission_exit()
                    _REST_IN_FLIGHT.dec()
                    _REST_REQUESTS.inc(
                        method=method, route=route or "/", status=str(status))
                    _REST_SECONDS.observe(
                        time.perf_counter() - t0,
                        method=method, route=route or "/")
                return
        _REST_REQUESTS.inc(method=method, route="<no route>", status="404")
        self._reply(404, {"__meta": {"schema_type": "Error"},
                          "msg": f"no route {method} {path}", "http_status": 404})

    def _reply(self, status: int, payload: dict, extra_headers: dict | None = None):
        data = json.dumps(payload, default=_json_default).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if getattr(self, "_trace_id", None):
            self.send_header("X-H2O3-Trace", self._trace_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _post_file(self):
        import tempfile

        parsed = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        suffix = q.get("filename", "upload.csv")
        suffix = "." + suffix.rsplit(".", 1)[-1] if "." in suffix else ".csv"
        with tempfile.NamedTemporaryFile(suffix=suffix, delete=False) as f:
            f.write(body)
            path = f.name
        import os as _os

        from h2o3_tpu.frame.parse import import_file

        try:
            fr = import_file(path, destination_frame=q.get("destination_frame"))
        finally:
            # a failing parse must not leak the staged upload into /tmp
            _os.unlink(path)
        self._reply(200, {"__meta": {"schema_type": "PostFile"},
                          "destination_frame": fr.key,
                          "total_bytes": length})

    def _reply_binary(self, out: dict):
        data = out["__binary__"]
        self.send_response(200)
        self.send_header("Content-Type", out.get("content_type", "application/octet-stream"))
        if out.get("filename"):
            self.send_header(
                "Content-Disposition", f'attachment; filename="{out["filename"]}"'
            )
        self.send_header("Content-Length", str(len(data)))
        if getattr(self, "_trace_id", None):
            self.send_header("X-H2O3-Trace", self._trace_id)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")


class H2OServer:
    """The RequestServer successor: owns the HTTP listener thread."""

    def __init__(self, ip: str = "127.0.0.1", port: int = 54321):
        from h2o3_tpu import config

        # per-connection read deadline: a client that stops sending
        # mid-request cannot pin a handler thread forever (class-level on
        # purpose — one process, one handler class, one policy)
        read_timeout = config.get_float("H2O3_TPU_REQUEST_READ_TIMEOUT")
        _Handler.timeout = read_timeout if read_timeout > 0 else None
        self.httpd = ThreadingHTTPServer((ip, port), _Handler)
        self.ip, self.port = self.httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.ip}:{self.port}"

    def start(self) -> "H2OServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="h2o3-rest", daemon=True
        )
        self._thread.start()
        Log.info(f"REST server up at {self.url}")
        return self

    def begin_drain(self) -> None:
        """Flip the process into draining: mutating requests and new jobs
        are shed with 503 + Retry-After from this instant; GETs (job polls,
        health, metrics) keep serving so the drain stays observable."""
        global _DRAINING
        if not _DRAINING:
            _DRAINING = True
            _G_DRAINING.set(1)
            Log.info("REST drain: no longer admitting mutating requests")

    def _drain(self, timeout: float | None) -> None:
        from h2o3_tpu import config

        t0 = time.monotonic()
        self.begin_drain()
        budget = (config.get_float("H2O3_TPU_DRAIN_TIMEOUT_SECS")
                  if timeout is None else timeout)
        deadline = t0 + max(budget, 0.0)
        with _JOBS_LOCK:
            jobs = [j for j in _REST_JOBS
                    if j.status in (Job.PENDING, Job.RUNNING)]
        now = time.time()
        for j in jobs:
            # truncate gracefully at the next iteration boundary: builders
            # polling stop_requested finish the current interval, keep the
            # partial model, and (with export_checkpoints_dir) flush it
            # through the snapshot path — the resumable-checkpoint contract
            j.soft_deadline = (now if j.soft_deadline is None
                               else min(j.soft_deadline, now))
        flushed = abandoned = 0
        for j in jobs:
            left = deadline - time.monotonic()
            if j.wait(max(left, 0.0)):
                flushed += 1
            else:
                abandoned += 1
        took = time.monotonic() - t0
        _DRAIN_SECONDS.set(took)
        Log.info(
            f"REST drain finished in {took:.2f}s: {flushed} job(s) flushed, "
            f"{abandoned} still running at the {budget}s deadline"
        )

    def stop(self, drain: bool = False, timeout: float | None = None) -> None:
        """Stop the listener. ``drain=True`` first stops admitting work,
        waits (bounded by ``timeout`` / H2O3_TPU_DRAIN_TIMEOUT_SECS) for
        running jobs to truncate and flush their latest checkpoints, and
        shuts down the follower ranks — the graceful path the k8s preStop
        hook drives. ``drain=False`` is the old hard stop."""
        global _DRAINING, _SERVER
        if drain:
            self._drain(timeout)
            from h2o3_tpu.cluster import spmd

            try:
                spmd.shutdown_followers()
            except Exception as e:  # noqa: BLE001 — closing anyway
                Log.warn(f"drain: follower shutdown failed: {e!r}")
        self.httpd.shutdown()
        self.httpd.server_close()
        # join the serving thread (bounded) so callers — tests binding the
        # same port next — never race a half-dead listener
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            if self._thread.is_alive():
                Log.warn("REST serving thread still alive after 10s join")
            self._thread = None
        _DRAINING = False  # a later server in this process starts clean
        _G_DRAINING.set(0)
        # a stopped server must not keep serving as the process singleton
        if _SERVER is self:
            _SERVER = None


_SERVER: H2OServer | None = None


def start_server(ip: str = "127.0.0.1", port: int | None = None) -> H2OServer:
    """Start (or return) the process-wide REST server. port=0 picks a free
    port — handy for tests running in parallel. Default port comes from the
    H2O3_TPU_PORT knob (config.py)."""
    global _SERVER
    if _SERVER is None:
        if port is None:
            from h2o3_tpu import config

            port = config.get_int("H2O3_TPU_PORT")
        _SERVER = H2OServer(ip, port).start()
        # fleet serving: a replica with a configured watch dir starts its
        # model-store watcher with the server (no-op otherwise)
        from h2o3_tpu.serving import registry as _sreg

        _sreg.install()
        # device-memory ledger: the background poller keeps the
        # device_hbm_bytes / unattributed series fresh on an IDLE server
        # (busy processes refresh at dispatch boundaries)
        from h2o3_tpu.utils import devmem as _devmem

        _devmem.install()
        # overload plane: the dispatch hang watchdog walks the flight-
        # recorder ring for wedged dispatches (no-op per pass while
        # H2O3_TPU_OVERLOAD=0, so installing is always safe)
        from h2o3_tpu.utils import overload as _overload

        _overload.install_watchdog()
    return _SERVER
