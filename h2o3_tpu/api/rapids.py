"""Rapids expression language — successor of ``water.rapids.Rapids`` /
``Session`` / ``Env`` / the ``ast/**`` node classes [UNVERIFIED upstream
paths, SURVEY.md §2.1].

H2O clients never run frame ops locally: ``H2OFrame`` builds a lazy
expression tree that is shipped as a Lisp-ish string to ``POST /99/Rapids``
(e.g. ``(tmp= k (cols_py frame_1 'age'))``) and evaluated server-side
against DKV frames. This evaluator keeps that wire contract; every AST op
dispatches to the device-backed ops in :mod:`h2o3_tpu.frame.ops` — the AST
layer adds no compute of its own, exactly like upstream (AST nodes call
MRTasks; here they call shard_map ops).

Grammar: ``(op arg ...)``, numbers, ``'str'``/``"str"``, number lists
``[1 2 3]``, string lists ``['a' 'b']``, bare symbols = DKV keys (frames) or
special consts (TRUE/FALSE/NaN). ``(tmp= key expr)`` names a result.
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame import ops as OPS
from h2o3_tpu.frame.frame import Frame, Vec


class RapidsError(Exception):
    pass


def _require_seed_if_replicated(op: str, seed: int) -> None:
    """Random ops on a multi-process cloud need an explicit seed: each rank
    evaluates the expression itself (spmd replication), and unseeded draws
    would give every rank a DIFFERENT frame — silent cross-rank divergence."""
    from h2o3_tpu.cluster import spmd

    if seed <= 0 and spmd.multi_process():
        raise RapidsError(
            f"{op} on a multi-process cloud requires an explicit positive "
            "seed (every rank must draw identical values)"
        )


# ---------------------------------------------------------------------------
# tokenizer / parser

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<lparen>\() | (?P<rparen>\)) |
        (?P<lbrack>\[) | (?P<rbrack>\]) |
        (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*") |
        (?P<number>-?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?) |
        (?P<symbol>[^\s()\[\]]+)
    )""",
    re.VERBOSE,
)


def _tokenize(src: str):
    pos = 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if not m:
            raise RapidsError(f"bad token at {src[pos:pos + 20]!r}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group(m.lastgroup)
        yield kind, text
    yield "eof", ""


class _Sym(str):
    """A bare symbol (op name or DKV key)."""


def _parse(tokens) -> Any:
    kind, text = next(tokens)
    if kind == "lparen":
        out = []
        while True:
            item = _parse_peekable(tokens)
            if item is _RPAREN:
                return out
            out.append(item)
    if kind == "lbrack":
        out = []
        while True:
            item = _parse_peekable(tokens)
            if item is _RBRACK:
                return np.array(out, dtype=object)
            out.append(item)
    if kind == "string":
        return text[1:-1].replace("\\'", "'").replace('\\"', '"')
    if kind == "number":
        v = float(text)
        return int(v) if v.is_integer() and "e" not in text.lower() and "." not in text else v
    if kind == "symbol":
        return _Sym(text)
    raise RapidsError(f"unexpected {kind}")


_RPAREN = object()
_RBRACK = object()


def _parse_peekable(tokens):
    kind, text = next(tokens)
    if kind == "rparen":
        return _RPAREN
    if kind == "rbrack":
        return _RBRACK
    if kind == "lparen":
        out = []
        while True:
            item = _parse_peekable(tokens)
            if item is _RPAREN:
                return out
            out.append(item)
    if kind == "lbrack":
        out = []
        while True:
            item = _parse_peekable(tokens)
            if item is _RBRACK:
                return np.array(out, dtype=object)
            out.append(item)
    if kind == "string":
        return text[1:-1].replace("\\'", "'").replace('\\"', '"')
    if kind == "number":
        v = float(text)
        return int(v) if v.is_integer() and "e" not in text.lower() and "." not in text else v
    if kind == "symbol":
        return _Sym(text)
    if kind == "eof":
        raise RapidsError("unexpected end of expression")
    raise RapidsError(f"unexpected {kind}")


def parse(src: str) -> Any:
    return _parse(_tokenize(src))


# ---------------------------------------------------------------------------
# evaluation


def _as_frame(x) -> Frame:
    if isinstance(x, Frame):
        return x
    if isinstance(x, Vec):
        return Frame([x], [x.name or "C1"])
    raise RapidsError(f"expected a frame, got {type(x).__name__}")


def _as_vec(x) -> Vec:
    if isinstance(x, Vec):
        return x
    if isinstance(x, Frame):
        if x.ncol != 1:
            raise RapidsError(f"expected 1 column, frame has {x.ncol}")
        return x.vec(0)
    raise RapidsError(f"expected a column, got {type(x).__name__}")


_BINOPS = {
    "+": "__add__", "-": "__sub__", "*": "__mul__", "/": "__truediv__",
    "%": "__mod__", "^": "__pow__", "intDiv": "__floordiv__",
    "<": "__lt__", "<=": "__le__", ">": "__gt__", ">=": "__ge__",
    "==": "__eq__", "!=": "__ne__", "&": "__and__", "|": "__or__",
}
_UNOPS = {
    "abs": "abs", "exp": "exp", "log": "log", "log10": "log10",
    "sqrt": "sqrt", "floor": "floor", "ceiling": "ceil", "trunc": "trunc",
    "cos": "cos", "sin": "sin", "tan": "tan", "not": "not", "!": "not",
    "sign": "sign", "log2": "log2", "log1p": "log1p", "expm1": "expm1",
    "acos": "acos", "asin": "asin", "atan": "atan",
    "cosh": "cosh", "sinh": "sinh", "tanh": "tanh",
    "gamma": "gamma", "lgamma": "lgamma", "digamma": "digamma",
}
_AGGS = ("sum", "mean", "min", "max", "sd", "var", "median", "prod",
         "skewness", "kurtosis", "all", "any", "anyNA")


class Session:
    """Rapids session — temp-key lifetime tracking (``water.rapids.Session``)."""

    def __init__(self, session_id: str = "default"):
        self.session_id = session_id
        self.temps: set[str] = set()


_SESSIONS: dict[str, Session] = {}


def _env_lookup(sym: _Sym):
    v = DKV.get(str(sym))
    if v is not None:
        return v
    consts = {"TRUE": 1.0, "FALSE": 0.0, "NA": float("nan"), "NaN": float("nan"),
              "null": None, "()": None}
    if sym in consts:
        return consts[sym]
    raise RapidsError(f"unknown identifier {sym!r}")


def _eval(node, sess: Session):
    if isinstance(node, _Sym):
        return _env_lookup(node)
    if isinstance(node, (int, float, str)):
        return node
    if isinstance(node, np.ndarray):
        return np.array([_eval(x, sess) for x in node], dtype=object)
    if isinstance(node, list):
        if not node:
            return None
        head = node[0]
        if not isinstance(head, _Sym):
            raise RapidsError(f"operator position must be a symbol, got {head!r}")
        return _apply(str(head), node[1:], sess)
    raise RapidsError(f"cannot eval {node!r}")


def _num_list(x) -> list:
    if isinstance(x, np.ndarray):
        return [float(v) for v in x]
    return [float(x)]


def _sel_list(x):
    """Column/row selector: number, number list, string, string list."""
    if isinstance(x, np.ndarray):
        return list(x)
    return [x]


def _table_values(x):
    """Lookup-table VALUES for match/%in%: literal list, or a Frame/Vec —
    enum vecs yield their LABELS (to_numpy gives frame-local codes, which
    must never be compared against another column's values)."""
    if isinstance(x, (Frame, Vec)):
        v = _as_vec(x)
        if v.kind == "enum":
            dom = np.asarray(list(v.domain or ()), dtype=object)
            codes = v.to_numpy()
            return [dom[int(c)] if c >= 0 else None for c in codes]
        if v.kind == "string":
            return list(v._host)
        return [float(t) for t in v.to_numpy()]
    return _sel_list(x)


def _apply(op: str, raw_args: list, sess: Session):
    # special forms first (unevaluated args)
    if op in ("tmp=", "rapids_tmp="):
        key = str(raw_args[0])
        val = _eval(raw_args[1], sess)
        if isinstance(val, (Frame, Vec)):
            fr = _as_frame(val)
            DKV.remove(fr.key)  # re-home under the client-chosen key
            fr.key = key
            DKV.put(key, fr)
            sess.temps.add(key)
            return fr
        DKV.put(key, val)
        return val
    if op == "rm":
        for a in raw_args:
            DKV.remove(str(a))
        return None
    if op == "GB":
        # special form: agg names are bare symbols (mean/sum/nrow/...), not
        # identifiers — (GB frame [by...] agg col na  agg col na ...)
        fr = _as_frame(_eval(raw_args[0], sess))
        by = [fr.names[int(c)] if isinstance(c, (int, float)) else str(c)
              for c in _sel_list(raw_args[1])]
        rest = raw_args[2:]
        spec: dict[str, list[str]] = {}
        for i in range(0, len(rest), 3):
            agg = str(rest[i])
            col = rest[i + 1]
            col = fr.names[int(col)] if isinstance(col, (int, float)) else str(col)
            spec.setdefault(col, []).append({"nrow": "count"}.get(agg, agg))
        return OPS.group_by(fr, by).agg(spec)

    args = [_eval(a, sess) for a in raw_args]

    # -- arithmetic / comparison ------------------------------------------
    if op in _BINOPS:
        a, b = args
        if isinstance(a, Frame) and a.ncol == 1:
            a = a.vec(0)
        if isinstance(b, Frame) and b.ncol == 1:
            b = b.vec(0)
        if isinstance(a, Vec):
            return getattr(a, _BINOPS[op])(b)
        if isinstance(b, Vec):  # scalar OP vec
            refl = {"+": "__radd__", "-": "__rsub__", "*": "__rmul__",
                    "/": "__rtruediv__", "^": "__rpow__", "%": "__rmod__"}
            if op in refl:
                return getattr(b, refl[op])(a)
            flip = {"<": "__gt__", "<=": "__ge__", ">": "__lt__", ">=": "__le__",
                    "==": "__eq__", "!=": "__ne__", "&": "__and__", "|": "__or__"}
            return getattr(b, flip[op])(a)
        return _scalar_binop(op, a, b)
    if op in _UNOPS:
        (a,) = args
        if isinstance(a, (Frame, Vec)):
            return OPS._unop(_as_vec(a), _UNOPS[op])
        name = _UNOPS[op]
        if name in ("gamma", "lgamma", "digamma"):  # not numpy ufuncs
            import math

            if name == "digamma":
                from scipy.special import digamma

                return float(digamma(a))
            return float(getattr(math, name)(a))
        return float(getattr(np, {"not": "logical_not"}.get(name, name))(a))

    # -- aggregates --------------------------------------------------------
    if op in _AGGS:
        return _np_agg(op, _as_vec(args[0]))
    if op in ("nrow", "ncol"):
        fr = _as_frame(args[0])
        return fr.nrow if op == "nrow" else fr.ncol
    if op == "colnames":
        return np.array(_as_frame(args[0]).names, dtype=object)
    if op == "levels":
        v = _as_vec(args[0])
        return np.array(list(v.domain or ()), dtype=object)

    # -- slicing / mutation ------------------------------------------------
    if op in ("cols", "cols_py"):
        fr = _as_frame(args[0])
        return fr[_normalize_cols(fr, _sel_list(args[1]))]
    if op in ("rows",):
        fr = _as_frame(args[0])
        sel = args[1]
        if isinstance(sel, (Frame, Vec)):
            mask = _as_vec(sel).to_numpy().astype(bool)
            return fr.subset_rows(mask)
        if isinstance(sel, np.ndarray):
            idx = np.array([int(v) for v in sel])
            mask = np.zeros(fr.nrow, bool)
            mask[idx] = True
            return fr.subset_rows(mask)
        raise RapidsError("rows selector must be a mask column or index list")
    if op == ":=":  # (:= frame newval col rows)
        fr = _as_frame(args[0])
        val = args[1]
        cols = _normalize_cols(fr, _sel_list(args[2]))
        for c in cols:
            OPS._replace_vec(fr, fr.names[c] if isinstance(c, int) else c, _as_vec(val))
        return fr
    if op == "append":  # (append frame vec 'name')
        fr = _as_frame(args[0])
        fr[str(args[2])] = _as_vec(args[1])
        return fr
    if op == "cbind":
        frames = [_as_frame(a) for a in args]
        base = frames[0]
        out = Frame([base.vec(i) for i in range(base.ncol)], list(base.names))
        for f in frames[1:]:
            for n in f.names:
                # duplicate names get a suffix (upstream renames too) —
                # assignment by name would silently OVERWRITE the original
                name, k = n, 0
                while name in out.names:
                    name = f"{n}{k}"
                    k += 1
                out[name] = f.vec(n)
        return out
    if op == "rbind":
        import pandas as pd

        dfs = [_as_frame(a).to_pandas() for a in args]
        return Frame.from_pandas(pd.concat(dfs, ignore_index=True))

    # -- frame ops ---------------------------------------------------------
    if op == "merge":
        left, right = _as_frame(args[0]), _as_frame(args[1])
        all_left = bool(args[2]) if len(args) > 2 else False
        all_right = bool(args[3]) if len(args) > 3 else False
        return OPS.merge(left, right, all_x=all_left, all_y=all_right)
    if op == "sort":
        fr = _as_frame(args[0])
        cols = _normalize_cols(fr, _sel_list(args[1]))
        names = [fr.names[c] for c in cols]
        asc = [bool(b) for b in _sel_list(args[2])] if len(args) > 2 else True
        return OPS.sort(fr, names, ascending=asc)
    if op == "unique":
        return OPS.unique(_as_vec(args[0]))
    if op == "match":  # (match vec [table...] nomatch start_index)
        nomatch = float(args[2]) if len(args) > 2 and args[2] is not None else float("nan")
        start = int(args[3]) if len(args) > 3 and args[3] is not None else 1
        return OPS.match(
            _as_vec(args[0]), _table_values(args[1]), nomatch=nomatch, start_index=start
        )
    if op == "%in%":
        return OPS.is_in(_as_vec(args[0]), _table_values(args[1]))
    if op == "which":
        return OPS.which(_as_vec(args[0]))
    if op == "na.omit":
        return OPS.na_omit(_as_frame(args[0]))
    if op == "rank_within_groupby":
        # (rank_within_groupby frame [group...] [sort...] [asc...] 'name' sorted)
        fr = _as_frame(args[0])
        gcols = [fr.names[c] for c in _normalize_cols(fr, _sel_list(args[1]))]
        scols = [fr.names[c] for c in _normalize_cols(fr, _sel_list(args[2]))]
        asc = [bool(b) for b in _sel_list(args[3])] if len(args) > 3 else True
        name = str(args[4]) if len(args) > 4 else "New_Rank_column"
        ssorted = bool(args[5]) if len(args) > 5 else False
        return OPS.rank_within_group_by(
            fr, gcols, scols, ascending=asc, new_col_name=name,
            sort_cols_sorted=ssorted,
        )
    if op == "pivot":  # (pivot frame 'index' 'column' 'value')
        fr = _as_frame(args[0])
        nm = lambda c: fr.names[int(c)] if isinstance(c, (int, float)) else str(c)
        return OPS.pivot(fr, nm(args[1]), nm(args[2]), nm(args[3]))
    if op == "h2o.random_stratified_split":
        # (h2o.random_stratified_split y test_frac seed) — upstream arg order
        frac = float(args[1]) if len(args) > 1 and args[1] is not None else 0.2
        seed = int(args[2]) if len(args) > 2 and args[2] is not None else -1
        _require_seed_if_replicated("h2o.random_stratified_split", seed)
        return OPS.stratified_split(_as_vec(args[0]), test_frac=frac, seed=seed)
    if op == "table":
        v2 = _as_vec(args[1]) if len(args) > 1 and isinstance(args[1], (Frame, Vec)) else None
        return OPS.table(_as_vec(args[0]), v2)
    if op == "quantile":
        fr = _as_frame(args[0])
        probs = _num_list(args[1]) if len(args) > 1 else None
        # upstream grammar: (quantile fr probs interp weights_col?) — the
        # interpolation arg is accepted and ignored (type 7 only)
        wv = None
        if len(args) > 3 and args[3] not in (None, "", "_"):
            if not isinstance(args[3], str) or args[3] not in fr.names:
                raise RapidsError(
                    f"quantile: weights column {args[3]!r} not in frame")
            wv = fr.vec(args[3])
            if not wv.is_numeric():
                raise RapidsError(
                    f"quantile: weights column {args[3]!r} must be numeric, "
                    f"got {wv.kind}")
            keep = [n for n in fr.names if n != args[3]]
            fr = Frame([fr.vec(n) for n in keep], keep)  # weights col excluded
        kw = {"weights": wv} if wv is not None else {}
        return OPS.quantile(fr, probs, **kw) if probs else OPS.quantile(fr, **kw)
    if op == "ifelse":
        return OPS.ifelse(_as_vec(args[0]), _maybe_vec(args[1]), _maybe_vec(args[2]))
    if op == "is.na":
        return _as_vec(args[0]).isna()
    if op == "h2o.impute":
        fr = _as_frame(args[0])
        col = args[1]
        col = fr.names[int(col)] if isinstance(col, (int, float)) else str(col)
        return OPS.impute(fr, col, method=str(args[2]) if len(args) > 2 else "mean")
    if op == "h2o.runif":
        fr = _as_frame(args[0])
        seed = int(args[1]) if len(args) > 1 and args[1] is not None else -1
        _require_seed_if_replicated("h2o.runif", seed)
        rng = np.random.default_rng(seed if seed > 0 else None)
        return Vec.from_numpy(rng.random(fr.nrow), "real")
    if op in OPS._CUMOPS:  # (cumsum vec) etc.
        return OPS._cumulative(_as_vec(args[0]), op)
    if op == "difflag1":  # (difflag1 vec)
        return OPS.diff_lag1(_as_vec(args[0]))
    if op == "h2o.fillna":  # (h2o.fillna frame 'forward' axis maxlen)
        method = str(args[1]) if len(args) > 1 else "forward"
        if len(args) > 2 and int(args[2]) != 0:
            raise RapidsError("h2o.fillna: only axis=0 (within-column) is supported")
        maxlen = int(args[3]) if len(args) > 3 else 0
        fr = _as_frame(args[0])
        out = Frame()
        for name in fr.names:
            v = fr.vec(name)
            out[name] = OPS.fillna(v, method=method, maxlen=maxlen) \
                if v.is_numeric() else v
        return out
    if op == "round":  # (round vec digits) — half-to-even, like R/upstream
        v, digits = args[0], int(args[1]) if len(args) > 1 else 0
        if isinstance(v, (Frame, Vec)):
            scale = 10.0 ** digits
            return OPS._unop(_as_vec(v) * scale, "round") / scale
        return float(np.round(v, digits))
    if op in ("is.factor", "is.numeric", "is.character"):
        v = _as_vec(args[0])
        return float({"is.factor": v.is_categorical(),
                      "is.numeric": v.is_numeric(),
                      "is.character": v.kind == "string"}[op])
    if op == "relevel":  # (relevel vec 'y')
        return OPS.relevel(_as_vec(args[0]), str(args[1]))
    if op == "signif":
        return OPS.signif(_as_vec(args[0]), int(args[1]) if len(args) > 1 else 6)
    if op in ("asfactor", "as.factor"):
        return OPS.asfactor(_as_vec(args[0]))
    if op in ("asnumeric", "as.numeric"):
        return OPS.asnumeric(_as_vec(args[0]))
    if op in ("ascharacter", "as.character"):
        return OPS.ascharacter(_as_vec(args[0]))
    if op == "hist":
        return OPS.hist(_as_vec(args[0]), int(args[1]) if len(args) > 1 else 20)
    if op == "cor":
        return OPS.cor(_as_frame(args[0]))
    if op == "scale":
        return OPS.scale(_as_frame(args[0]),
                         center=bool(args[1]) if len(args) > 1 else True,
                         scale_=bool(args[2]) if len(args) > 2 else True)

    # -- string / time -----------------------------------------------------
    str_ops = {"toupper": OPS.toupper, "tolower": OPS.tolower, "trim": OPS.trim,
               "nchar": OPS.nchar, "strsplit": OPS.strsplit, "grep": OPS.grep,
               "lstrip": OPS.lstrip, "rstrip": OPS.rstrip,
               "entropy": OPS.entropy}
    if op in str_ops:
        v = _as_vec(args[0])
        return str_ops[op](v, *[str(a) for a in args[1:]]) if args[1:] else str_ops[op](v)
    if op == "countmatches":  # (countmatches vec ['pat' ...])
        pats = args[1]
        if isinstance(pats, np.ndarray):
            pats = [str(p) for p in pats.tolist()]
        return OPS.countmatches(_as_vec(args[0]), pats)
    if op in ("sub", "gsub"):
        # rapids arg order: (sub pattern replacement frame)
        pat, repl, v = str(args[0]), str(args[1]), _as_vec(args[2])
        return (OPS.sub if op == "sub" else OPS.gsub)(v, pat, repl)
    if op == "substring":
        v = _as_vec(args[0])
        return OPS.substring(v, int(args[1]), int(args[2]) if len(args) > 2 else None)
    if op == "cut":
        # (cut vec [breaks] ['labels'...]|null include_lowest right) — ASTCut
        v = _as_vec(args[0])
        breaks = [float(b) for b in np.asarray(args[1]).ravel()]
        labels = None
        if len(args) > 2 and args[2] is not None:
            labels = [str(s) for s in np.asarray(args[2]).ravel()]
        inc_low = bool(args[3]) if len(args) > 3 else False
        right = bool(args[4]) if len(args) > 4 else True
        return OPS.cut(v, breaks, labels=labels, include_lowest=inc_low,
                       right=right)
    time_ops = {"year": OPS.year, "month": OPS.month, "day": OPS.day,
                "hour": OPS.hour, "minute": OPS.minute, "second": OPS.second,
                "dayOfWeek": OPS.day_of_week, "week": OPS.week}
    if op in time_ops:
        return time_ops[op](_as_vec(args[0]))

    raise RapidsError(f"unknown rapids op {op!r}")


def _maybe_vec(x):
    return _as_vec(x) if isinstance(x, Frame) else x


def _np_agg(op: str, v: Vec) -> float:
    if op == "anyNA":  # every column kind; rides the cached device rollup
        return float(v.na_count() > 0)
    x = v.to_numpy().astype(np.float64)
    x = x[~np.isnan(x)]
    if len(x) == 0 and op in ("all", "any"):
        return float(op == "all")  # vacuous truth, like Python all([])/any([])

    def _skew(a):
        m, s = a.mean(), a.std(ddof=0)
        return ((a - m) ** 3).mean() / s**3 if s else float("nan")

    def _kurt(a):
        m, s = a.mean(), a.std(ddof=0)
        return ((a - m) ** 4).mean() / s**4 if s else float("nan")

    fn = {"sum": np.sum, "mean": np.mean, "min": np.min, "max": np.max,
          "sd": lambda a: np.std(a, ddof=1), "var": lambda a: np.var(a, ddof=1),
          "median": np.median, "prod": np.prod,
          "skewness": _skew, "kurtosis": _kurt,
          "all": lambda a: float((a != 0).all()),
          "any": lambda a: float((a != 0).any())}[op]
    return float(fn(x)) if len(x) else float("nan")


def _scalar_binop(op: str, a, b):
    import operator

    fn = {"+": operator.add, "-": operator.sub, "*": operator.mul,
          "/": operator.truediv, "%": operator.mod, "^": operator.pow,
          "intDiv": operator.floordiv,
          "<": operator.lt, "<=": operator.le, ">": operator.gt,
          ">=": operator.ge, "==": operator.eq, "!=": operator.ne,
          "&": lambda x, y: bool(x) and bool(y),
          "|": lambda x, y: bool(x) or bool(y)}[op]
    out = fn(a, b)
    return float(out) if isinstance(out, bool) else out


def _normalize_cols(fr: Frame, sel: list) -> list[int]:
    out = []
    for s in sel:
        if isinstance(s, (int, float)):
            out.append(int(s))
        else:
            if str(s) not in fr.names:
                raise RapidsError(f"no column {s!r}")
            out.append(fr.names.index(str(s)))
    return out


# ---------------------------------------------------------------------------
# public entry (the /99/Rapids handler body)


def rapids_eval(ast: str, session: str | None = None) -> dict:
    """Evaluate a Rapids string; returns the wire-shaped result dict.

    Elementwise/ifelse steps inside the AST walk come back DEFERRED
    (frame/lazy.py LazyExprVec, ``H2O3_TPU_MUNGE_FUSE``): a whole chain
    materializes as one fused program at first data access instead of one
    eager kernel per node. The response carries the plane's dispatch
    deltas (``munge_dispatches``) so clients — and the A/B harness — can
    see what an AST actually cost in device programs.
    """
    from h2o3_tpu.utils import metrics as _mx

    sess = _SESSIONS.setdefault(session or "default", Session(session or "default"))
    _disp_ops = ("elementwise", "expr_fuse", "expr_stream", "groupby",
                 "groupby_stream", "join", "join_exchange", "sort")
    d0 = {o: _mx.counter_value("munge_dispatches_total", op=o)
          for o in _disp_ops}
    result = _eval(parse(ast), sess)

    def _munge_disp() -> dict:
        d = {o: _mx.counter_value("munge_dispatches_total", op=o) - d0[o]
             for o in _disp_ops}
        return {o: int(v) for o, v in d.items() if v}
    if isinstance(result, (Frame, Vec)):
        fr = _as_frame(result)
        key = getattr(fr, "key", None) or DKV.make_key("rapids")
        fr.key = key
        DKV.put(key, fr)  # results are always client-fetchable by key
        return {"key": {"name": key}, "num_rows": fr.nrow,
                "num_cols": fr.ncol, "munge_dispatches": _munge_disp()}
    if result is None:
        return {"string": ""}
    if isinstance(result, str):
        return {"string": result}
    if isinstance(result, np.ndarray):
        return {"string": str(result.tolist())}
    return {"scalar": float(result), "munge_dispatches": _munge_disp()}
