"""Flow UI successor — the notebook-style web console upstream ships as
``h2o-web``/Flow [UNVERIFIED upstream paths, SURVEY.md §2.3].

One self-contained page (no build step, no external assets — the coordinator
may be air-gapped) served at ``/`` and ``/flow``: a notebook of ordered
runnable cells (markdown / Rapids / model-build / raw REST — the Flow-cell
model, with save/load through ``/3/NodePersistentStorage/notebook/*`` like
upstream), plus browse tabs for frames / models / jobs, import + parse,
schema-generated build forms ("assists"), AutoML, and a Rapids console —
every action a plain ``fetch`` against the public REST routes, so the page
doubles as live API documentation.
"""

FLOW_HTML = r"""<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>h2o3-tpu Flow</title>
<style>
  :root { --bg:#101418; --panel:#1a2026; --edge:#2c353d; --fg:#dfe7ee;
          --dim:#8b98a5; --acc:#ffd54a; --good:#7bd88f; --bad:#ff6e6e; }
  * { box-sizing:border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 -apple-system, "Segoe UI", Roboto, sans-serif; }
  header { display:flex; align-items:center; gap:14px; padding:10px 18px;
           background:var(--panel); border-bottom:1px solid var(--edge); }
  header h1 { font-size:16px; margin:0; color:var(--acc); }
  header .cloud { color:var(--dim); font-size:12px; }
  nav { display:flex; gap:4px; padding:8px 14px 0; }
  nav button { background:none; border:1px solid var(--edge);
               border-bottom:none; border-radius:6px 6px 0 0; color:var(--dim);
               padding:6px 14px; cursor:pointer; font-size:13px; }
  nav button.on { color:var(--fg); background:var(--panel); }
  main { padding:14px 18px; }
  section { display:none; } section.on { display:block; }
  table { border-collapse:collapse; width:100%; margin:8px 0; }
  th, td { text-align:left; padding:5px 10px; border-bottom:1px solid var(--edge);
           font-size:13px; }
  th { color:var(--dim); font-weight:600; }
  tr:hover td { background:#20272e; }
  .panel { background:var(--panel); border:1px solid var(--edge);
           border-radius:8px; padding:12px 14px; margin-bottom:12px; }
  input, select, textarea { background:#0d1114; color:var(--fg);
      border:1px solid var(--edge); border-radius:5px; padding:6px 8px;
      font-size:13px; }
  textarea { width:100%; font-family:ui-monospace, monospace; }
  button.act { background:var(--acc); color:#101418; border:none;
      border-radius:5px; padding:6px 14px; cursor:pointer; font-weight:600; }
  .muted { color:var(--dim); } .ok { color:var(--good); } .err { color:var(--bad); }
  pre { background:#0d1114; border:1px solid var(--edge); border-radius:6px;
        padding:10px; overflow:auto; font-size:12px; }
  .row { display:flex; gap:10px; flex-wrap:wrap; align-items:center; }
  progress { accent-color: var(--acc); }
</style>
</head>
<body>
<header>
  <h1>h2o3-tpu Flow</h1>
  <span class="cloud" id="cloud">connecting…</span>
</header>
<nav id="tabs"></nav>
<main id="main"></main>
<script>
const $$ = (h) => { const d = document.createElement('div'); d.innerHTML = h; return d.firstElementChild; };
const api = async (method, path, body) => {
  const opt = { method, headers: {} };
  if (body) { opt.body = JSON.stringify(body); opt.headers['Content-Type'] = 'application/json'; }
  const r = await fetch(path, opt);
  const j = await r.json();
  if (!r.ok) throw new Error(j.msg || r.statusText);
  return j;
};
const fmt = (v) => typeof v === 'number' ? (Number.isInteger(v) ? v : v.toPrecision(5)) : v;
// Server-controlled strings (keys, algo names, errors) are NOT trusted HTML:
// esc() for interpolation into markup/attributes, setMsg() for status lines.
const esc = (v) => String(v ?? '').replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const setMsg = (el, cls, text) => {
  const sp = document.createElement('span');
  sp.className = cls; sp.textContent = String(text);
  el.replaceChildren(sp);
};

const TABS = ['Notebook', 'Frames', 'Models', 'Jobs', 'Build', 'AutoML', 'Rapids'];
const tabs = document.getElementById('tabs'), main = document.getElementById('main');
const sections = {};
for (const t of TABS) {
  const b = $$(`<button>${t}</button>`);
  b.onclick = () => show(t);
  tabs.appendChild(b);
  sections[t] = $$('<section></section>');
  main.appendChild(sections[t]);
}
function show(t) {
  [...tabs.children].forEach((b, i) => b.classList.toggle('on', TABS[i] === t));
  for (const k of TABS) sections[k].classList.toggle('on', k === t);
  render[t]();
}

// ---- Notebook: ordered runnable cells (the Flow-notebook successor) ----
// Cell types: md (markdown-lite), rapids (/99/Rapids ast), build (JSON with
// "algo" -> /3/ModelBuilders/{algo}, waits for the job), rest (one
// "METHOD /path {json}" line). Flows save/load through the
// /3/NodePersistentStorage/notebook/{name} routes, like upstream Flow.
let cells = [{ type: 'md', text: '# Untitled Flow\nAdd cells, run them in order.' }];

const mdRender = (t) => esc(t)
  .replace(/^### (.*)$/gm, '<b style="font-size:14px">$1</b>')
  .replace(/^## (.*)$/gm, '<b style="font-size:15px">$1</b>')
  .replace(/^# (.*)$/gm, '<b style="font-size:17px;color:var(--acc)">$1</b>')
  .replace(/\*\*([^*]+)\*\*/g, '<b>$1</b>')
  .replace(/`([^`]+)`/g, '<code>$1</code>')
  .replace(/\n/g, '<br>');

const waitJob = async (key) => {
  for (;;) {
    const j = await api('GET', `/3/Jobs/${encodeURIComponent(key)}`);
    const jj = j.jobs ? j.jobs[0] : j;
    if (jj.status === 'DONE') return jj;
    if (jj.status === 'FAILED' || jj.status === 'CANCELLED')
      throw new Error(`job ${jj.status}: ${jj.exception || ''}`);
    await new Promise(r => setTimeout(r, 800));  // PENDING/RUNNING: keep polling
  }
};

async function runCell(i) {
  const c = cells[i];
  if (c.type === 'md') { c.out = null; drawCells(); return; }
  c.out = 'running…'; drawCells();
  try {
    let out;
    if (c.type === 'rapids') {
      out = await api('POST', '/99/Rapids', { ast: c.text });
    } else if (c.type === 'build') {
      const body = JSON.parse(c.text);
      const algo = body.algo; delete body.algo;
      const j = await api('POST', `/3/ModelBuilders/${encodeURIComponent(algo)}`, body);
      const done = await waitJob(j.job.key.name || j.job.key);
      out = done.dest ? await api('GET',
        `/3/Models/${encodeURIComponent(done.dest.name)}`) : done;
    } else {  // rest
      const m = c.text.trim().match(/^(GET|POST|DELETE)\s+(\S+)\s*([\s\S]*)$/);
      if (!m) throw new Error('cell format: METHOD /path {json?}');
      out = await api(m[1], m[2], m[3].trim() ? JSON.parse(m[3]) : undefined);
    }
    c.out = JSON.stringify(out, null, 2);
    if (c.out.length > 20000) c.out = c.out.slice(0, 20000) + '\n… (truncated)';
  } catch (e) { c.out = 'ERROR: ' + e; c.failed = true; }
  drawCells();
}

window.nbRunAll = async () => {
  for (let i = 0; i < cells.length; i++) {
    cells[i].failed = false;
    await runCell(i);
    if (cells[i].failed) break;  // sequential semantics: stop at first error
  }
};
window.nbAdd = (i, type) => { cells.splice(i + 1, 0, { type, text: '' }); drawCells(); };
window.nbDel = (i) => { cells.splice(i, 1); if (!cells.length) cells = [{ type: 'md', text: '' }]; drawCells(); };
window.nbMove = (i, d) => {
  const j = i + d;
  if (j < 0 || j >= cells.length) return;
  [cells[i], cells[j]] = [cells[j], cells[i]];
  drawCells();
};
window.nbRun = runCell;
window.nbEdit = (i, v) => { cells[i].text = v; };
window.nbType = (i, v) => { cells[i].type = v; cells[i].out = null; drawCells(); };

function drawCells() {
  const box = document.getElementById('nbcells');
  if (!box) return;
  box.replaceChildren(...cells.map((c, i) => {
    const d = document.createElement('div');
    d.className = 'panel';
    d.innerHTML = `
      <div class="row" style="margin-bottom:6px">
        <select onchange="nbType(${i}, this.value)">
          ${['md', 'rapids', 'build', 'rest'].map(t =>
            `<option ${t === c.type ? 'selected' : ''}>${t}</option>`).join('')}
        </select>
        <button class="act" onclick="nbRun(${i})">run</button>
        <button onclick="nbMove(${i},-1)">↑</button>
        <button onclick="nbMove(${i},1)">↓</button>
        <button onclick="nbAdd(${i},'rapids')">+ cell</button>
        <button onclick="nbDel(${i})">✕</button>
      </div>`;
    const ta = document.createElement('textarea');
    ta.rows = Math.max(2, Math.min(10, c.text.split('\n').length));
    ta.value = c.text;
    ta.oninput = () => nbEdit(i, ta.value);
    d.appendChild(ta);
    if (c.type === 'md' && c.text) {
      const md = document.createElement('div');
      md.innerHTML = mdRender(c.text);  // mdRender escapes first
      d.appendChild(md);
    }
    if (c.out != null) {
      const pre = document.createElement('pre');
      pre.textContent = c.out;  // never innerHTML: output echoes server strings
      d.appendChild(pre);
    }
    return d;
  }));
}

window.nbSave = async () => {
  const el = document.getElementById('nbmsg');
  const name = document.getElementById('nbname').value.trim();
  if (!name) { setMsg(el, 'err', 'name required'); return; }
  try {
    await api('POST', `/3/NodePersistentStorage/notebook/${encodeURIComponent(name)}`,
      { value: JSON.stringify(cells.map(({ type, text }) => ({ type, text }))) });
    setMsg(el, 'ok', 'saved ✓'); nbRefreshList();
  } catch (e) { setMsg(el, 'err', e); }
};
window.nbLoad = async (name) => {
  const el = document.getElementById('nbmsg');
  try {
    const j = await api('GET', `/3/NodePersistentStorage/notebook/${encodeURIComponent(name)}`);
    cells = JSON.parse(j.value);
    document.getElementById('nbname').value = name;
    setMsg(el, 'ok', `loaded ${name}`); drawCells();
  } catch (e) { setMsg(el, 'err', e); }
};
window.nbDelete = async (name) => {
  try {
    await api('DELETE', `/3/NodePersistentStorage/notebook/${encodeURIComponent(name)}`);
    nbRefreshList();
  } catch (e) {}
};
window.nbRefreshList = async () => {
  const box = document.getElementById('nblist');
  try {
    const j = await api('GET', '/3/NodePersistentStorage/notebook');
    box.replaceChildren(...(j.entries || []).map(e => {
      const sp = document.createElement('span');
      sp.className = 'row';
      const load = document.createElement('button');
      load.textContent = e.name; load.onclick = () => nbLoad(e.name);
      const del = document.createElement('button');
      del.textContent = '✕'; del.onclick = () => nbDelete(e.name);
      sp.append(load, del);
      return sp;
    }));
  } catch (e) { box.textContent = ''; }
};

const render = {
  async Notebook() {
    const s = sections.Notebook;
    if (!s.dataset.ready) {
      s.dataset.ready = 1;
      s.innerHTML = `<div class="panel row">
          <input id="nbname" placeholder="flow name">
          <button class="act" onclick="nbSave()">Save</button>
          <button class="act" onclick="nbRunAll()">Run all</button>
          <span id="nbmsg" class="muted"></span>
          <span id="nblist" class="row"></span></div>
        <div id="nbcells"></div>`;
    }
    drawCells(); nbRefreshList();
  },
  async Frames() {
    const s = sections.Frames;
    s.innerHTML = `<div class="panel"><div class="row">
        <input id="imp" size="50" placeholder="/path/to/file.csv" list="implist">
        <datalist id="implist"></datalist>
        <button class="act" onclick="importFile()">Import + parse</button>
        <span id="impmsg" class="muted"></span></div></div>
      <div id="frlist" class="muted">loading…</div>`;
    // server-side path completion (the Flow typeahead assist): debounced,
    // and stale responses (slow glob for an older prefix) are dropped
    let taTimer = null;
    s.querySelector('#imp').oninput = (ev) => {
      clearTimeout(taTimer);
      const src = ev.target.value;
      taTimer = setTimeout(async () => {
        try {
          const j = await api('GET',
            `/3/Typeahead/files?src=${encodeURIComponent(src)}`);
          if (s.querySelector('#imp').value !== src) return;  // stale
          const dl = s.querySelector('#implist');
          dl.replaceChildren(...(j.matches || []).map(m => {
            const o = document.createElement('option'); o.value = m; return o;
          }));
        } catch (e) {}
      }, 200);
    };
    try {
      const j = await api('GET', '/3/Frames');
      const rows = (j.frames || []).map(f =>
        `<tr><td>${esc(f.frame_id.name || f.frame_id)}</td><td>${esc(f.rows)}</td>
         <td>${esc(f.column_count ?? '')}</td>
         <td><button data-k="${esc(f.frame_id.name || f.frame_id)}"
              onclick="frameSummary(this.dataset.k)">summary</button></td></tr>`);
      s.querySelector('#frlist').innerHTML =
        `<table><tr><th>key</th><th>rows</th><th>cols</th><th></th></tr>${rows.join('')}</table>
         <pre id="frdetail" style="display:none"></pre>`;
    } catch (e) { setMsg(s.querySelector('#frlist'), 'err', e); }
  },
  async Models() {
    const s = sections.Models;
    s.innerHTML = `<div id="mlist" class="muted">loading…</div>`;
    try {
      const j = await api('GET', '/3/Models');
      const rows = (j.models || []).map(m =>
        `<tr><td>${esc(m.model_id.name || m.model_id)}</td><td>${esc(m.algo)}</td>
         <td><button data-k="${esc(m.model_id.name || m.model_id)}"
              onclick="modelDetail(this.dataset.k)">inspect</button>
         <a href="/3/Models/${esc(encodeURIComponent(m.model_id.name || m.model_id))}/mojo"><button>mojo</button></a></td></tr>`);
      s.querySelector('#mlist').innerHTML =
        `<table><tr><th>key</th><th>algo</th><th></th></tr>${rows.join('')}</table>
         <div class="panel row"><b>Predict:</b>
           <input id="pm" placeholder="model key"><input id="pf" placeholder="frame key">
           <button class="act" onclick="predict()">score</button>
           <span id="pmsg" class="muted"></span></div>
         <pre id="mdetail" style="display:none"></pre>`;
    } catch (e) { setMsg(s.querySelector('#mlist'), 'err', e); }
  },
  async Jobs() {
    const s = sections.Jobs;
    s.innerHTML = `<div id="jlist" class="muted">loading…</div>`;
    try {
      const j = await api('GET', '/3/Jobs');
      const rows = (j.jobs || []).map(jb =>
        `<tr><td>${esc(jb.key.name || jb.key)}</td><td>${esc(jb.description || '')}</td>
         <td>${esc(jb.status)}</td><td><progress value="${Number(jb.progress) || 0}" max="1"></progress></td></tr>`);
      s.querySelector('#jlist').innerHTML =
        `<table><tr><th>job</th><th>description</th><th>status</th><th>progress</th></tr>${rows.join('')}</table>`;
    } catch (e) { setMsg(s.querySelector('#jlist'), 'err', e); }
  },
  async Build() {
    const s = sections.Build;
    if (s.dataset.ready) return;
    s.dataset.ready = 1;
    let algos = [];
    try { algos = Object.keys((await api('GET', '/3/ModelBuilders')).model_builders); } catch (e) {}
    s.innerHTML = `<div class="panel">
      <div class="row"><b>Algorithm:</b>
        <select id="balgo" onchange="loadBuildForm()">${algos.map(a => `<option>${esc(a)}</option>`).join('')}</select>
        <b>Training frame:</b> <input id="bframe" placeholder="frame key">
        <b>Response:</b> <input id="by" size="12" placeholder="y"></div>
      <p class="muted">Parameters (schema-generated from the live
        /3/ModelBuilders/{algo} metadata — the Flow "assist" form; values
        left at their defaults are not sent):</p>
      <div id="bform" style="max-height:260px;overflow:auto"></div>
      <p class="muted">Extra parameters (JSON) — merged over the form:</p>
      <textarea id="bparams" rows="2">{}</textarea>
      <p><button class="act" onclick="buildModel()">Build</button>
      <span id="bmsg" class="muted"></span></p></div>`;
    loadBuildForm();
  },
  async AutoML() {
    const s = sections.AutoML;
    if (s.dataset.ready) return;
    s.dataset.ready = 1;
    s.innerHTML = `<div class="panel">
      <div class="row"><b>Training frame:</b> <input id="aframe">
        <b>Response:</b> <input id="ay" size="12">
        <b>max_models:</b> <input id="amax" size="5" value="8"></div>
      <p><button class="act" onclick="runAutoML()">Run AutoML</button>
      <span id="amsg" class="muted"></span></p>
      <pre id="aboard" style="display:none"></pre></div>`;
  },
  async Rapids() {
    const s = sections.Rapids;
    if (s.dataset.ready) return;
    s.dataset.ready = 1;
    s.innerHTML = `<div class="panel">
      <p class="muted">Rapids expression (the /99/Rapids wire grammar):</p>
      <div class="row"><input id="rast" size="70"
        placeholder='(tmp= new_fr (cols_py frame_key [0 1]))'>
      <button class="act" onclick="runRapids()">Eval</button></div>
      <pre id="rout" style="display:none"></pre></div>`;
  },
};

window.importFile = async () => {
  const el = document.getElementById('impmsg');
  try {
    el.textContent = 'importing…';
    const path = document.getElementById('imp').value;
    const setup = await api('POST', '/3/ParseSetup', { source_frames: [path] });
    await api('POST', '/3/Parse', setup);
    setMsg(el, 'ok', 'parsed ✓');
    render.Frames();
  } catch (e) { setMsg(el, 'err', e); }
};
window.frameSummary = async (k) => {
  const pre = document.getElementById('frdetail');
  pre.style.display = 'block';
  pre.textContent = JSON.stringify(
    await api('GET', `/3/Frames/${encodeURIComponent(k)}/summary`), null, 2);
};
window.modelDetail = async (k) => {
  const pre = document.getElementById('mdetail');
  pre.style.display = 'block';
  pre.textContent = JSON.stringify(
    await api('GET', `/3/Models/${encodeURIComponent(k)}`), null, 2);
};
window.predict = async () => {
  const el = document.getElementById('pmsg');
  try {
    const m = document.getElementById('pm').value, f = document.getElementById('pf').value;
    const j = await api('POST',
      `/3/Predictions/models/${encodeURIComponent(m)}/frames/${encodeURIComponent(f)}`, {});
    setMsg(el, 'ok', `→ ${j.predictions_frame.name || j.predictions_frame}`);
  } catch (e) { setMsg(el, 'err', e); }
};
window.loadBuildForm = async () => {
  const algo = document.getElementById('balgo').value;
  const box = document.getElementById('bform');
  try {
    const meta = await api('GET', `/3/ModelBuilders/${encodeURIComponent(algo)}`);
    const ps = (meta.model_builders[algo] || {}).parameters || [];
    const skip = new Set(['response_column', 'training_frame',
                          'validation_frame', 'ignored_columns']);
    box.innerHTML = `<table>${ps.filter(p => !skip.has(p.name)).map(p =>
      `<tr><td class="muted">${esc(p.name)}</td><td>
         <input size="14" data-param="${esc(p.name)}"
           data-default="${esc(p.default_value ?? '')}"
           value="${esc(p.default_value ?? '')}">
       </td><td class="muted">${esc(p.type)}</td></tr>`).join('')}</table>`;
  } catch (e) { setMsg(box, 'err', e); }
};
window.buildModel = async () => {
  const el = document.getElementById('bmsg');
  try {
    el.textContent = 'building…';
    const body = JSON.parse(document.getElementById('bparams').value || '{}');
    for (const inp of document.querySelectorAll('#bform input[data-param]')) {
      if (inp.value !== inp.dataset.default && inp.value !== '' &&
          !(inp.dataset.param in body)) {
        body[inp.dataset.param] = inp.value;
      }
    }
    body.training_frame = document.getElementById('bframe').value;
    body.response_column = document.getElementById('by').value;
    const algo = document.getElementById('balgo').value;
    const j = await api('POST', `/3/ModelBuilders/${encodeURIComponent(algo)}`, body);
    setMsg(el, 'ok', `job ${j.job.key.name || j.job.key} started`);
    show('Jobs');
  } catch (e) { setMsg(el, 'err', e); }
};
window.runAutoML = async () => {
  const el = document.getElementById('amsg');
  try {
    el.textContent = 'running…';
    const j = await api('POST', '/99/AutoMLBuilder', {
      build_control: { stopping_criteria: {
        max_models: parseInt(document.getElementById('amax').value || '8') } },
      input_spec: {
        training_frame: { name: document.getElementById('aframe').value },
        response_column: { column_name: document.getElementById('ay').value } },
      build_models: {},
    });
    const id = j.automl_id.name || j.automl_id;
    const jobKey = j.job.key.name || j.job.key;
    setMsg(el, 'ok', `started ${id}`);
    const pre = document.getElementById('aboard');
    pre.style.display = 'block';
    const poll = async () => {
      const a = await api('GET', `/99/AutoML/${encodeURIComponent(id)}`);
      pre.textContent = JSON.stringify(a.leaderboard_table || a, null, 2);
      const jb = await api('GET', `/3/Jobs/${encodeURIComponent(jobKey)}`);
      const st = (jb.jobs ? jb.jobs[0] : jb).status;
      if (st !== 'DONE' && st !== 'FAILED') setTimeout(poll, 3000);
      else setMsg(el, st === 'DONE' ? 'ok' : 'err', st);
    };
    poll();
  } catch (e) { setMsg(el, 'err', e); }
};
window.runRapids = async () => {
  const pre = document.getElementById('rout');
  pre.style.display = 'block';
  try {
    const j = await api('POST', '/99/Rapids', { ast: document.getElementById('rast').value });
    pre.textContent = JSON.stringify(j, null, 2);
  } catch (e) { pre.textContent = String(e); }
};

(async () => {
  try {
    const c = await api('GET', '/3/Cloud');
    document.getElementById('cloud').textContent =
      `${c.cloud_name || 'cloud'} — ${c.cloud_size} device(s), healthy=${c.cloud_healthy}`;
  } catch (e) { document.getElementById('cloud').textContent = 'cloud unreachable'; }
  show('Notebook');
})();
</script>
</body>
</html>
"""
