"""Flow UI successor — the notebook-style web console upstream ships as
``h2o-web``/Flow [UNVERIFIED upstream paths, SURVEY.md §2.3].

One self-contained page (no build step, no external assets — the coordinator
may be air-gapped) served at ``/`` and ``/flow``: browse frames / models /
jobs / grids, import + parse files, launch model builds and AutoML, inspect
metrics and variable importances, score a model on a frame — every action a
plain ``fetch`` against the public REST routes, so the page doubles as live
API documentation.
"""

FLOW_HTML = r"""<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>h2o3-tpu Flow</title>
<style>
  :root { --bg:#101418; --panel:#1a2026; --edge:#2c353d; --fg:#dfe7ee;
          --dim:#8b98a5; --acc:#ffd54a; --good:#7bd88f; --bad:#ff6e6e; }
  * { box-sizing:border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 -apple-system, "Segoe UI", Roboto, sans-serif; }
  header { display:flex; align-items:center; gap:14px; padding:10px 18px;
           background:var(--panel); border-bottom:1px solid var(--edge); }
  header h1 { font-size:16px; margin:0; color:var(--acc); }
  header .cloud { color:var(--dim); font-size:12px; }
  nav { display:flex; gap:4px; padding:8px 14px 0; }
  nav button { background:none; border:1px solid var(--edge);
               border-bottom:none; border-radius:6px 6px 0 0; color:var(--dim);
               padding:6px 14px; cursor:pointer; font-size:13px; }
  nav button.on { color:var(--fg); background:var(--panel); }
  main { padding:14px 18px; }
  section { display:none; } section.on { display:block; }
  table { border-collapse:collapse; width:100%; margin:8px 0; }
  th, td { text-align:left; padding:5px 10px; border-bottom:1px solid var(--edge);
           font-size:13px; }
  th { color:var(--dim); font-weight:600; }
  tr:hover td { background:#20272e; }
  .panel { background:var(--panel); border:1px solid var(--edge);
           border-radius:8px; padding:12px 14px; margin-bottom:12px; }
  input, select, textarea { background:#0d1114; color:var(--fg);
      border:1px solid var(--edge); border-radius:5px; padding:6px 8px;
      font-size:13px; }
  textarea { width:100%; font-family:ui-monospace, monospace; }
  button.act { background:var(--acc); color:#101418; border:none;
      border-radius:5px; padding:6px 14px; cursor:pointer; font-weight:600; }
  .muted { color:var(--dim); } .ok { color:var(--good); } .err { color:var(--bad); }
  pre { background:#0d1114; border:1px solid var(--edge); border-radius:6px;
        padding:10px; overflow:auto; font-size:12px; }
  .row { display:flex; gap:10px; flex-wrap:wrap; align-items:center; }
  progress { accent-color: var(--acc); }
</style>
</head>
<body>
<header>
  <h1>h2o3-tpu Flow</h1>
  <span class="cloud" id="cloud">connecting…</span>
</header>
<nav id="tabs"></nav>
<main id="main"></main>
<script>
const $$ = (h) => { const d = document.createElement('div'); d.innerHTML = h; return d.firstElementChild; };
const api = async (method, path, body) => {
  const opt = { method, headers: {} };
  if (body) { opt.body = JSON.stringify(body); opt.headers['Content-Type'] = 'application/json'; }
  const r = await fetch(path, opt);
  const j = await r.json();
  if (!r.ok) throw new Error(j.msg || r.statusText);
  return j;
};
const fmt = (v) => typeof v === 'number' ? (Number.isInteger(v) ? v : v.toPrecision(5)) : v;
// Server-controlled strings (keys, algo names, errors) are NOT trusted HTML:
// esc() for interpolation into markup/attributes, setMsg() for status lines.
const esc = (v) => String(v ?? '').replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const setMsg = (el, cls, text) => {
  const sp = document.createElement('span');
  sp.className = cls; sp.textContent = String(text);
  el.replaceChildren(sp);
};

const TABS = ['Frames', 'Models', 'Jobs', 'Build', 'AutoML', 'Rapids'];
const tabs = document.getElementById('tabs'), main = document.getElementById('main');
const sections = {};
for (const t of TABS) {
  const b = $$(`<button>${t}</button>`);
  b.onclick = () => show(t);
  tabs.appendChild(b);
  sections[t] = $$('<section></section>');
  main.appendChild(sections[t]);
}
function show(t) {
  [...tabs.children].forEach((b, i) => b.classList.toggle('on', TABS[i] === t));
  for (const k of TABS) sections[k].classList.toggle('on', k === t);
  render[t]();
}

const render = {
  async Frames() {
    const s = sections.Frames;
    s.innerHTML = `<div class="panel"><div class="row">
        <input id="imp" size="50" placeholder="/path/to/file.csv">
        <button class="act" onclick="importFile()">Import + parse</button>
        <span id="impmsg" class="muted"></span></div></div>
      <div id="frlist" class="muted">loading…</div>`;
    try {
      const j = await api('GET', '/3/Frames');
      const rows = (j.frames || []).map(f =>
        `<tr><td>${esc(f.frame_id.name || f.frame_id)}</td><td>${esc(f.rows)}</td>
         <td>${esc(f.column_count ?? '')}</td>
         <td><button data-k="${esc(f.frame_id.name || f.frame_id)}"
              onclick="frameSummary(this.dataset.k)">summary</button></td></tr>`);
      s.querySelector('#frlist').innerHTML =
        `<table><tr><th>key</th><th>rows</th><th>cols</th><th></th></tr>${rows.join('')}</table>
         <pre id="frdetail" style="display:none"></pre>`;
    } catch (e) { setMsg(s.querySelector('#frlist'), 'err', e); }
  },
  async Models() {
    const s = sections.Models;
    s.innerHTML = `<div id="mlist" class="muted">loading…</div>`;
    try {
      const j = await api('GET', '/3/Models');
      const rows = (j.models || []).map(m =>
        `<tr><td>${esc(m.model_id.name || m.model_id)}</td><td>${esc(m.algo)}</td>
         <td><button data-k="${esc(m.model_id.name || m.model_id)}"
              onclick="modelDetail(this.dataset.k)">inspect</button>
         <a href="/3/Models/${esc(encodeURIComponent(m.model_id.name || m.model_id))}/mojo"><button>mojo</button></a></td></tr>`);
      s.querySelector('#mlist').innerHTML =
        `<table><tr><th>key</th><th>algo</th><th></th></tr>${rows.join('')}</table>
         <div class="panel row"><b>Predict:</b>
           <input id="pm" placeholder="model key"><input id="pf" placeholder="frame key">
           <button class="act" onclick="predict()">score</button>
           <span id="pmsg" class="muted"></span></div>
         <pre id="mdetail" style="display:none"></pre>`;
    } catch (e) { setMsg(s.querySelector('#mlist'), 'err', e); }
  },
  async Jobs() {
    const s = sections.Jobs;
    s.innerHTML = `<div id="jlist" class="muted">loading…</div>`;
    try {
      const j = await api('GET', '/3/Jobs');
      const rows = (j.jobs || []).map(jb =>
        `<tr><td>${esc(jb.key.name || jb.key)}</td><td>${esc(jb.description || '')}</td>
         <td>${esc(jb.status)}</td><td><progress value="${Number(jb.progress) || 0}" max="1"></progress></td></tr>`);
      s.querySelector('#jlist').innerHTML =
        `<table><tr><th>job</th><th>description</th><th>status</th><th>progress</th></tr>${rows.join('')}</table>`;
    } catch (e) { setMsg(s.querySelector('#jlist'), 'err', e); }
  },
  async Build() {
    const s = sections.Build;
    if (s.dataset.ready) return;
    s.dataset.ready = 1;
    let algos = [];
    try { algos = Object.keys((await api('GET', '/3/ModelBuilders')).model_builders); } catch (e) {}
    s.innerHTML = `<div class="panel">
      <div class="row"><b>Algorithm:</b>
        <select id="balgo" onchange="loadBuildForm()">${algos.map(a => `<option>${esc(a)}</option>`).join('')}</select>
        <b>Training frame:</b> <input id="bframe" placeholder="frame key">
        <b>Response:</b> <input id="by" size="12" placeholder="y"></div>
      <p class="muted">Parameters (schema-generated from the live
        /3/ModelBuilders/{algo} metadata — the Flow "assist" form; values
        left at their defaults are not sent):</p>
      <div id="bform" style="max-height:260px;overflow:auto"></div>
      <p class="muted">Extra parameters (JSON) — merged over the form:</p>
      <textarea id="bparams" rows="2">{}</textarea>
      <p><button class="act" onclick="buildModel()">Build</button>
      <span id="bmsg" class="muted"></span></p></div>`;
    loadBuildForm();
  },
  async AutoML() {
    const s = sections.AutoML;
    if (s.dataset.ready) return;
    s.dataset.ready = 1;
    s.innerHTML = `<div class="panel">
      <div class="row"><b>Training frame:</b> <input id="aframe">
        <b>Response:</b> <input id="ay" size="12">
        <b>max_models:</b> <input id="amax" size="5" value="8"></div>
      <p><button class="act" onclick="runAutoML()">Run AutoML</button>
      <span id="amsg" class="muted"></span></p>
      <pre id="aboard" style="display:none"></pre></div>`;
  },
  async Rapids() {
    const s = sections.Rapids;
    if (s.dataset.ready) return;
    s.dataset.ready = 1;
    s.innerHTML = `<div class="panel">
      <p class="muted">Rapids expression (the /99/Rapids wire grammar):</p>
      <div class="row"><input id="rast" size="70"
        placeholder='(tmp= new_fr (cols_py frame_key [0 1]))'>
      <button class="act" onclick="runRapids()">Eval</button></div>
      <pre id="rout" style="display:none"></pre></div>`;
  },
};

window.importFile = async () => {
  const el = document.getElementById('impmsg');
  try {
    el.textContent = 'importing…';
    const path = document.getElementById('imp').value;
    const setup = await api('POST', '/3/ParseSetup', { source_frames: [path] });
    await api('POST', '/3/Parse', setup);
    setMsg(el, 'ok', 'parsed ✓');
    render.Frames();
  } catch (e) { setMsg(el, 'err', e); }
};
window.frameSummary = async (k) => {
  const pre = document.getElementById('frdetail');
  pre.style.display = 'block';
  pre.textContent = JSON.stringify(
    await api('GET', `/3/Frames/${encodeURIComponent(k)}/summary`), null, 2);
};
window.modelDetail = async (k) => {
  const pre = document.getElementById('mdetail');
  pre.style.display = 'block';
  pre.textContent = JSON.stringify(
    await api('GET', `/3/Models/${encodeURIComponent(k)}`), null, 2);
};
window.predict = async () => {
  const el = document.getElementById('pmsg');
  try {
    const m = document.getElementById('pm').value, f = document.getElementById('pf').value;
    const j = await api('POST',
      `/3/Predictions/models/${encodeURIComponent(m)}/frames/${encodeURIComponent(f)}`, {});
    setMsg(el, 'ok', `→ ${j.predictions_frame.name || j.predictions_frame}`);
  } catch (e) { setMsg(el, 'err', e); }
};
window.loadBuildForm = async () => {
  const algo = document.getElementById('balgo').value;
  const box = document.getElementById('bform');
  try {
    const meta = await api('GET', `/3/ModelBuilders/${encodeURIComponent(algo)}`);
    const ps = (meta.model_builders[algo] || {}).parameters || [];
    const skip = new Set(['response_column', 'training_frame',
                          'validation_frame', 'ignored_columns']);
    box.innerHTML = `<table>${ps.filter(p => !skip.has(p.name)).map(p =>
      `<tr><td class="muted">${esc(p.name)}</td><td>
         <input size="14" data-param="${esc(p.name)}"
           data-default="${esc(p.default_value ?? '')}"
           value="${esc(p.default_value ?? '')}">
       </td><td class="muted">${esc(p.type)}</td></tr>`).join('')}</table>`;
  } catch (e) { setMsg(box, 'err', e); }
};
window.buildModel = async () => {
  const el = document.getElementById('bmsg');
  try {
    el.textContent = 'building…';
    const body = JSON.parse(document.getElementById('bparams').value || '{}');
    for (const inp of document.querySelectorAll('#bform input[data-param]')) {
      if (inp.value !== inp.dataset.default && inp.value !== '' &&
          !(inp.dataset.param in body)) {
        body[inp.dataset.param] = inp.value;
      }
    }
    body.training_frame = document.getElementById('bframe').value;
    body.response_column = document.getElementById('by').value;
    const algo = document.getElementById('balgo').value;
    const j = await api('POST', `/3/ModelBuilders/${encodeURIComponent(algo)}`, body);
    setMsg(el, 'ok', `job ${j.job.key.name || j.job.key} started`);
    show('Jobs');
  } catch (e) { setMsg(el, 'err', e); }
};
window.runAutoML = async () => {
  const el = document.getElementById('amsg');
  try {
    el.textContent = 'running…';
    const j = await api('POST', '/99/AutoMLBuilder', {
      build_control: { stopping_criteria: {
        max_models: parseInt(document.getElementById('amax').value || '8') } },
      input_spec: {
        training_frame: { name: document.getElementById('aframe').value },
        response_column: { column_name: document.getElementById('ay').value } },
      build_models: {},
    });
    const id = j.automl_id.name || j.automl_id;
    const jobKey = j.job.key.name || j.job.key;
    setMsg(el, 'ok', `started ${id}`);
    const pre = document.getElementById('aboard');
    pre.style.display = 'block';
    const poll = async () => {
      const a = await api('GET', `/99/AutoML/${encodeURIComponent(id)}`);
      pre.textContent = JSON.stringify(a.leaderboard_table || a, null, 2);
      const jb = await api('GET', `/3/Jobs/${encodeURIComponent(jobKey)}`);
      const st = (jb.jobs ? jb.jobs[0] : jb).status;
      if (st !== 'DONE' && st !== 'FAILED') setTimeout(poll, 3000);
      else setMsg(el, st === 'DONE' ? 'ok' : 'err', st);
    };
    poll();
  } catch (e) { setMsg(el, 'err', e); }
};
window.runRapids = async () => {
  const pre = document.getElementById('rout');
  pre.style.display = 'block';
  try {
    const j = await api('POST', '/99/Rapids', { ast: document.getElementById('rast').value });
    pre.textContent = JSON.stringify(j, null, 2);
  } catch (e) { pre.textContent = String(e); }
};

(async () => {
  try {
    const c = await api('GET', '/3/Cloud');
    document.getElementById('cloud').textContent =
      `${c.cloud_name || 'cloud'} — ${c.cloud_size} device(s), healthy=${c.cloud_healthy}`;
  } catch (e) { document.getElementById('cloud').textContent = 'cloud unreachable'; }
  show('Frames');
})();
</script>
</body>
</html>
"""
