"""Multi-host launcher — the ``h2odriver`` / ``h2o-k8s`` successor
[UNVERIFIED upstream paths, SURVEY.md §2.3].

H2O launches one JVM per Hadoop/k8s node and gossips a cloud; here each
host runs one process of a ``jax.distributed`` pod and the coordination
service forms the cloud (cluster/cloud.py, bootstrapped through
cluster/multihost.py). On k8s, point every pod at the rank-0 pod's
headless-service DNS name — via args:

    python -m h2o3_tpu.launch --coordinator pod-0.svc:1234 \
        --num-processes 4 --process-id $POD_INDEX --port 54321

or entirely via environment (the StatefulSet mode — the SAME command runs
on every replica, the rank deriving from the pod-name ordinal):

    H2O3_TPU_COORDINATOR=h2o3-tpu-0.h2o3-tpu:1234 \
    H2O3_TPU_NUM_PROCESSES=4 python -m h2o3_tpu.launch

Process 0 additionally serves the REST coordinator (any process can, but
one suffices — clients talk to one coordinator like H2O clients talk to any
cloud member). On a multi-process pod every rank installs the pod-restart
watcher (H2O3_TPU_POD_EXIT_DEGRADED, cluster/multihost.py): a degraded
latch that cannot heal in-process exits the rank so the pod supervisor
re-forms the whole cloud and the PR-10 supervisor resumes from snapshots.
"""

from __future__ import annotations

import argparse
import time


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="h2o3_tpu.launch")
    ap.add_argument("--coordinator", default=None,
                    help="rank-0 address host:port (the -flatfile successor; "
                         "default: H2O3_TPU_COORDINATOR env)")
    ap.add_argument("--num-processes", type=int, default=None,
                    help="pod size (default: H2O3_TPU_NUM_PROCESSES env)")
    ap.add_argument("--process-id", type=int, default=None,
                    help="this rank (default: H2O3_TPU_PROCESS_ID env, or "
                         "the trailing pod-name ordinal)")
    ap.add_argument("--ip", default="0.0.0.0",
                    help="REST bind address for process 0 (default: all "
                         "interfaces — other pods must reach it)")
    from h2o3_tpu import config

    ap.add_argument("--port", type=int,
                    default=config.get_int("H2O3_TPU_PORT"),
                    help="REST port served by process 0 "
                         "(default: H2O3_TPU_PORT knob)")
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)

    from h2o3_tpu.cluster import multihost

    rec = multihost.bootstrap(
        coordinator=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        log_level=args.log_level,
    )
    from h2o3_tpu.utils.log import Log

    pid, nproc = rec["process_index"], rec["processes"]
    Log.info(f"process {pid}/{nproc} joined: {rec}")
    if nproc > 1:
        # the k8s restart loop's trigger (no-op while the knob is 0)
        multihost.install_pod_restart()
    if pid == 0:
        import signal

        import h2o3_tpu
        from h2o3_tpu.api import server as _api_server
        from h2o3_tpu.cluster import recovery

        # self-healing: the background supervisor re-forms the cloud when
        # the degraded latch is set with no supervised job attached (a
        # watchdog trip between jobs) — no-op under H2O3_TPU_RECOVERY=0
        recovery.install()
        # overload plane: the dispatch hang watchdog (no-op per pass under
        # H2O3_TPU_OVERLOAD=0); start_server installs it too, but followers
        # route here without a server, and every rank watches its OWN ring
        # — the federation scrape rank-labels dispatch_hung, so the
        # coordinator reads which rank lags from /3/Metrics
        from h2o3_tpu.utils import overload

        overload.install_watchdog()
        srv = h2o3_tpu.start_server(ip=args.ip, port=args.port)

        def _graceful_term(signum, frame):
            # k8s rotation (or any SIGTERM) drains before dying even when no
            # preStop hook fired: stop admitting, flush running jobs'
            # checkpoints, shut down followers, close the listener
            Log.info("SIGTERM: graceful drain starting")
            try:
                srv.stop(drain=True)
            except Exception as e:  # noqa: BLE001 — exiting either way
                Log.warn(f"drain on SIGTERM failed: {e!r}")

        signal.signal(signal.SIGTERM, _graceful_term)
        try:
            # serve until stopped — a REST /3/Shutdown (or the SIGTERM drain
            # above) clears the process singleton, and the launcher exits so
            # the pod terminates instead of sleeping out its grace period
            while _api_server._SERVER is srv:
                time.sleep(1.0)
        except KeyboardInterrupt:
            pass
    else:
        # followers execute the coordinator's replicated command stream (the
        # DTask successor) — every rank runs the same device programs
        from h2o3_tpu.cluster.spmd import follower_loop
        from h2o3_tpu.utils import overload

        # each rank watches its OWN flight-recorder ring: a dispatch wedged
        # on one rank trips that rank's dispatch_hung{site} gauge, which
        # the pod federation scrape rank-labels — the lagging-rank flag
        overload.install_watchdog()
        follower_loop()


if __name__ == "__main__":
    main()
