"""KMeans — successor of ``hex.kmeans.KMeans`` (Lloyd + k-means‖ init,
constrained variant excluded) [UNVERIFIED upstream path, SURVEY.md §2.2].

Each Lloyd iteration is one fused device program over the row-sharded design
matrix: distance matrix (n,k) on the MXU, hard assignment, centroid partial
sums via one-hot matmul (no scatter), psum across the mesh implicit in the
sharded einsum. H2O's per-iteration MRTask maps exactly onto this.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.datainfo import DataInfo
from h2o3_tpu.models.metrics import ModelMetrics
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder

_HI = jax.lax.Precision.HIGHEST


@dataclass
class KMeansParams(CommonParams):
    k: int = 2
    max_iterations: int = 10
    init: str = "Furthest"  # Furthest | PlusPlus | Random
    standardize: bool = True
    estimate_k: bool = False


@partial(jax.jit, static_argnames=())
def _lloyd_step(X, w, centers):
    """One Lloyd iteration: assignment + weighted centroid sums + SSE."""
    d2 = (
        jnp.sum(X * X, axis=1, keepdims=True)
        - 2.0 * jnp.einsum("np,kp->nk", X, centers, precision=_HI)
        + jnp.sum(centers * centers, axis=1)[None, :]
    )
    assign = jnp.argmin(d2, axis=1)
    mind2 = jnp.maximum(jnp.min(d2, axis=1), 0.0)
    oh = (assign[:, None] == jnp.arange(centers.shape[0])[None, :]).astype(
        jnp.float32
    ) * w[:, None]
    sums = jnp.einsum("nk,np->kp", oh, X, precision=_HI)
    counts = oh.sum(axis=0)
    sse = jnp.sum(w * mind2)
    within = jnp.einsum("nk,n->k", oh, mind2, precision=_HI)
    return assign, sums, counts, sse, within


class KMeansModel(Model):
    algo = "kmeans"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, valid = di.transform(frame)
        centers = jnp.asarray(self.output["centers_std"], jnp.float32)
        assign, *_ = _lloyd_step(X, valid, centers)
        return np.asarray(assign)[: frame.nrow]

    def predict(self, frame: Frame) -> Frame:
        assign = self._predict_raw(frame)
        return Frame([Vec.from_numpy(assign.astype(np.float64), "int")], ["predict"])

    @property
    def centers(self) -> np.ndarray:
        return self.output["centers"]


class KMeans(ModelBuilder):
    algo = "kmeans"
    PARAMS_CLS = KMeansParams
    SUPPORTS_CLASSIFICATION = False

    def train(self, x=None, training_frame=None, **kw):
        return super().train(x=x, y=None, training_frame=training_frame, **kw)

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: KMeansParams = self.params
        di = DataInfo.fit(train, self._x, standardize=p.standardize)
        X, w = di.transform(train)
        k = int(p.k)
        rng = np.random.default_rng(abs(p.seed) if p.seed and p.seed > 0 else 1)

        Xn = np.asarray(X)
        wn = np.asarray(w)
        rows = np.flatnonzero(wn > 0)
        centers = self._init_centers(Xn, rows, k, p.init, rng)

        sse_prev = np.inf
        centers_j = jnp.asarray(centers, jnp.float32)
        for it in range(max(1, p.max_iterations)):
            assign, sums, counts, sse, within = _lloyd_step(X, w, centers_j)
            counts_n = np.asarray(counts)
            sums_n = np.asarray(sums)
            new_centers = np.where(
                counts_n[:, None] > 0, sums_n / np.maximum(counts_n[:, None], 1e-30),
                np.asarray(centers_j),
            )
            # dead cluster re-seed (h2o re-initializes empty clusters)
            for ki in np.flatnonzero(counts_n == 0):
                new_centers[ki] = Xn[rng.choice(rows)]
            centers_j = jnp.asarray(new_centers, jnp.float32)
            sse_now = float(sse)
            job.update(0.1 + 0.8 * (it + 1) / p.max_iterations)
            if abs(sse_prev - sse_now) / max(sse_now, 1e-30) < 1e-6:
                break
            sse_prev = sse_now

        assign, sums, counts, sse, within = _lloyd_step(X, w, centers_j)
        centers_std = np.asarray(centers_j)
        # destandardize for reporting
        centers_orig = centers_std.copy()
        col_i = 0
        for c in di.columns:
            if c.kind == "num":
                centers_orig[:, c.offset] = centers_std[:, c.offset] * c.sigma + c.mean
        tot_within = float(jnp.sum(within))
        gm = np.average(centers_std, axis=0, weights=np.maximum(np.asarray(counts), 1e-9))
        between = float(
            np.sum(np.asarray(counts) * np.sum((centers_std - gm) ** 2, axis=1))
        )

        out = {
            "datainfo": di,
            "centers_std": centers_std,
            "centers": centers_orig,
            "names": list(self._x),
            "k": k,
            "size": np.asarray(counts).tolist(),
            "response_domain": None,
        }
        model = KMeansModel(DKV.make_key("kmeans"), p, out)
        model.training_metrics = ModelMetrics(
            "clustering",
            {
                "tot_withinss": tot_within,
                "betweenss": between,
                "totss": tot_within + between,
                "within_cluster_sum_of_squares": np.asarray(within).tolist(),
                "cluster_sizes": np.asarray(counts).tolist(),
            },
        )
        return model

    def _init_centers(self, Xn, rows, k, method, rng) -> np.ndarray:
        method = (method or "Furthest").lower()
        first = Xn[rng.choice(rows)]
        centers = [first]
        if method == "random":
            return Xn[rng.choice(rows, size=k, replace=False)]
        # Furthest (h2o default) and PlusPlus share the distance recursion
        sample = Xn[rows] if len(rows) <= 100_000 else Xn[rng.choice(rows, 100_000, replace=False)]
        d2 = np.sum((sample - first) ** 2, axis=1)
        for _ in range(1, k):
            if method == "plusplus":
                probs = d2 / max(d2.sum(), 1e-30)
                nxt = sample[rng.choice(len(sample), p=probs)]
            else:
                nxt = sample[int(np.argmax(d2))]
            centers.append(nxt)
            d2 = np.minimum(d2, np.sum((sample - nxt) ** 2, axis=1))
        return np.stack(centers)

    def _validate(self, train, valid):
        pass
