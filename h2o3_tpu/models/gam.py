"""GAM — successor of ``hex.gam.GAM`` / ``GamSplines`` [UNVERIFIED upstream
paths, SURVEY.md §2.2]: generalized additive models with cubic regression
splines.

Per ``gam_column``: quantile knots, Wood-style cardinal natural cubic spline
basis (function values at knots are the coefficients; the curvature penalty
is S = DᵀB⁻¹D), sum-to-zero centering via the Z null-space transform for
identifiability — the same construction H2O inherits from mgcv.

TPU design: basis expansion happens host-side once (it is O(n·k) float math,
k ~ 10), the expanded design [linear | splines | intercept] ships to the
device row-sharded, and each IRLS step is ONE fused Gram pass on the MXU
(ops/gram.weighted_gram). The penalized solve (G + λ·blockdiag(S̃)) happens
host-side in float64, mirroring the GLM split.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.glm_families import get_family
from h2o3_tpu.models.model_base import CommonParams, Model, ModelBuilder
from h2o3_tpu.ops.gram import solve_cholesky, weighted_gram
from h2o3_tpu.parallel.mesh import row_sharding


@dataclass
class GAMParams(CommonParams):
    family: str = "AUTO"
    gam_columns: list = field(default_factory=list)
    num_knots: list = field(default_factory=list)  # per gam col; default 10
    scale: list = field(default_factory=list)  # smoothing lambda per gam col
    bs: list = field(default_factory=list)  # basis type per col; 0 = cr (only)
    lambda_: float = 0.0  # ridge on the parametric part
    standardize: bool = True
    intercept: bool = True
    max_iterations: int = 50
    beta_epsilon: float = 1e-6
    keep_gam_cols: bool = False


def _cr_penalty(knots: np.ndarray):
    """Return (F, S): second-derivative map (k,k) and penalty DᵀB⁻¹D (k,k)."""
    k = len(knots)
    h = np.diff(knots)
    D = np.zeros((k - 2, k))
    B = np.zeros((k - 2, k - 2))
    for i in range(k - 2):
        D[i, i] = 1.0 / h[i]
        D[i, i + 1] = -1.0 / h[i] - 1.0 / h[i + 1]
        D[i, i + 2] = 1.0 / h[i + 1]
        B[i, i] = (h[i] + h[i + 1]) / 3.0
        if i + 1 < k - 2:
            B[i, i + 1] = B[i + 1, i] = h[i + 1] / 6.0
    Binv = np.linalg.inv(B)
    F = np.zeros((k, k))
    F[1:-1] = Binv @ D  # natural spline: zero curvature at the boundary knots
    S = D.T @ Binv @ D
    return F, S


def _cr_basis(x: np.ndarray, knots: np.ndarray, F: np.ndarray) -> np.ndarray:
    """Evaluate the cardinal CR basis at x -> (n, k). Clamped at the range."""
    k = len(knots)
    xc = np.clip(x, knots[0], knots[-1])
    j = np.clip(np.searchsorted(knots, xc, side="right") - 1, 0, k - 2)
    h = knots[j + 1] - knots[j]
    am = (knots[j + 1] - xc) / h
    ap = (xc - knots[j]) / h
    cm = ((knots[j + 1] - xc) ** 3 / h - h * (knots[j + 1] - xc)) / 6.0
    cp = ((xc - knots[j]) ** 3 / h - h * (xc - knots[j])) / 6.0
    n = len(x)
    X = np.zeros((n, k))
    rows = np.arange(n)
    X[rows, j] += am
    X[rows, j + 1] += ap
    X += cm[:, None] * F[j] + cp[:, None] * F[j + 1]
    return X


def _center_transform(X: np.ndarray):
    """Z with columns spanning {v : 1ᵀXv = 0} — mgcv's centering constraint."""
    c = X.sum(axis=0, keepdims=True)  # (1, k)
    # householder-style: QR of cᵀ, Z = last k-1 columns of Q
    q, _ = np.linalg.qr(c.T, mode="complete")
    return q[:, 1:]  # (k, k-1)


class GAMModel(Model):
    algo = "gam"

    def _expand(self, frame: Frame) -> np.ndarray:
        o = self.output
        cols = []
        for n in o["linear_names"]:
            x = frame.vec(n).to_numpy().astype(np.float64)
            info = o["linear_info"][n]
            x = np.where(np.isnan(x), info["mean"], x)
            cols.append(((x - info["mean"]) / info["sigma"])[:, None])
        for g in o["gam_terms"]:
            x = frame.vec(g["name"]).to_numpy().astype(np.float64)
            x = np.where(np.isnan(x), g["impute"], x)
            Xb = _cr_basis(x, g["knots"], g["F"]) @ g["Z"]
            cols.append(Xb)
        if o.get("intercept", True):
            cols.append(np.ones((frame.nrow, 1)))
        return np.concatenate(cols, axis=1)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X = self._expand(frame)
        eta = X @ self.output["beta"]
        fam = self.output["family_obj"]
        mu = np.asarray(fam.link.inv(jnp.asarray(eta)))
        if self.is_classifier:
            return np.stack([1 - mu, mu], axis=1)
        return mu

    @property
    def coef(self) -> dict:
        return dict(zip(self.output["coef_names"], self.output["beta"]))

    def _distribution_for_metrics(self) -> str:
        fam = self.output["family"]
        return {"poisson": "poisson", "gamma": "gamma"}.get(fam, "gaussian")


class GAM(ModelBuilder):
    algo = "gam"
    PARAMS_CLS = GAMParams

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: GAMParams = self.params
        if not p.gam_columns:
            raise ValueError("gam requires gam_columns")
        yv = train.vec(p.response_column)
        family = p.family.lower()
        if family == "auto":
            family = "binomial" if yv.is_categorical() else "gaussian"
        fam = get_family(family)

        gam_cols = [
            c[0] if isinstance(c, (list, tuple)) else c for c in p.gam_columns
        ]
        linear_names = [
            n for n in self._x
            if n not in gam_cols and train.vec(n).is_numeric()
        ]

        # linear (parametric) part, standardized
        linear_info: dict[str, dict] = {}
        cols = []
        for n in linear_names:
            x = train.vec(n).to_numpy().astype(np.float64)
            mean = float(np.nanmean(x)) if p.standardize else 0.0
            sigma = (float(np.nanstd(x)) or 1.0) if p.standardize else 1.0
            linear_info[n] = {"mean": mean, "sigma": sigma}
            x = np.where(np.isnan(x), mean if p.standardize else 0.0, x)
            cols.append(((x - mean) / sigma)[:, None])

        # spline blocks
        gam_terms: list[dict] = []
        blocks: list[tuple[int, int]] = []  # (offset, width) of each spline
        off = sum(c.shape[1] for c in cols)
        penalties: list[tuple[np.ndarray, float]] = []
        for gi, name in enumerate(gam_cols):
            v = train.vec(name)
            if not v.is_numeric():
                raise ValueError(f"gam column {name!r} must be numeric")
            x = v.to_numpy().astype(np.float64)
            impute = float(np.nanmean(x))
            x = np.where(np.isnan(x), impute, x)
            nk = int(p.num_knots[gi]) if gi < len(p.num_knots) else 10
            nk = max(3, nk)
            qs = np.linspace(0, 1, nk)
            knots = np.unique(np.quantile(x, qs))
            if len(knots) < 3:
                raise ValueError(f"gam column {name!r} has too few distinct values")
            F, S = _cr_penalty(knots)
            Xb = _cr_basis(x, knots, F)
            Z = _center_transform(Xb)
            Xc = Xb @ Z
            Sc = Z.T @ S @ Z
            lam = float(p.scale[gi]) if gi < len(p.scale) else 1.0
            gam_terms.append(
                {"name": name, "knots": knots, "F": F, "Z": Z, "impute": impute,
                 "scale": lam}
            )
            blocks.append((off, Xc.shape[1]))
            penalties.append((Sc, lam))
            cols.append(Xc)
            off += Xc.shape[1]
        if p.intercept:
            cols.append(np.ones((train.nrow, 1)))
        Xh = np.concatenate(cols, axis=1)
        nrow, P = Xh.shape

        # penalty matrix over the full design
        Pen = np.zeros((P, P))
        for (o_, w_), (Sc, lam) in zip(blocks, penalties):
            Pen[o_ : o_ + w_, o_ : o_ + w_] = lam * Sc
        if p.lambda_:
            n_ridge = P - 1 if p.intercept else P  # never ridge the intercept
            for i in range(n_ridge):
                Pen[i, i] += p.lambda_

        y_np = yv.to_numpy().astype(np.float64)
        if yv.is_categorical():
            y_np[y_np < 0] = np.nan
        w_np = np.ones(nrow, np.float64)
        if p.weights_column:
            w_np *= np.nan_to_num(train.vec(p.weights_column).to_numpy())
        w_np *= ~np.isnan(y_np)
        y_clean = np.nan_to_num(y_np, nan=0.0)

        npad = train.npad
        Xp = np.zeros((npad, P), np.float32)
        Xp[:nrow] = Xh
        Xd = jax.device_put(jnp.asarray(Xp), row_sharding())
        wp = np.zeros(npad, np.float32)
        wp[:nrow] = w_np
        yp = np.zeros(npad, np.float32)
        yp[:nrow] = y_clean
        wd, yd = jnp.asarray(wp), jnp.asarray(yp)

        # penalized IRLS: device Gram pass + host f64 penalized solve
        beta = np.zeros(P, np.float64)
        if p.intercept:
            mu0 = float(np.sum(w_np * y_clean) / max(np.sum(w_np), 1e-10))
            if family == "binomial":
                mu0 = min(max(mu0, 1e-4), 1 - 1e-4)
            beta[-1] = float(np.asarray(fam.link.fwd(jnp.asarray(mu0))))

        max_iter = p.max_iterations if p.max_iterations > 0 else 50
        dev = np.inf
        for it in range(max_iter):
            G_d, b_d, dev_d = _gam_irls_pass(
                Xd, yd, wd, jnp.asarray(beta, jnp.float32), family
            )
            G = np.asarray(G_d, np.float64)
            b = np.asarray(b_d, np.float64)
            new = solve_cholesky(G + Pen, b)
            delta = np.max(np.abs(new - beta))
            beta = new
            dev = float(dev_d)
            job.update(0.1 + 0.8 * (it + 1) / max_iter)
            if delta < p.beta_epsilon:
                break

        coef_names = (
            list(linear_names)
            + [
                f"{g['name']}_cr_{i}"
                for g, (o_, w_) in zip(gam_terms, blocks)
                for i in range(w_)
            ]
            + (["Intercept"] if p.intercept else [])
        )
        out = {
            "intercept": p.intercept,
            "beta": beta,
            "coef_names": coef_names,
            "linear_names": linear_names,
            "linear_info": linear_info,
            "gam_terms": gam_terms,
            "family": family,
            "family_obj": fam,
            "deviance": dev,
            "names": list(self._x),
            "response_domain": tuple(yv.domain) if yv.is_categorical() else None,
        }
        model = GAMModel(DKV.make_key("gam"), p, out)
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model


from functools import partial


@partial(jax.jit, static_argnames=("family_key",))
def _gam_irls_pass(X, y, w, beta, family_key):
    fam = get_family(family_key)
    eta = jnp.einsum("np,p->n", X, beta, precision=jax.lax.Precision.HIGHEST)
    mu = fam.link.inv(eta)
    d = fam.link.dinv(eta)
    d = jnp.where(d == 0, 1e-10, jnp.sign(d) * jnp.maximum(jnp.abs(d), 1e-10))
    var = fam.variance(mu)
    z = eta + (y - mu) / d
    W = w * d * d / var
    G, b, sw = weighted_gram(X, W, z)
    dev = fam.deviance(y, mu, w)
    return G, b, dev
