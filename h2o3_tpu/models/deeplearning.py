"""DeepLearning — successor of ``hex.deeplearning.DeepLearning`` /
``DeepLearningModel`` / ``Neurons`` [UNVERIFIED upstream paths, SURVEY.md
§2.2].

H2O trains a fully-connected MLP with **Hogwild!** lock-free async SGD
within a node plus periodic cross-node model averaging. The north star
(BASELINE.json) explicitly licenses replacing that with synchronous
data-parallel SGD: here each epoch is ONE compiled ``lax.scan`` over
minibatches of the row-sharded design matrix — flax MLP forward/backward on
the MXU, ADADELTA (h2o's adaptive_rate default) or momentum SGD from optax.
Parameter parity: hidden/activation (+dropout variants), input_dropout,
l1/l2, adaptive-rate rho/epsilon, rate/rate_decay, standardize, early
stopping. Deviation noted: ``mini_batch_size`` defaults to 32 (h2o's
online default of 1 serializes the MXU for no accuracy gain).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.datainfo import DataInfo
from h2o3_tpu.models.model_base import (
    CommonParams,
    Model,
    ModelBuilder,
    ScoreKeeper,
)
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log

_DL_EPOCHS = _mx.counter("dl_epochs_total", "DeepLearning epochs executed")
_DL_EPOCH_SECONDS = _mx.histogram(
    "dl_epoch_seconds", "per-epoch wall time of the sync-SGD driver")


@dataclass
class DeepLearningParams(CommonParams):
    hidden: Sequence[int] = field(default_factory=lambda: (200, 200))
    epochs: float = 10.0
    activation: str = "Rectifier"
    input_dropout_ratio: float = 0.0
    hidden_dropout_ratios: Sequence[float] | None = None
    l1: float = 0.0
    l2: float = 0.0
    adaptive_rate: bool = True
    rho: float = 0.99
    epsilon: float = 1e-8
    rate: float = 0.005
    rate_decay: float = 1.0
    momentum_start: float = 0.0
    mini_batch_size: int = 32
    standardize: bool = True
    loss: str = "Automatic"
    reproducible: bool = True  # sync SGD is deterministic by construction
    autoencoder: bool = False  # reconstruct inputs; y is ignored
    # feature hashing for Criteo-class cardinalities (datainfo.py)
    hash_buckets: int | None = None


class _MLP(nn.Module):
    hidden: tuple
    n_out: int
    activation: str
    dropout: tuple
    input_dropout: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = {
            "rectifier": nn.relu,
            "rectifierwithdropout": nn.relu,
            "tanh": nn.tanh,
            "tanhwithdropout": nn.tanh,
            "maxout": nn.relu,  # maxout approximated [deviation noted]
        }[self.activation.lower()]
        if self.input_dropout > 0:
            x = nn.Dropout(self.input_dropout, deterministic=not train)(x)
        for i, h in enumerate(self.hidden):
            x = nn.Dense(h)(x)
            x = act(x)
            if self.dropout[i] > 0:
                x = nn.Dropout(self.dropout[i], deterministic=not train)(x)
        return nn.Dense(self.n_out)(x)




def _run_sync_sgd(job, p, loss_fn, tx, params, opt_state, X, y, w,
                  nrow: int, npad: int, key, start_epochs: int = 0,
                  on_epoch=None):
    """The shared sync-SGD epoch driver for both supervised and autoencoder
    training: permutation shuffling, lax.scan over mini-batches, epoch-loss
    early stopping, checkpoint RNG alignment. ``loss_fn(prm, xb, yb, wb,
    kb)`` supplies the per-batch objective (yb is the permuted target slice
    — unused by the autoencoder loss). ``on_epoch(params, opt_state,
    epochs_done, history)`` fires at every epoch boundary — the interval-
    checkpoint/fault hook. Returns (params, opt_state, history,
    epochs_done)."""
    batch = min(int(p.mini_batch_size), npad)
    nbatch = max(1, nrow // batch)
    # padded permutation slots alias row 0 — a SLOT mask zeroes their weight
    # so a final partial batch cannot over-count real rows (nrow < batch)
    slot_mask = jnp.asarray((np.arange(npad) < nrow).astype(np.float32))

    @jax.jit
    def epoch(params, opt_state, Xp, yp, wp, dkey):
        def step(carry, i):
            prm, ost, k = carry
            k, bk = jax.random.split(k)
            start = i * batch
            xb = jax.lax.dynamic_slice(Xp, (start, 0), (batch, Xp.shape[1]))
            yb = jax.lax.dynamic_slice(yp, (start,), (batch,))
            wb = jax.lax.dynamic_slice(wp, (start,), (batch,))
            loss, g = jax.value_and_grad(loss_fn)(prm, xb, yb, wb, bk)
            upd, ost = tx.update(g, ost, prm)
            prm = optax.apply_updates(prm, upd)
            return (prm, ost, k), loss

        (params, opt_state, _), losses = jax.lax.scan(
            step, (params, opt_state, dkey), jnp.arange(nbatch)
        )
        return params, opt_state, losses.mean()

    # epoch-level stopping tracks the (always smaller-is-better) training
    # loss; the resolved stopping_metric drives final scoring only
    keeper = ScoreKeeper(p.stopping_rounds, p.stopping_tolerance, False)
    seed = abs(p.seed) if p.seed and p.seed > 0 else 99
    rng = np.random.default_rng(seed)
    history = []
    n_epochs = max(1, int(np.ceil(p.epochs)))
    for _ in range(start_epochs):  # continuation: keep the epoch RNG
        rng.permutation(nrow)  # stream aligned with an
        key, _ = jax.random.split(key)  # uninterrupted run
    epochs_done = start_epochs
    import time as _time

    for e in range(start_epochs, n_epochs):
        _ep_t0 = _time.perf_counter()
        perm = np.zeros(npad, np.int64)
        perm[:nrow] = rng.permutation(nrow)
        perm_j = jnp.asarray(perm)
        key, dkey = jax.random.split(key)
        params, opt_state, mean_loss = epoch(
            params, opt_state, X[perm_j], y[perm_j], w[perm_j] * slot_mask, dkey
        )
        epochs_done = e + 1
        # the float() below syncs on the epoch's device work, so the
        # observation covers shuffle + scan, not just dispatch
        history.append({"epoch": e + 1, "loss": float(mean_loss)})
        _DL_EPOCHS.inc()
        _DL_EPOCH_SECONDS.observe(_time.perf_counter() - _ep_t0)
        keeper.record(float(mean_loss))
        if on_epoch is not None:
            on_epoch(params, opt_state, epochs_done, history)
        job.update(0.05 + 0.9 * (e + 1) / n_epochs)
        if keeper.should_stop() or job.stop_requested:
            Log.info(f"DeepLearning early stop at epoch {e + 1}")
            break
    return params, opt_state, history, epochs_done


def _make_optimizer(p):
    if p.adaptive_rate:
        return optax.adadelta(learning_rate=1.0, rho=p.rho, eps=p.epsilon)
    return optax.sgd(
        optax.exponential_decay(p.rate, 1000, p.rate_decay),
        momentum=p.momentum_start or None,
    )


def _resolved_dropout(p, n_hidden: int) -> tuple:
    """THE dropout-default rule (WithDropout activations default to 0.5) —
    single source for the network build and the model_summary table."""
    return tuple(
        p.hidden_dropout_ratios
        or ((0.5,) * n_hidden if "dropout" in p.activation.lower()
            else (0.0,) * n_hidden)
    )


def _make_mlp(p, n_out: int) -> _MLP:
    return _MLP(hidden=tuple(int(h) for h in p.hidden), n_out=n_out,
                activation=p.activation,
                dropout=_resolved_dropout(p, len(p.hidden)),
                input_dropout=p.input_dropout_ratio)


class DeepLearningModel(Model):
    algo = "deeplearning"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, _ = di.transform(frame)
        logits = self.output["apply_fn"](self.output["params"], X)
        if self.output.get("autoencoder"):
            return np.asarray(logits)[: frame.nrow]  # (n, expanded) recon
        if self.is_classifier:
            return np.asarray(jax.nn.softmax(logits, axis=1))[: frame.nrow]
        return np.asarray(logits[:, 0])[: frame.nrow]

    def predict(self, frame: Frame) -> Frame:
        if not self.output.get("autoencoder"):
            return super().predict(frame)
        # upstream autoencoder predict: one reconstr_* column per expanded
        # input feature (the standardized design-matrix space)
        recon = self._predict_raw(frame)
        names = [f"reconstr_{n}" for n in self.output["expanded_names"]]
        return Frame(
            [Vec.from_numpy(recon[:, j], "real") for j in range(recon.shape[1])],
            names,
        )

    def _recon_row_mse(self, frame: Frame, X=None, wmask=None):
        """Per-row reconstruction MSE in the standardized feature space —
        the ONE formula behind anomaly() and the AutoEncoder metrics.
        Pass (X, wmask) to reuse an existing design-matrix transform."""
        di: DataInfo = self.output["datainfo"]
        if X is None:
            X, wmask = di.transform(frame)
        recon = self.output["apply_fn"](self.output["params"], X)
        row_mse = np.asarray(jnp.mean((recon - X) ** 2, axis=1))[: frame.nrow]
        return row_mse, np.asarray(wmask)[: frame.nrow] > 0

    def _autoencoder_metrics(self, frame: Frame, X=None, wmask=None):
        """ModelMetricsAutoEncoder analog: reconstruction MSE on the
        standardized design matrix."""
        from h2o3_tpu.models.metrics import ModelMetrics

        row_mse, mask = self._recon_row_mse(frame, X, wmask)
        mse = float(row_mse[mask].mean()) if mask.any() else float("nan")
        return ModelMetrics("AutoEncoder", {"mse": mse, "rmse": float(np.sqrt(mse))})

    def model_performance(self, frame: Frame | None = None):
        if self.output.get("autoencoder"):
            return (self._autoencoder_metrics(frame) if frame is not None
                    else self.training_metrics)
        return super().model_performance(frame)

    def model_summary(self) -> list[dict]:
        """Upstream DL model_summary: the layer table."""
        p = self.params
        di: DataInfo = self.output["datainfo"]
        hidden = list(self.output.get("hidden") or p.hidden)
        n_out = (di.ncols_expanded if self.output.get("autoencoder")
                 else (self.nclasses if self.is_classifier else 1))
        dropout = list(_resolved_dropout(p, len(hidden)))
        rows = [{"layer": 1, "units": di.ncols_expanded, "type": "Input",
                 "dropout": p.input_dropout_ratio}]
        for i, h in enumerate(hidden):
            rows.append({"layer": i + 2, "units": int(h),
                         "type": p.activation, "dropout": dropout[i],
                         "l1": p.l1, "l2": p.l2})
        rows.append({"layer": len(hidden) + 2, "units": int(n_out),
                     "type": ("Linear" if (self.output.get("autoencoder")
                              or not self.is_classifier) else "Softmax")})
        return rows

    def anomaly(self, frame: Frame) -> Frame:
        """Per-row reconstruction MSE (``h2o.anomaly`` successor): the
        anomaly score in the standardized feature space."""
        if not self.output.get("autoencoder"):
            raise ValueError("anomaly() requires an autoencoder model")
        mse, _ = self._recon_row_mse(frame)
        return Frame([Vec.from_numpy(mse, "real")], ["Reconstruction.MSE"])


class DeepLearning(ModelBuilder):
    algo = "deeplearning"
    PARAMS_CLS = DeepLearningParams

    def _epoch_snapshot(self, key, di, prm, ost, done, hist, domain,
                        autoencoder=False, expanded=None) -> DeepLearningModel:
        """Interval-snapshot factory: params + optimizer accumulators +
        epoch count — everything the existing checkpoint-resume path reads
        (``apply_fn`` is rebuilt on load by persist._rebuild_deeplearning)."""
        p = self.params
        out = {
            "datainfo": di, "params": prm, "names": list(self._x),
            "hidden": list(p.hidden), "epochs_trained": done,
            "opt_state": ost, "response_domain": domain,
        }
        if autoencoder:
            out["autoencoder"] = True
            out["expanded_names"] = expanded
        m = DeepLearningModel(key, p, out)
        m.scoring_history = list(hist)
        return m

    def _build_autoencoder(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        """Autoencoder mode (upstream ``autoencoder=true`` /
        H2OAutoEncoderEstimator): reconstruct the standardized design
        matrix; no response. Same sync-SGD driver as the supervised path."""
        p: DeepLearningParams = self.params
        di = DataInfo.fit(train, self._x, standardize=p.standardize,
                          hash_buckets=p.hash_buckets)
        X, wmask = di.transform(train)
        w = wmask
        if p.weights_column:
            w = w * jnp.nan_to_num(train.vec(p.weights_column).data)
        w = jnp.asarray(np.asarray(w))

        D = di.ncols_expanded
        mlp = _make_mlp(p, n_out=D)
        seed = abs(p.seed) if p.seed and p.seed > 0 else 99
        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        params = mlp.init(init_key, jnp.zeros((1, D)), train=False)

        from h2o3_tpu.models.model_base import check_checkpoint_compat, resolve_checkpoint

        prior = resolve_checkpoint(p.checkpoint)
        start_epochs = 0
        if prior is not None:
            check_checkpoint_compat(
                prior, self,
                ("hidden", "activation", "standardize", "adaptive_rate",
                 "autoencoder"),
            )
            if prior.output["datainfo"].ncols_expanded != D:
                raise ValueError("checkpoint design-matrix width differs")
            start_epochs = int(prior.output.get("epochs_trained", 0))
            if p.epochs <= start_epochs:
                raise ValueError(
                    f"checkpoint continuation needs epochs > {start_epochs}"
                )
            params = prior.output["params"]

        tx = _make_optimizer(p)
        opt_state = tx.init(params)
        if prior is not None and prior.output.get("opt_state") is not None:
            opt_state = prior.output["opt_state"]

        l1, l2 = float(p.l1), float(p.l2)

        def loss_fn(prm, xb, yb, wb, kb):  # yb unused: the input IS the target
            recon = mlp.apply(prm, xb, train=True, rngs={"dropout": kb})
            ll = jnp.mean((recon - xb) ** 2, axis=1)
            loss = jnp.sum(wb * ll) / jnp.maximum(jnp.sum(wb), 1e-9)
            if l2:
                loss += l2 * 0.5 * sum(jnp.sum(q**2) for q in jax.tree.leaves(prm))
            if l1:
                loss += l1 * sum(jnp.sum(jnp.abs(q)) for q in jax.tree.leaves(prm))
            return loss

        def on_epoch(prm, ost, done, hist):
            self._export_interval_checkpoint(
                job, lambda key: self._epoch_snapshot(
                    key, di, prm, ost, done, hist, None,
                    autoencoder=True, expanded=di.coef_names(),
                )
            )
            faults.abort_check(self.algo, done)

        params, opt_state, history, epochs_done = _run_sync_sgd(
            job, p, loss_fn, tx, params, opt_state,
            X, jnp.zeros(train.npad, jnp.float32), w,
            train.nrow, train.npad, key, start_epochs, on_epoch=on_epoch,
        )

        apply_fn = jax.jit(lambda prm, xx: mlp.apply(prm, xx, train=False))
        out = {
            "datainfo": di, "params": params, "apply_fn": apply_fn,
            "names": list(self._x), "hidden": list(p.hidden),
            "epochs_trained": epochs_done, "opt_state": opt_state,
            "response_domain": None, "autoencoder": True,
            "expanded_names": di.coef_names(),
        }
        model = DeepLearningModel(DKV.make_key("dl"), p, out)
        model.scoring_history = history
        model.training_metrics = model._autoencoder_metrics(train, X, wmask)
        if valid is not None:
            model.validation_metrics = model._autoencoder_metrics(valid)
        return model

    def _validate(self, train: Frame, valid: Frame | None) -> None:
        p: DeepLearningParams = self.params
        if p.autoencoder:
            if p.nfolds and p.nfolds > 1:
                raise ValueError("autoencoder does not support cross-validation")
            return  # unsupervised: no response checks
        super()._validate(train, valid)

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: DeepLearningParams = self.params
        if p.autoencoder:
            return self._build_autoencoder(job, train, valid)
        yv = train.vec(p.response_column)
        classification = yv.is_categorical()
        K = yv.cardinality if classification else 1
        n_out = max(K, 1) if classification else 1

        di = DataInfo.fit(train, self._x, standardize=p.standardize,
                          hash_buckets=p.hash_buckets)
        X, wmask = di.transform(train)
        w = wmask
        if p.weights_column:
            w = w * jnp.nan_to_num(train.vec(p.weights_column).data)
        y_np = yv.to_numpy().astype(np.float64)
        ybuf = np.zeros(train.npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        okresp = np.ones(train.npad, np.float32)
        okresp[: train.nrow] = (
            (y_np >= 0) if classification else ~np.isnan(y_np)
        ).astype(np.float32)
        w = jnp.asarray(np.asarray(w) * okresp)
        y = jnp.asarray(ybuf)

        mlp = _make_mlp(p, n_out=n_out)
        seed = abs(p.seed) if p.seed and p.seed > 0 else 99
        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        params = mlp.init(init_key, jnp.zeros((1, di.ncols_expanded)), train=False)

        from h2o3_tpu.models.model_base import check_checkpoint_compat, resolve_checkpoint

        prior = resolve_checkpoint(p.checkpoint)
        start_epochs = 0
        if prior is not None:
            check_checkpoint_compat(
                prior, self, ("hidden", "activation", "standardize", "adaptive_rate")
            )
            if prior.output["datainfo"].ncols_expanded != di.ncols_expanded:
                raise ValueError("checkpoint design-matrix width differs")
            start_epochs = int(prior.output.get("epochs_trained", 0))
            if p.epochs <= start_epochs:
                raise ValueError(
                    f"checkpoint continuation needs epochs > {start_epochs}"
                )
            params = prior.output["params"]

        tx = _make_optimizer(p)
        opt_state = tx.init(params)
        if prior is not None and prior.output.get("opt_state") is not None:
            # carry the optimizer accumulators (adadelta rho-averages /
            # momentum + schedule counter) so continuation matches an
            # uninterrupted run, like GBM carries F and the split chain
            opt_state = prior.output["opt_state"]

        l1, l2 = float(p.l1), float(p.l2)
        use_ce = classification

        def loss_fn(prm, xb, yb, wb, kb):
            logits = mlp.apply(prm, xb, train=True, rngs={"dropout": kb})
            if use_ce:
                ll = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb.astype(jnp.int32)
                )
            else:
                ll = (logits[:, 0] - yb) ** 2
            loss = jnp.sum(wb * ll) / jnp.maximum(jnp.sum(wb), 1e-9)
            if l2:
                loss += l2 * 0.5 * sum(
                    jnp.sum(q**2) for q in jax.tree.leaves(prm)
                )
            if l1:
                loss += l1 * sum(
                    jnp.sum(jnp.abs(q)) for q in jax.tree.leaves(prm)
                )
            return loss

        domain = tuple(yv.domain) if classification else None

        def on_epoch(prm, ost, done, hist):
            self._export_interval_checkpoint(
                job, lambda key: self._epoch_snapshot(
                    key, di, prm, ost, done, hist, domain,
                )
            )
            faults.abort_check(self.algo, done)

        params, opt_state, history, epochs_done = _run_sync_sgd(
            job, p, loss_fn, tx, params, opt_state, X, y, w,
            train.nrow, train.npad, key, start_epochs, on_epoch=on_epoch,
        )
        apply_fn = jax.jit(lambda prm, xx: mlp.apply(prm, xx, train=False))

        out = {
            "datainfo": di,
            "params": params,
            "apply_fn": apply_fn,
            "names": list(self._x),
            "hidden": list(p.hidden),
            "epochs_trained": epochs_done,
            "opt_state": opt_state,
            "response_domain": tuple(yv.domain) if classification else None,
        }
        model = DeepLearningModel(DKV.make_key("dl"), p, out)
        model.scoring_history = history
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
