"""DeepLearning — successor of ``hex.deeplearning.DeepLearning`` /
``DeepLearningModel`` / ``Neurons`` [UNVERIFIED upstream paths, SURVEY.md
§2.2].

H2O trains a fully-connected MLP with **Hogwild!** lock-free async SGD
within a node plus periodic cross-node model averaging. The north star
(BASELINE.json) explicitly licenses replacing that with synchronous
data-parallel SGD: here each epoch is ONE compiled ``lax.scan`` over
minibatches of the row-sharded design matrix — flax MLP forward/backward on
the MXU, ADADELTA (h2o's adaptive_rate default) or momentum SGD from optax.
Parameter parity: hidden/activation (+dropout variants), input_dropout,
l1/l2, adaptive-rate rho/epsilon, rate/rate_decay, standardize, early
stopping. Deviation noted: ``mini_batch_size`` defaults to 32 (h2o's
online default of 1 serializes the MXU for no accuracy gain).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame, Vec
from h2o3_tpu.models.datainfo import DataInfo
from h2o3_tpu.models.model_base import (
    CommonParams,
    Model,
    ModelBuilder,
    ScoreKeeper,
)
from h2o3_tpu.utils import faults
from h2o3_tpu.utils import metrics as _mx
from h2o3_tpu.utils.log import Log

_DL_EPOCHS = _mx.counter("dl_epochs_total", "DeepLearning epochs executed")
_DL_EPOCH_SECONDS = _mx.histogram(
    "dl_epoch_seconds", "per-epoch wall time of the sync-SGD driver")
# host dispatches issued by the epoch driver (the epoch-chunk acceptance
# metric: O(epochs) per-epoch vs O(epochs/K) chunked) and program-cache
# traffic for the chunk programs — BUILD_STATS-style contract counters
_DL_DISPATCHES = _mx.counter(
    "dl_dispatches_total",
    "device-program launches issued by the DeepLearning epoch driver",
    always=True)
_DL_COMPILED = _mx.counter(
    "dl_programs_compiled_total",
    "DeepLearning epoch-chunk program cache misses", always=True)
_DL_HITS = _mx.counter(
    "dl_program_cache_hits_total",
    "DeepLearning epoch-chunk program cache hits (same shape bucket, no "
    "recompile)", always=True)
# the PR-5 collective byte family grows DL phases (dl_grad_reduce = the
# per-minibatch gradient psum_scatter — or the replicated allreduce volume
# on the unsharded lane — dl_param_gather = the all_gather of updated
# parameter shards); replication-volume model, tallied per dispatch
_COLL_BYTES = _mx.counter(
    "tree_collective_bytes_total",
    "per-device collective payload bytes moved by tree builds (replication-"
    "volume model), by phase", always=True)

# Fallback observability (ISSUE 15): trainings that WANT the sharded
# gradient lane (knob on, >1-device mesh) but drop to the replicated
# reduce for a structural reason. Dropout is no longer one — the dropout
# key folds the shard index per device (see _dl_chunk_program) — leaving
# batch divisibility and non-elementwise optimizer state.
_DL_SHARD_FALLBACKS = _mx.counter(
    "dl_shard_fallbacks_total",
    "DeepLearning trainings that fell back from the sharded-gradient lane "
    "while the knob was on and the mesh had >1 device, by structural "
    "reason", always=True)

# epoch-chunk program cache: (shape bucket, net/optimizer descriptor,
# lanes, mesh, backend) -> compiled chunk
_DL_PROGRAMS: dict = {}


def _dl_epoch_chunk(p) -> int:
    """Epochs folded into one compiled dispatch (H2O3_TPU_DL_EPOCH_CHUNK).

    Clamped to 1 whenever per-epoch boundaries are load-bearing: interval
    checkpoints (export_checkpoints_dir — PR-2 snapshots must land at every
    epoch), epoch-loss early stopping (stopping_rounds), or armed fault
    injection (the chaos suite aborts at exact epoch counts)."""
    from h2o3_tpu import config

    raw = config.get("H2O3_TPU_DL_EPOCH_CHUNK").strip().lower()
    k = int(raw) if raw.isdigit() else 8
    if (getattr(p, "export_checkpoints_dir", None)
            or (p.stopping_rounds or 0) > 0 or faults.armed()):
        return 1
    return max(k, 1)


def _flat_state_ok(opt_state, params) -> bool:
    """True iff every optimizer-state field is parameter-shaped (one array
    per param leaf, elementwise semantics) — the eligibility gate for the
    sharded-gradient lane, which runs the optimizer on 1/P slices of the
    FLATTENED parameter vector. Adadelta qualifies; a schedule's scalar
    step counter does not (its update is not elementwise in the flat
    view)."""
    pl = jax.tree.leaves(params)
    sl = jax.tree.leaves(opt_state)
    n = len(pl)
    if n == 0 or len(sl) % n != 0:
        return False
    return all(s.shape == pl[i % n].shape for i, s in enumerate(sl))


def _dl_grad_shard(p, dropout, input_dropout, batch: int, opt_ok: bool) -> bool:
    """Sharded minibatch gradient reduction (H2O3_TPU_DL_GRAD_SHARD):
    psum_scatter the flat gradient, update only the local parameter shard,
    all_gather the updated params — instead of the replicated
    allreduce+full-update. Eligible when the mesh has >1 device, the batch
    splits evenly over it and the optimizer state is elementwise. Dropout
    composes since ISSUE 15: each device folds its shard index into the
    minibatch dropout key (``H2O3_TPU_DL_GRAD_SHARD=ctl`` is the matching
    replicated parity-control lane — see :func:`_dl_dropout_ctl`).
    Structural ineligibility tallies ``dl_shard_fallbacks_total``."""
    from h2o3_tpu import config
    from h2o3_tpu.parallel.mesh import n_shards

    raw = config.get("H2O3_TPU_DL_GRAD_SHARD").strip().lower()
    if raw in ("0", "ctl"):
        return False
    n_sh = n_shards()
    if n_sh <= 1:
        return False
    ok = batch % n_sh == 0 and opt_ok
    if not ok:
        _DL_SHARD_FALLBACKS.inc(
            reason="batch_indivisible" if batch % n_sh else "opt_state")
    return ok


def _dl_dropout_ctl(p, dropout, input_dropout) -> int:
    """Shard count for the ``H2O3_TPU_DL_GRAD_SHARD=ctl`` parity-control
    lane: the REPLICATED trainer draws its dropout masks in n_shards
    contiguous batch chunks with the sharded lane's exact per-chunk key
    folds, so a ctl run is the trajectory-parity control for the sharded
    dropout run (same masks, replicated math). 0 = not the ctl lane or no
    dropout to control for."""
    from h2o3_tpu import config
    from h2o3_tpu.parallel.mesh import n_shards

    raw = config.get("H2O3_TPU_DL_GRAD_SHARD").strip().lower()
    if raw != "ctl":
        return 0
    if float(input_dropout) == 0.0 and all(float(d) == 0.0 for d in dropout):
        return 0  # no masks to align — plain replicated lane
    return n_shards()


def _state_to_flat(opt_state, params, tx, fpad: int):
    """Standard (params-structured) optimizer state -> the state of the
    same optimizer over the zero-padded FLAT parameter vector. Field order
    follows ``jax.tree.leaves``; padded tail entries are zero and stay zero
    (zero gradients under an elementwise transform). Inverse of
    :func:`_state_from_flat`; only called when :func:`_flat_state_ok`."""
    pl = jax.tree.leaves(params)
    n = len(pl)
    sl = jax.tree.leaves(opt_state)
    fields = []
    for i in range(0, len(sl), n):
        flat = jnp.concatenate([jnp.ravel(a) for a in sl[i:i + n]])
        fields.append(jnp.pad(flat, (0, fpad - flat.size)))
    ref = jax.tree.structure(tx.init(jnp.zeros(fpad, jnp.float32)))
    return jax.tree.unflatten(ref, fields)


def _state_from_flat(flat_state, unravel, n_real: int):
    """Flat optimizer state back to the standard params-structured form
    (what checkpoints serialize and the unsharded lane consumes)."""
    return jax.tree.map(lambda leaf: unravel(leaf[:n_real]), flat_state)


@dataclass
class DeepLearningParams(CommonParams):
    hidden: Sequence[int] = field(default_factory=lambda: (200, 200))
    epochs: float = 10.0
    activation: str = "Rectifier"
    input_dropout_ratio: float = 0.0
    hidden_dropout_ratios: Sequence[float] | None = None
    l1: float = 0.0
    l2: float = 0.0
    adaptive_rate: bool = True
    rho: float = 0.99
    epsilon: float = 1e-8
    rate: float = 0.005
    rate_decay: float = 1.0
    momentum_start: float = 0.0
    mini_batch_size: int = 32
    standardize: bool = True
    loss: str = "Automatic"
    reproducible: bool = True  # sync SGD is deterministic by construction
    autoencoder: bool = False  # reconstruct inputs; y is ignored
    # feature hashing for Criteo-class cardinalities (datainfo.py)
    hash_buckets: int | None = None


class _MLP(nn.Module):
    hidden: tuple
    n_out: int
    activation: str
    dropout: tuple
    input_dropout: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = {
            "rectifier": nn.relu,
            "rectifierwithdropout": nn.relu,
            "tanh": nn.tanh,
            "tanhwithdropout": nn.tanh,
            "maxout": nn.relu,  # maxout approximated [deviation noted]
        }[self.activation.lower()]
        if self.input_dropout > 0:
            x = nn.Dropout(self.input_dropout, deterministic=not train)(x)
        for i, h in enumerate(self.hidden):
            x = nn.Dense(h)(x)
            x = act(x)
            if self.dropout[i] > 0:
                x = nn.Dropout(self.dropout[i], deterministic=not train)(x)
        return nn.Dense(self.n_out)(x)




def _dl_chunk_program(desc, mlp, tx, kind: str, batch: int, npad: int,
                      n_chunk: int, shard_on: bool, unravel=None,
                      n_real: int = 0, fpad: int = 0, ctl_shards: int = 0):
    """Build (or fetch) the compiled K-epochs-per-dispatch training chunk.

    One program runs ``n_chunk`` whole epochs: an outer fori over the
    host-precomputed shuffle permutations (stacked ``(K, npad)`` — the
    permutation RNG stays host-side so trajectories are bit-identical to
    the per-epoch path), an inner fori over minibatches with a DYNAMIC trip
    count (row-count variation inside a shape bucket never recompiles), the
    dropout RNG threading through the carry exactly as the per-epoch path
    split it. ``params``/``opt_state`` are donated — chunk d+1 reuses chunk
    d's buffers with no copies.

    On the sharded lane (``shard_on``) params/opt_state are flat
    ``(fpad,)`` vectors: each device grads its local batch rows, the flat
    gradient ends in a ``psum_scatter`` (each device keeps 1/P), the
    elementwise optimizer updates only that shard, and one ``all_gather``
    republishes the updated parameters for the next forward. With dropout
    active (ISSUE 15), each device folds its flat shard index into the
    minibatch dropout key before the forward — batch rows are contiguous
    per shard, so a replicated trainer drawing its masks in the same
    per-chunk folds reproduces the identical masks: that is the
    ``ctl_shards`` lane (``H2O3_TPU_DL_GRAD_SHARD=ctl``), the
    trajectory-parity control for the sharded dropout run.
    """
    import jax.tree_util as jtu

    from h2o3_tpu.parallel.mesh import (
        col_axis_name, get_mesh, mesh_key, n_col_shards, row_axes, row_pspec,
        shard_map,
    )
    from jax.sharding import PartitionSpec as Spec

    key = ("dl_chunk", desc, batch, npad, n_chunk, bool(shard_on),
           int(ctl_shards), mesh_key(), jax.default_backend())
    fn = _DL_PROGRAMS.get(key)
    if fn is not None:
        _DL_HITS.inc()
        return fn
    _DL_COMPILED.inc()

    def row_loss(prm, xb, yb, kb):
        out = mlp.apply(prm, xb, train=True, rngs={"dropout": kb})
        if kind == "ce":
            return optax.softmax_cross_entropy_with_integer_labels(
                out, yb.astype(jnp.int32)
            )
        if kind == "mse":
            return (out[:, 0] - yb) ** 2
        return jnp.mean((out - xb) ** 2, axis=1)  # recon: the input IS the target

    def penalties(prm, l1, l2):
        # written unconditionally with dynamic scalars: +0.0 when a knob is
        # zero, which leaves loss AND gradient bits identical to the old
        # `if l2:` closures while letting one program serve every (l1, l2)
        pen = l2 * 0.5 * sum(jnp.sum(q**2) for q in jax.tree.leaves(prm))
        return pen + l1 * sum(jnp.sum(jnp.abs(q)) for q in jax.tree.leaves(prm))

    def row_loss_ctl(prm, xb, yb, kb):
        """The ctl parity lane's row loss: the SAME masks as the sharded
        lane — the batch in ``ctl_shards`` contiguous chunks, chunk d's
        dropout drawn from fold_in(kb, d), vmapped (identical bits to
        per-chunk applies)."""
        D = xb.shape[1]
        xbr = xb.reshape(ctl_shards, batch // ctl_shards, D)
        ybr = yb.reshape(ctl_shards, batch // ctl_shards)
        keys = jax.vmap(lambda i: jax.random.fold_in(kb, i))(
            jnp.arange(ctl_shards, dtype=jnp.int32))
        ll = jax.vmap(row_loss, in_axes=(None, 0, 0, 0))(prm, xbr, ybr, keys)
        return ll.reshape(batch)

    def loss_fn(prm, xb, yb, wb, kb, l1, l2):
        rl = row_loss_ctl if ctl_shards > 1 else row_loss
        ll = rl(prm, xb, yb, kb)
        loss = jnp.sum(wb * ll) / jnp.maximum(jnp.sum(wb), 1e-9)
        return loss + penalties(prm, l1, l2)

    has_drop = float(mlp.input_dropout) > 0 or any(
        float(d) > 0 for d in mlp.dropout)

    if shard_on:
        mesh = get_mesh()
        n_sh = int(mesh.devices.size)
        cax = col_axis_name(mesh)
        raxes = row_axes(mesh)
        fb = fpad // n_col_shards(mesh)

        def shard_step(prm_flat, ost, xb, yb, wb, bk, l1, l2):
            def local(prm_flat, ost_l, xb_l, yb_l, wb_l, bk, l1, l2):
                # dropout composes with sharding (ISSUE 15): fold the FLAT
                # row-shard index into the minibatch key so each device
                # draws its own rows' masks — shard-major order matches
                # row_axes, so the ctl lane's per-chunk folds reproduce
                # the identical mask sequence. No-dropout nets skip the
                # fold: their traced program stays byte-identical
                if has_drop:
                    sidx = jax.lax.axis_index(raxes[0])
                    for a in raxes[1:]:
                        sidx = sidx * mesh.shape[a] + jax.lax.axis_index(a)
                    bk = jax.random.fold_in(bk, sidx)

                def wsum_loss(pf):
                    prm = unravel(pf[:n_real])
                    return jnp.sum(wb_l * row_loss(prm, xb_l, yb_l, bk))

                lsum, g = jax.value_and_grad(wsum_loss)(prm_flat)
                # the flat-gradient reduce rides the collective lane
                # (ops/collectives.py): block-quantized with a residual-
                # correction pass when on — the optimizer consumes the
                # shard directly — stock psum_scatter bit-for-bit when off.
                # On a 2-D mesh the wrapper reduces the rows axis exactly
                # first and param shards live on the COLS axis (replicated
                # across rows groups — identical updates by construction)
                from h2o3_tpu.ops import collectives

                gs = collectives.psum_scatter(
                    g, n_dev=n_sh, passes=2, mesh=mesh)
                wsum = collectives.exact_psum(jnp.sum(wb_l), mesh)
                d = jax.lax.axis_index(cax)
                my = jax.lax.dynamic_slice(prm_flat, (d * fb,), (fb,))
                gshard = (gs / jnp.maximum(wsum, 1e-9)
                          + l2 * my + l1 * jnp.sign(my))
                upd, ost_l = tx.update(gshard, ost_l, my)
                my = optax.apply_updates(my, upd)
                prm_new = jax.lax.all_gather(
                    my, cax, axis=0, tiled=True)
                loss = (collectives.exact_psum(lsum, mesh)
                        / jnp.maximum(wsum, 1e-9)
                        + penalties(prm_flat[:n_real], l1, l2))
                return loss, prm_new, ost_l

            rspec = row_pspec(mesh)
            ost_spec = jtu.tree_map(lambda _: Spec(cax), ost)
            return shard_map(
                local, mesh,
                in_specs=(Spec(), ost_spec, row_pspec(mesh, ndim=2),
                          rspec, rspec, Spec(), Spec(),
                          Spec()),
                out_specs=(Spec(), Spec(), ost_spec),
                check_vma=False,
            )(prm_flat, ost, xb, yb, wb, bk, l1, l2)

    def chunk(params, opt_state, X, y, w, perms, key, nbatch, l1, l2,
              slot_mask):
        D = X.shape[1]

        def epoch_body(e, c):
            prm, ost, key, losses = c
            perm = perms[e]
            Xp, yp, wp = X[perm], y[perm], w[perm] * slot_mask
            key, dkey = jax.random.split(key)

            def step(i, sc):
                prm, ost, k, loss_sum = sc
                k, bk = jax.random.split(k)
                start = i * batch
                xb = jax.lax.dynamic_slice(Xp, (start, 0), (batch, D))
                yb = jax.lax.dynamic_slice(yp, (start,), (batch,))
                wb = jax.lax.dynamic_slice(wp, (start,), (batch,))
                if shard_on:
                    loss, prm, ost = shard_step(
                        prm, ost, xb, yb, wb, bk, l1, l2)
                else:
                    loss, g = jax.value_and_grad(loss_fn)(
                        prm, xb, yb, wb, bk, l1, l2)
                    upd, ost = tx.update(g, ost, prm)
                    prm = optax.apply_updates(prm, upd)
                return (prm, ost, k, loss_sum + loss)

            prm, ost, _, loss_sum = jax.lax.fori_loop(
                0, nbatch, step, (prm, ost, dkey, jnp.float32(0.0)))
            losses = losses.at[e].set(loss_sum / nbatch)
            return (prm, ost, key, losses)

        params, opt_state, key, losses = jax.lax.fori_loop(
            0, n_chunk, epoch_body,
            (params, opt_state, key, jnp.zeros(n_chunk, jnp.float32)))
        return params, opt_state, key, losses

    fn = jax.jit(chunk, donate_argnums=(0, 1))
    _DL_PROGRAMS[key] = fn
    return fn


def _run_sync_sgd(job, p, mlp, kind, tx, params, opt_state, X, y, w,
                  nrow: int, npad: int, key, start_epochs: int = 0,
                  on_epoch=None):
    """The shared sync-SGD epoch driver for both supervised and autoencoder
    training: permutation shuffling, epoch-chunk compiled loops
    (H2O3_TPU_DL_EPOCH_CHUNK) with donated (params, opt_state) buffers,
    epoch-loss early stopping, checkpoint RNG alignment. ``kind`` selects
    the per-row objective ('ce' | 'mse' | 'recon'). ``on_epoch(params,
    opt_state, epochs_done, history)`` fires at every chunk boundary — with
    checkpoints/faults/early-stopping active the chunk clamps to one epoch,
    so that IS every epoch boundary. Returns (params, opt_state, history,
    epochs_done)."""
    import time as _time

    from h2o3_tpu.frame.chunkstore import ChunkStore
    from h2o3_tpu.parallel.mesh import n_shards, pad_flat_to_shards

    if isinstance(X, ChunkStore):
        return _run_sync_sgd_streamed(
            job, p, mlp, kind, tx, params, opt_state, X, nrow, key,
            start_epochs, on_epoch,
        )

    batch = min(int(p.mini_batch_size), npad)
    nbatch = max(1, nrow // batch)
    # padded permutation slots alias row 0 — a SLOT mask zeroes their weight
    # so a final partial batch cannot over-count real rows (nrow < batch)
    slot_mask = jnp.asarray((np.arange(npad) < nrow).astype(np.float32))
    l1, l2 = jnp.float32(p.l1), jnp.float32(p.l2)

    chunk_k = _dl_epoch_chunk(p)
    dropout = _resolved_dropout(p, len(p.hidden))
    shard_on = _dl_grad_shard(
        p, dropout, p.input_dropout_ratio, batch, _flat_state_ok(opt_state, params)
    )
    ctl = _dl_dropout_ctl(p, dropout, p.input_dropout_ratio)
    if ctl and batch % ctl:
        ctl = 0  # the sharded lane it controls for would be ineligible too
    n_sh = n_shards()
    # the FULL network + optimizer identity: n_out matters even at equal
    # hidden/width (a cached program's closed-over mlp bakes the output
    # head), and every optimizer hyper is baked into tx's update closure
    desc = (tuple(int(h) for h in mlp.hidden), mlp.activation.lower(),
            tuple(mlp.dropout), float(mlp.input_dropout), int(mlp.n_out),
            kind, X.shape[1],
            bool(p.adaptive_rate), float(p.rho), float(p.epsilon),
            float(p.rate), float(p.rate_decay), float(p.momentum_start or 0))

    unravel = None
    n_real = fpad = 0
    if shard_on:
        from jax.flatten_util import ravel_pytree

        flat, unravel = ravel_pytree(params)
        n_real = int(flat.size)
        fpad = pad_flat_to_shards(n_real)
        params = jnp.pad(flat, (0, fpad - n_real))
        opt_state = _state_to_flat(opt_state, unravel(flat), tx, fpad)

    # epoch-level stopping tracks the (always smaller-is-better) training
    # loss; the resolved stopping_metric drives final scoring only
    keeper = ScoreKeeper(p.stopping_rounds, p.stopping_tolerance, False)
    seed = abs(p.seed) if p.seed and p.seed > 0 else 99
    rng = np.random.default_rng(seed)
    history = []
    n_epochs = max(1, int(np.ceil(p.epochs)))
    for _ in range(start_epochs):  # continuation: keep the epoch RNG
        rng.permutation(nrow)  # stream aligned with an
        key, _ = jax.random.split(key)  # uninterrupted run
    epochs_done = start_epochs

    # modeled per-batch collective volume, per lane: sharded = the 1/P
    # gradient scatter (through the quantized collective lane when on —
    # wire bytes + residual pass — exact f32 otherwise) + the exact wsum
    # psum + the exact full param gather; unsharded = the full replicated
    # gradient reduce (XLA-inserted — the lane cannot intercept it, exact
    # by construction). Zero on a 1-device mesh.
    coll = {}
    if n_sh > 1:
        from h2o3_tpu.ops.collectives import modeled_reduce_bytes

        n_param = n_real if shard_on else sum(
            int(np.prod(q.shape)) for q in jax.tree.leaves(params))
        if shard_on:
            reduce_lanes = dict(modeled_reduce_bytes(fpad, n_sh, passes=2))
            reduce_lanes["exact"] = reduce_lanes.get("exact", 0.0) + 4.0
            coll = {"dl_grad_reduce": reduce_lanes,
                    "dl_param_gather": {"exact": fpad * 4.0}}
        else:
            coll = {"dl_grad_reduce": {"exact": n_param * 4.0}}

    e = start_epochs
    stopped = False
    while e < n_epochs and not stopped:
        k_i = min(chunk_k, n_epochs - e)
        _ep_t0 = _time.perf_counter()
        perms = np.zeros((k_i, npad), np.int64)
        for j in range(k_i):
            perms[j, :nrow] = rng.permutation(nrow)
        prog = _dl_chunk_program(
            desc, mlp, tx, kind, batch, npad, k_i, shard_on,
            unravel=unravel, n_real=n_real, fpad=fpad, ctl_shards=ctl,
        )
        _DL_DISPATCHES.inc()
        from h2o3_tpu.utils import flightrec as _fr

        with _fr.dispatch("dl_chunk", epochs=int(k_i), rows=int(npad)):
            params, opt_state, key, losses = prog(
                params, opt_state, X, y, w, jnp.asarray(perms), key,
                jnp.int32(nbatch), l1, l2, slot_mask,
            )
            losses = np.asarray(losses, np.float64)  # syncs the chunk's work
        _dt = _time.perf_counter() - _ep_t0
        for j in range(k_i):
            epochs_done = e + j + 1
            history.append({"epoch": epochs_done, "loss": float(losses[j])})
            _DL_EPOCHS.inc()
            _DL_EPOCH_SECONDS.observe(_dt / k_i)
            keeper.record(float(losses[j]))
        for ph, lanes in coll.items():
            for lane, nb in lanes.items():
                if nb:
                    _COLL_BYTES.inc(nb * k_i * nbatch, phase=ph)
                    _COLL_BYTES.inc(nb * k_i * nbatch, phase=ph, lane=lane)
        if on_epoch is not None:
            if shard_on:
                on_epoch(unravel(params[:n_real]),
                         _state_from_flat(opt_state, unravel, n_real),
                         epochs_done, history)
            else:
                on_epoch(params, opt_state, epochs_done, history)
        job.update(0.05 + 0.9 * epochs_done / n_epochs)
        e += k_i
        if keeper.should_stop() or job.stop_requested:
            Log.info(f"DeepLearning early stop at epoch {epochs_done}")
            stopped = True
    if shard_on:
        params = unravel(params[:n_real])
        opt_state = _state_from_flat(opt_state, unravel, n_real)
    return params, opt_state, history, epochs_done


def _run_sync_sgd_streamed(job, p, mlp, kind, tx, params, opt_state, store,
                           nrow: int, key, start_epochs: int = 0,
                           on_epoch=None):
    """Out-of-core epoch driver (ISSUE 11): one epoch = one pass over the
    ChunkStore's row blocks, each block running the EXISTING compiled
    chunk program (one-epoch form) on its streamed (X, y, w) lanes while
    the next block's transfer rides behind it. Shuffling is within-block —
    the documented deviation from the resident global shuffle (frames that
    fit the window never reach this driver, so the bit-parity pins hold on
    the resident path). params/opt_state stay donated across block
    dispatches; epoch-loss early stopping and checkpoint cadence match the
    resident driver's."""
    import time as _time

    from h2o3_tpu.parallel.mesh import n_shards, pad_flat_to_shards

    blk_rows = store.block_rows
    batch = min(int(p.mini_batch_size), blk_rows)
    l1, l2 = jnp.float32(p.l1), jnp.float32(p.l2)
    dropout = _resolved_dropout(p, len(p.hidden))
    shard_on = _dl_grad_shard(
        p, dropout, p.input_dropout_ratio, batch,
        _flat_state_ok(opt_state, params),
    )
    ctl = _dl_dropout_ctl(p, dropout, p.input_dropout_ratio)
    if ctl and batch % ctl:
        ctl = 0
    n_sh = n_shards()
    D = store.lane("X").shape[1]
    desc = (tuple(int(h) for h in mlp.hidden), mlp.activation.lower(),
            tuple(mlp.dropout), float(mlp.input_dropout), int(mlp.n_out),
            kind, D,
            bool(p.adaptive_rate), float(p.rho), float(p.epsilon),
            float(p.rate), float(p.rate_decay), float(p.momentum_start or 0))

    unravel = None
    n_real = fpad = 0
    if shard_on:
        from jax.flatten_util import ravel_pytree

        flat, unravel = ravel_pytree(params)
        n_real = int(flat.size)
        fpad = pad_flat_to_shards(n_real)
        params = jnp.pad(flat, (0, fpad - n_real))
        opt_state = _state_to_flat(opt_state, unravel(flat), tx, fpad)

    keeper = ScoreKeeper(p.stopping_rounds, p.stopping_tolerance, False)
    seed = abs(p.seed) if p.seed and p.seed > 0 else 99
    rng = np.random.default_rng(seed)
    history = []
    n_epochs = max(1, int(np.ceil(p.epochs)))
    real = [max(min(store.span(bi)[1], nrow) - store.span(bi)[0], 0)
            for bi in range(store.n_blocks)]
    for _ in range(start_epochs):  # continuation: keep the RNG streams
        for bi in range(store.n_blocks):  # aligned with an uninterrupted
            if real[bi]:  # streamed run
                rng.permutation(real[bi])
        key, _ = jax.random.split(key)
    epochs_done = start_epochs

    coll = {}
    if n_sh > 1:
        from h2o3_tpu.ops.collectives import modeled_reduce_bytes

        n_param = n_real if shard_on else sum(
            int(np.prod(q.shape)) for q in jax.tree.leaves(params))
        if shard_on:
            reduce_lanes = dict(modeled_reduce_bytes(fpad, n_sh, passes=2))
            reduce_lanes["exact"] = reduce_lanes.get("exact", 0.0) + 4.0
            coll = {"dl_grad_reduce": reduce_lanes,
                    "dl_param_gather": {"exact": fpad * 4.0}}
        else:
            coll = {"dl_grad_reduce": {"exact": n_param * 4.0}}

    e = start_epochs
    stopped = False
    while e < n_epochs and not stopped:
        _ep_t0 = _time.perf_counter()
        key, ekey = jax.random.split(key)
        loss_sum, nb_sum = 0.0, 0
        for bi, blk in store.stream(("X", "y", "w")):
            if real[bi] == 0:
                continue  # all-padding tail block
            nbatch = max(1, real[bi] // batch)
            perm = np.zeros((1, blk_rows), np.int64)
            perm[0, : real[bi]] = rng.permutation(real[bi])
            slot = jnp.asarray(
                (np.arange(blk_rows) < real[bi]).astype(np.float32))
            prog = _dl_chunk_program(
                desc, mlp, tx, kind, batch, blk_rows, 1, shard_on,
                unravel=unravel, n_real=n_real, fpad=fpad, ctl_shards=ctl,
            )
            _DL_DISPATCHES.inc()
            from h2o3_tpu.utils import flightrec as _fr

            with _fr.dispatch("dl_chunk", block=int(bi),
                              rows=int(blk_rows)):
                params, opt_state, _k, losses = prog(
                    params, opt_state, blk["X"], blk["y"], blk["w"],
                    jnp.asarray(perm), jax.random.fold_in(ekey, bi),
                    jnp.int32(nbatch), l1, l2, slot,
                )
                loss_sum += float(np.asarray(losses)[0]) * nbatch
            nb_sum += nbatch
        epochs_done = e + 1
        loss = loss_sum / max(nb_sum, 1)
        history.append({"epoch": epochs_done, "loss": loss})
        _DL_EPOCHS.inc()
        _DL_EPOCH_SECONDS.observe(_time.perf_counter() - _ep_t0)
        keeper.record(loss)
        for ph, lanes in coll.items():
            for lane, nb in lanes.items():
                if nb:
                    _COLL_BYTES.inc(nb * nb_sum, phase=ph)
                    _COLL_BYTES.inc(nb * nb_sum, phase=ph, lane=lane)
        if on_epoch is not None:
            if shard_on:
                on_epoch(unravel(params[:n_real]),
                         _state_from_flat(opt_state, unravel, n_real),
                         epochs_done, history)
            else:
                on_epoch(params, opt_state, epochs_done, history)
        job.update(0.05 + 0.9 * epochs_done / n_epochs)
        e += 1
        if keeper.should_stop() or job.stop_requested:
            Log.info(f"DeepLearning early stop at epoch {epochs_done}")
            stopped = True
    if shard_on:
        params = unravel(params[:n_real])
        opt_state = _state_from_flat(opt_state, unravel, n_real)
    return params, opt_state, history, epochs_done


def _make_optimizer(p):
    if p.adaptive_rate:
        return optax.adadelta(learning_rate=1.0, rho=p.rho, eps=p.epsilon)
    return optax.sgd(
        optax.exponential_decay(p.rate, 1000, p.rate_decay),
        momentum=p.momentum_start or None,
    )


def _dl_pad_cols(d: int) -> int:
    """Bucketed input width for the supervised DL program keys: columns to
    a multiple of 4 (the PR-1 ladder) so AutoML/grid steps over
    near-identical frames share one compiled chunk program. Padded input
    columns are all-zero; the first Dense kernel's extra rows start at zero
    and receive zero gradients forever, so a bucketed build's trajectory is
    bit-identical to the exact-shape one."""
    from h2o3_tpu import config

    if not config.get_bool("H2O3_TPU_SHAPE_BUCKETS"):
        return d
    return -(-d // 4) * 4


def _repad_input_kernel(params, d_real: int, d_pad: int):
    """Zero-pad (or re-pad, on checkpoint resume across bucket settings)
    the first Dense kernel's input rows to ``d_pad``. Rows past ``d_real``
    are exactly zero by construction, so slicing them off is lossless."""
    import flax.core

    frozen = isinstance(params, flax.core.FrozenDict)
    prm = flax.core.unfreeze(params) if frozen else jax.tree.map(
        lambda x: x, params)
    k = prm["params"]["Dense_0"]["kernel"]
    if int(k.shape[0]) != d_pad:
        k = k[:d_real]
        k = jnp.pad(k, ((0, d_pad - d_real), (0, 0)))
        prm["params"]["Dense_0"]["kernel"] = k
    return flax.core.freeze(prm) if frozen else prm


def _resolved_dropout(p, n_hidden: int) -> tuple:
    """THE dropout-default rule (WithDropout activations default to 0.5) —
    single source for the network build and the model_summary table."""
    return tuple(
        p.hidden_dropout_ratios
        or ((0.5,) * n_hidden if "dropout" in p.activation.lower()
            else (0.0,) * n_hidden)
    )


def _make_mlp(p, n_out: int) -> _MLP:
    return _MLP(hidden=tuple(int(h) for h in p.hidden), n_out=n_out,
                activation=p.activation,
                dropout=_resolved_dropout(p, len(p.hidden)),
                input_dropout=p.input_dropout_ratio)


class DeepLearningModel(Model):
    algo = "deeplearning"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, _ = di.transform(frame)
        pad = int(self.output.get("input_pad") or 0)
        if pad:  # bucketed input width: scoring pads with the same zeros
            X = jnp.pad(X, ((0, 0), (0, pad)))
        logits = self.output["apply_fn"](self.output["params"], X)
        if self.output.get("autoencoder"):
            return np.asarray(logits)[: frame.nrow]  # (n, expanded) recon
        if self.is_classifier:
            return np.asarray(jax.nn.softmax(logits, axis=1))[: frame.nrow]
        return np.asarray(logits[:, 0])[: frame.nrow]

    def predict(self, frame: Frame) -> Frame:
        if not self.output.get("autoencoder"):
            return super().predict(frame)
        # upstream autoencoder predict: one reconstr_* column per expanded
        # input feature (the standardized design-matrix space)
        recon = self._predict_raw(frame)
        names = [f"reconstr_{n}" for n in self.output["expanded_names"]]
        return Frame(
            [Vec.from_numpy(recon[:, j], "real") for j in range(recon.shape[1])],
            names,
        )

    def _recon_row_mse(self, frame: Frame, X=None, wmask=None):
        """Per-row reconstruction MSE in the standardized feature space —
        the ONE formula behind anomaly() and the AutoEncoder metrics.
        Pass (X, wmask) to reuse an existing design-matrix transform."""
        di: DataInfo = self.output["datainfo"]
        if X is None:
            X, wmask = di.transform(frame)
        recon = self.output["apply_fn"](self.output["params"], X)
        row_mse = np.asarray(jnp.mean((recon - X) ** 2, axis=1))[: frame.nrow]
        return row_mse, np.asarray(wmask)[: frame.nrow] > 0

    def _autoencoder_metrics(self, frame: Frame, X=None, wmask=None):
        """ModelMetricsAutoEncoder analog: reconstruction MSE on the
        standardized design matrix."""
        from h2o3_tpu.models.metrics import ModelMetrics

        row_mse, mask = self._recon_row_mse(frame, X, wmask)
        mse = float(row_mse[mask].mean()) if mask.any() else float("nan")
        return ModelMetrics("AutoEncoder", {"mse": mse, "rmse": float(np.sqrt(mse))})

    def model_performance(self, frame: Frame | None = None):
        if self.output.get("autoencoder"):
            return (self._autoencoder_metrics(frame) if frame is not None
                    else self.training_metrics)
        return super().model_performance(frame)

    def model_summary(self) -> list[dict]:
        """Upstream DL model_summary: the layer table."""
        p = self.params
        di: DataInfo = self.output["datainfo"]
        hidden = list(self.output.get("hidden") or p.hidden)
        n_out = (di.ncols_expanded if self.output.get("autoencoder")
                 else (self.nclasses if self.is_classifier else 1))
        dropout = list(_resolved_dropout(p, len(hidden)))
        rows = [{"layer": 1, "units": di.ncols_expanded, "type": "Input",
                 "dropout": p.input_dropout_ratio}]
        for i, h in enumerate(hidden):
            rows.append({"layer": i + 2, "units": int(h),
                         "type": p.activation, "dropout": dropout[i],
                         "l1": p.l1, "l2": p.l2})
        rows.append({"layer": len(hidden) + 2, "units": int(n_out),
                     "type": ("Linear" if (self.output.get("autoencoder")
                              or not self.is_classifier) else "Softmax")})
        return rows

    def anomaly(self, frame: Frame) -> Frame:
        """Per-row reconstruction MSE (``h2o.anomaly`` successor): the
        anomaly score in the standardized feature space."""
        if not self.output.get("autoencoder"):
            raise ValueError("anomaly() requires an autoencoder model")
        mse, _ = self._recon_row_mse(frame)
        return Frame([Vec.from_numpy(mse, "real")], ["Reconstruction.MSE"])


class DeepLearning(ModelBuilder):
    algo = "deeplearning"
    PARAMS_CLS = DeepLearningParams

    def _plan_streamed(self, train: Frame, di, p, d_pad: int, ybuf, okresp):
        """ChunkStore of block design lanes for out-of-core epochs, or
        None for the resident path (autoencoder is excluded — its
        reconstruction target is the whole design; docs/MIGRATION.md
        fallback matrix)."""
        from h2o3_tpu.frame import chunkstore as cs

        if p.autoencoder:
            return None
        store = cs.ChunkStore.plan(train.npad, (d_pad + 2) * 4 + 8)
        if store is None:
            return None
        npad = train.npad
        Log.info(
            f"DeepLearning out-of-core streaming: {store.n_blocks} blocks "
            f"x {store.block_rows} rows, input width {d_pad}"
        )
        Xlane = store.add_empty("X", (npad, d_pad), np.float32)
        vmask = np.zeros(npad, np.float32)
        need = [c.name for c in di.columns if c.pair is None]
        for c in di.columns:
            if c.pair is not None:
                need += [nm for nm in c.pair if nm not in need]
        for bi in range(store.n_blocks):
            lo, hi = store.span(bi)
            bf = cs.host_block_frame(train, need, lo, hi)
            Xb, vb = di.transform(bf)
            Xlane[lo:hi, : di.ncols_expanded] = np.asarray(jax.device_get(Xb))
            vmask[lo:hi] = np.asarray(jax.device_get(vb))
        cs.release_frame_features(train, need)
        w_np = vmask
        if p.weights_column:
            w_np = w_np * np.nan_to_num(
                train.vec(p.weights_column).host_values().astype(np.float32))
        store.add("w", (w_np * okresp).astype(np.float32))
        store.add("y", np.asarray(ybuf, np.float32))
        return store

    def _streamed_metrics(self, model: "DeepLearningModel", store,
                          frame: Frame):
        """Training metrics from per-block forward passes over the store's
        design lanes — the resident design is never re-materialized."""
        from h2o3_tpu.models.model_base import _make_metrics

        parts = []
        for bi, blk in store.stream(("X",)):
            logits = model.output["apply_fn"](model.output["params"],
                                              blk["X"])
            if model.is_classifier:
                parts.append(np.asarray(jax.nn.softmax(logits, axis=1)))
            else:
                parts.append(np.asarray(logits[:, 0]))
        raw = np.concatenate(parts)[: frame.nrow]
        yh, wh = model._response_and_weights(frame)
        return _make_metrics(model, raw, yh, wh)

    def _epoch_snapshot(self, key, di, prm, ost, done, hist, domain,
                        autoencoder=False, expanded=None) -> DeepLearningModel:
        """Interval-snapshot factory: params + optimizer accumulators +
        epoch count — everything the existing checkpoint-resume path reads
        (``apply_fn`` is rebuilt on load by persist._rebuild_deeplearning)."""
        p = self.params
        out = {
            "datainfo": di, "params": prm, "names": list(self._x),
            "hidden": list(p.hidden), "epochs_trained": done,
            "opt_state": ost, "response_domain": domain,
        }
        if autoencoder:
            out["autoencoder"] = True
            out["expanded_names"] = expanded
        else:
            k0 = prm["params"]["Dense_0"]["kernel"]
            out["input_pad"] = int(k0.shape[0]) - di.ncols_expanded
        m = DeepLearningModel(key, p, out)
        m.scoring_history = list(hist)
        return m

    def _build_autoencoder(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        """Autoencoder mode (upstream ``autoencoder=true`` /
        H2OAutoEncoderEstimator): reconstruct the standardized design
        matrix; no response. Same sync-SGD driver as the supervised path."""
        p: DeepLearningParams = self.params
        di = DataInfo.fit(train, self._x, standardize=p.standardize,
                          hash_buckets=p.hash_buckets)
        X, wmask = di.transform(train)
        w = wmask
        if p.weights_column:
            w = w * jnp.nan_to_num(train.vec(p.weights_column).data)
        w = jnp.asarray(np.asarray(w))

        D = di.ncols_expanded
        mlp = _make_mlp(p, n_out=D)
        seed = abs(p.seed) if p.seed and p.seed > 0 else 99
        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        params = mlp.init(init_key, jnp.zeros((1, D)), train=False)

        from h2o3_tpu.models.model_base import check_checkpoint_compat, resolve_checkpoint

        prior = resolve_checkpoint(p.checkpoint)
        start_epochs = 0
        if prior is not None:
            check_checkpoint_compat(
                prior, self,
                ("hidden", "activation", "standardize", "adaptive_rate",
                 "autoencoder"),
            )
            if prior.output["datainfo"].ncols_expanded != D:
                raise ValueError("checkpoint design-matrix width differs")
            start_epochs = int(prior.output.get("epochs_trained", 0))
            if p.epochs <= start_epochs:
                raise ValueError(
                    f"checkpoint continuation needs epochs > {start_epochs}"
                )
            params = prior.output["params"]

        tx = _make_optimizer(p)
        opt_state = tx.init(params)
        if prior is not None and prior.output.get("opt_state") is not None:
            opt_state = prior.output["opt_state"]

        def on_epoch(prm, ost, done, hist):
            self._export_interval_checkpoint(
                job, lambda key: self._epoch_snapshot(
                    key, di, prm, ost, done, hist, None,
                    autoencoder=True, expanded=di.coef_names(),
                )
            )
            faults.die_check(self.algo)  # chaos: worker death at boundary
            faults.abort_check(self.algo, done)

        # autoencoder inputs are NOT shape-bucketed: the reconstruction
        # target is the input itself, so padded columns would enter the
        # per-row MSE mean (docs/MIGRATION.md fallback matrix)
        params, opt_state, history, epochs_done = _run_sync_sgd(
            job, p, mlp, "recon", tx, params, opt_state,
            X, jnp.zeros(train.npad, jnp.float32), w,
            train.nrow, train.npad, key, start_epochs, on_epoch=on_epoch,
        )

        apply_fn = jax.jit(lambda prm, xx: mlp.apply(prm, xx, train=False))
        out = {
            "datainfo": di, "params": params, "apply_fn": apply_fn,
            "names": list(self._x), "hidden": list(p.hidden),
            "epochs_trained": epochs_done, "opt_state": opt_state,
            "response_domain": None, "autoencoder": True,
            "expanded_names": di.coef_names(),
        }
        model = DeepLearningModel(DKV.make_key("dl"), p, out)
        model.scoring_history = history
        model.training_metrics = model._autoencoder_metrics(train, X, wmask)
        if valid is not None:
            model.validation_metrics = model._autoencoder_metrics(valid)
        return model

    def _validate(self, train: Frame, valid: Frame | None) -> None:
        p: DeepLearningParams = self.params
        if p.autoencoder:
            if p.nfolds and p.nfolds > 1:
                raise ValueError("autoencoder does not support cross-validation")
            return  # unsupervised: no response checks
        super()._validate(train, valid)

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: DeepLearningParams = self.params
        if p.autoencoder:
            return self._build_autoencoder(job, train, valid)
        yv = train.vec(p.response_column)
        classification = yv.is_categorical()
        K = yv.cardinality if classification else 1
        n_out = max(K, 1) if classification else 1

        di = DataInfo.fit(train, self._x, standardize=p.standardize,
                          hash_buckets=p.hash_buckets)
        # shape-bucket ladder on the input width (zero columns, proven
        # bit-inert via the zero-padded first kernel — _dl_pad_cols)
        D = di.ncols_expanded
        d_pad = _dl_pad_cols(D)
        y_np = yv.to_numpy().astype(np.float64)
        ybuf = np.zeros(train.npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        okresp = np.ones(train.npad, np.float32)
        okresp[: train.nrow] = (
            (y_np >= 0) if classification else ~np.isnan(y_np)
        ).astype(np.float32)

        # out-of-core streaming (ISSUE 11, frame/chunkstore.py): a design
        # matrix past the HBM window trains as row-block epochs — DL
        # already minibatches, so each block runs the existing chunk
        # program; shuffling is within-block (documented deviation).
        stream = self._plan_streamed(train, di, p, d_pad, ybuf, okresp)
        if stream is not None:
            X = stream
            w = jnp.asarray(stream.lane("w"))
            y = jnp.asarray(ybuf)
        else:
            X, wmask = di.transform(train)
            if d_pad > D:
                X = jnp.pad(X, ((0, 0), (0, d_pad - D)))
            w = wmask
            if p.weights_column:
                w = w * jnp.nan_to_num(train.vec(p.weights_column).data)
            w = jnp.asarray(np.asarray(w) * okresp)
            y = jnp.asarray(ybuf)

        mlp = _make_mlp(p, n_out=n_out)
        seed = abs(p.seed) if p.seed and p.seed > 0 else 99
        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        # init at the EXACT width (initializer fan-in must not see padding)
        # then zero-pad the first kernel's rows to the bucketed width
        params = mlp.init(init_key, jnp.zeros((1, di.ncols_expanded)), train=False)
        if d_pad > D:
            params = _repad_input_kernel(params, D, d_pad)

        from h2o3_tpu.models.model_base import check_checkpoint_compat, resolve_checkpoint

        prior = resolve_checkpoint(p.checkpoint)
        start_epochs = 0
        if prior is not None:
            check_checkpoint_compat(
                prior, self, ("hidden", "activation", "standardize", "adaptive_rate")
            )
            if prior.output["datainfo"].ncols_expanded != di.ncols_expanded:
                raise ValueError("checkpoint design-matrix width differs")
            start_epochs = int(prior.output.get("epochs_trained", 0))
            if p.epochs <= start_epochs:
                raise ValueError(
                    f"checkpoint continuation needs epochs > {start_epochs}"
                )
            params = _repad_input_kernel(prior.output["params"], D, d_pad)

        tx = _make_optimizer(p)
        opt_state = tx.init(params)
        if prior is not None and prior.output.get("opt_state") is not None:
            # carry the optimizer accumulators (adadelta rho-averages /
            # momentum + schedule counter) so continuation matches an
            # uninterrupted run, like GBM carries F and the split chain
            prior_ost = prior.output["opt_state"]
            shapes_ok = jax.tree.structure(prior_ost) == jax.tree.structure(
                opt_state
            ) and all(
                a.shape == b.shape
                for a, b in zip(jax.tree.leaves(prior_ost),
                                jax.tree.leaves(opt_state))
            )
            if shapes_ok:
                opt_state = prior_ost
            else:  # bucket-width change between runs: accumulators reset
                Log.warn(
                    "DeepLearning checkpoint optimizer state has a "
                    "different shape bucket; accumulators re-initialized"
                )

        domain = tuple(yv.domain) if classification else None

        def on_epoch(prm, ost, done, hist):
            self._export_interval_checkpoint(
                job, lambda key: self._epoch_snapshot(
                    key, di, prm, ost, done, hist, domain,
                )
            )
            faults.die_check(self.algo)  # chaos: worker death at boundary
            faults.abort_check(self.algo, done)

        params, opt_state, history, epochs_done = _run_sync_sgd(
            job, p, mlp, "ce" if classification else "mse", tx, params,
            opt_state, X, y, w,
            train.nrow, train.npad, key, start_epochs, on_epoch=on_epoch,
        )
        apply_fn = jax.jit(lambda prm, xx: mlp.apply(prm, xx, train=False))

        out = {
            "datainfo": di,
            "params": params,
            "apply_fn": apply_fn,
            "names": list(self._x),
            "hidden": list(p.hidden),
            "epochs_trained": epochs_done,
            "opt_state": opt_state,
            "input_pad": d_pad - D,
            "response_domain": tuple(yv.domain) if classification else None,
        }
        model = DeepLearningModel(DKV.make_key("dl"), p, out)
        model.scoring_history = history
        if stream is not None:
            # streamed scoring: never re-materialize the resident design
            model.training_metrics = self._streamed_metrics(model, stream, train)
            stream.close()
        else:
            model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
