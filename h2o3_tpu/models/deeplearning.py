"""DeepLearning — successor of ``hex.deeplearning.DeepLearning`` /
``DeepLearningModel`` / ``Neurons`` [UNVERIFIED upstream paths, SURVEY.md
§2.2].

H2O trains a fully-connected MLP with **Hogwild!** lock-free async SGD
within a node plus periodic cross-node model averaging. The north star
(BASELINE.json) explicitly licenses replacing that with synchronous
data-parallel SGD: here each epoch is ONE compiled ``lax.scan`` over
minibatches of the row-sharded design matrix — flax MLP forward/backward on
the MXU, ADADELTA (h2o's adaptive_rate default) or momentum SGD from optax.
Parameter parity: hidden/activation (+dropout variants), input_dropout,
l1/l2, adaptive-rate rho/epsilon, rate/rate_decay, standardize, early
stopping. Deviation noted: ``mini_batch_size`` defaults to 32 (h2o's
online default of 1 serializes the MXU for no accuracy gain).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from h2o3_tpu.cluster.job import Job
from h2o3_tpu.cluster.registry import DKV
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.datainfo import DataInfo
from h2o3_tpu.models.model_base import (
    CommonParams,
    Model,
    ModelBuilder,
    ScoreKeeper,
)
from h2o3_tpu.utils.log import Log


@dataclass
class DeepLearningParams(CommonParams):
    hidden: Sequence[int] = field(default_factory=lambda: (200, 200))
    epochs: float = 10.0
    activation: str = "Rectifier"
    input_dropout_ratio: float = 0.0
    hidden_dropout_ratios: Sequence[float] | None = None
    l1: float = 0.0
    l2: float = 0.0
    adaptive_rate: bool = True
    rho: float = 0.99
    epsilon: float = 1e-8
    rate: float = 0.005
    rate_decay: float = 1.0
    momentum_start: float = 0.0
    mini_batch_size: int = 32
    standardize: bool = True
    loss: str = "Automatic"
    reproducible: bool = True  # sync SGD is deterministic by construction


class _MLP(nn.Module):
    hidden: tuple
    n_out: int
    activation: str
    dropout: tuple
    input_dropout: float

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = {
            "rectifier": nn.relu,
            "rectifierwithdropout": nn.relu,
            "tanh": nn.tanh,
            "tanhwithdropout": nn.tanh,
            "maxout": nn.relu,  # maxout approximated [deviation noted]
        }[self.activation.lower()]
        if self.input_dropout > 0:
            x = nn.Dropout(self.input_dropout, deterministic=not train)(x)
        for i, h in enumerate(self.hidden):
            x = nn.Dense(h)(x)
            x = act(x)
            if self.dropout[i] > 0:
                x = nn.Dropout(self.dropout[i], deterministic=not train)(x)
        return nn.Dense(self.n_out)(x)


class DeepLearningModel(Model):
    algo = "deeplearning"

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        di: DataInfo = self.output["datainfo"]
        X, _ = di.transform(frame)
        logits = self.output["apply_fn"](self.output["params"], X)
        if self.is_classifier:
            return np.asarray(jax.nn.softmax(logits, axis=1))[: frame.nrow]
        return np.asarray(logits[:, 0])[: frame.nrow]


class DeepLearning(ModelBuilder):
    algo = "deeplearning"
    PARAMS_CLS = DeepLearningParams

    def _build(self, job: Job, train: Frame, valid: Frame | None) -> Model:
        p: DeepLearningParams = self.params
        yv = train.vec(p.response_column)
        classification = yv.is_categorical()
        K = yv.cardinality if classification else 1
        n_out = max(K, 1) if classification else 1

        di = DataInfo.fit(train, self._x, standardize=p.standardize)
        X, wmask = di.transform(train)
        w = wmask
        if p.weights_column:
            w = w * jnp.nan_to_num(train.vec(p.weights_column).data)
        y_np = yv.to_numpy().astype(np.float64)
        ybuf = np.zeros(train.npad, np.float32)
        ybuf[: train.nrow] = np.nan_to_num(y_np, nan=0.0)
        okresp = np.ones(train.npad, np.float32)
        okresp[: train.nrow] = (
            (y_np >= 0) if classification else ~np.isnan(y_np)
        ).astype(np.float32)
        w = jnp.asarray(np.asarray(w) * okresp)
        y = jnp.asarray(ybuf)

        dropout = tuple(
            p.hidden_dropout_ratios
            or ((0.5,) * len(p.hidden) if "dropout" in p.activation.lower() else (0.0,) * len(p.hidden))
        )
        mlp = _MLP(
            hidden=tuple(int(h) for h in p.hidden),
            n_out=n_out,
            activation=p.activation,
            dropout=dropout,
            input_dropout=p.input_dropout_ratio,
        )
        seed = abs(p.seed) if p.seed and p.seed > 0 else 99
        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        params = mlp.init(init_key, jnp.zeros((1, di.ncols_expanded)), train=False)

        from h2o3_tpu.models.model_base import check_checkpoint_compat, resolve_checkpoint

        prior = resolve_checkpoint(p.checkpoint)
        start_epochs = 0
        if prior is not None:
            check_checkpoint_compat(
                prior, self, ("hidden", "activation", "standardize", "adaptive_rate")
            )
            if prior.output["datainfo"].ncols_expanded != di.ncols_expanded:
                raise ValueError("checkpoint design-matrix width differs")
            start_epochs = int(prior.output.get("epochs_trained", 0))
            if p.epochs <= start_epochs:
                raise ValueError(
                    f"checkpoint continuation needs epochs > {start_epochs}"
                )
            params = prior.output["params"]

        if p.adaptive_rate:
            tx = optax.adadelta(learning_rate=1.0, rho=p.rho, eps=p.epsilon)
        else:
            tx = optax.sgd(
                optax.exponential_decay(p.rate, 1000, p.rate_decay),
                momentum=p.momentum_start or None,
            )
        opt_state = tx.init(params)
        if prior is not None and prior.output.get("opt_state") is not None:
            # carry the optimizer accumulators (adadelta rho-averages /
            # momentum + schedule counter) so continuation matches an
            # uninterrupted run, like GBM carries F and the split chain
            opt_state = prior.output["opt_state"]

        batch = int(p.mini_batch_size)
        npad = train.npad
        nbatch = max(1, train.nrow // batch)

        l1, l2 = float(p.l1), float(p.l2)
        use_ce = classification

        @jax.jit
        def epoch(params, opt_state, Xp, yp, wp, dkey):
            def loss_fn(prm, xb, yb, wb, kb):
                logits = mlp.apply(prm, xb, train=True, rngs={"dropout": kb})
                if use_ce:
                    ll = optax.softmax_cross_entropy_with_integer_labels(
                        logits, yb.astype(jnp.int32)
                    )
                else:
                    ll = (logits[:, 0] - yb) ** 2
                loss = jnp.sum(wb * ll) / jnp.maximum(jnp.sum(wb), 1e-9)
                if l2:
                    loss += l2 * 0.5 * sum(
                        jnp.sum(q**2) for q in jax.tree.leaves(prm)
                    )
                if l1:
                    loss += l1 * sum(
                        jnp.sum(jnp.abs(q)) for q in jax.tree.leaves(prm)
                    )
                return loss

            def step(carry, i):
                prm, ost, k = carry
                k, bk = jax.random.split(k)
                start = i * batch
                xb = jax.lax.dynamic_slice(Xp, (start, 0), (batch, Xp.shape[1]))
                yb = jax.lax.dynamic_slice(yp, (start,), (batch,))
                wb = jax.lax.dynamic_slice(wp, (start,), (batch,))
                loss, g = jax.value_and_grad(loss_fn)(prm, xb, yb, wb, bk)
                upd, ost = tx.update(g, ost, prm)
                prm = optax.apply_updates(prm, upd)
                return (prm, ost, k), loss

            (params, opt_state, _), losses = jax.lax.scan(
                step, (params, opt_state, dkey), jnp.arange(nbatch)
            )
            return params, opt_state, losses.mean()

        apply_fn = jax.jit(lambda prm, xx: mlp.apply(prm, xx, train=False))

        # epoch-level stopping tracks the (always smaller-is-better) training
        # loss; the resolved stopping_metric drives final scoring only
        keeper = ScoreKeeper(p.stopping_rounds, p.stopping_tolerance, False)
        rng = np.random.default_rng(seed)
        history = []
        n_epochs = max(1, int(np.ceil(p.epochs)))
        for _ in range(start_epochs):  # continuation: keep the epoch RNG
            rng.permutation(train.nrow)  # stream aligned with an
            key, _ = jax.random.split(key)  # uninterrupted run
        epochs_done = start_epochs
        for e in range(start_epochs, n_epochs):
            perm = np.zeros(npad, np.int64)
            perm[: train.nrow] = rng.permutation(train.nrow)
            perm_j = jnp.asarray(perm)
            Xp = X[perm_j]
            yp = y[perm_j]
            wp = w[perm_j]
            key, dkey = jax.random.split(key)
            params, opt_state, mean_loss = epoch(params, opt_state, Xp, yp, wp, dkey)
            epochs_done = e + 1
            history.append({"epoch": e + 1, "loss": float(mean_loss)})
            keeper.record(float(mean_loss))
            job.update(0.05 + 0.9 * (e + 1) / n_epochs)
            if keeper.should_stop() or job.stop_requested:
                Log.info(f"DeepLearning early stop at epoch {e + 1}")
                break

        out = {
            "datainfo": di,
            "params": params,
            "apply_fn": apply_fn,
            "names": list(self._x),
            "hidden": list(p.hidden),
            "epochs_trained": epochs_done,
            "opt_state": opt_state,
            "response_domain": tuple(yv.domain) if classification else None,
        }
        model = DeepLearningModel(DKV.make_key("dl"), p, out)
        model.scoring_history = history
        model.training_metrics = model._score_metrics(train)
        if valid is not None:
            model.validation_metrics = model._score_metrics(valid)
        return model
