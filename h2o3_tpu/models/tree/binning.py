"""Feature binning for histogram tree building — the quantile-bin successor
of ``hex.tree.DHistogram`` bin-edge derivation [UNVERIFIED upstream path,
SURVEY.md §2.2].

H2O re-derives per-(node,col) bin ranges from surviving rows at every level;
static quantile binning (the XGBoost-hist approach) computes edges ONCE from
global column quantiles and prebins every row to a uint8 code — trading
h2o's adaptive ranges for a single O(n) pass and a device-resident compressed
design matrix (the C1Chunk analog that actually pays on TPU: 1 byte/cell in
HBM, histograms indexed directly by code). SURVEY.md §7 flags AUC-parity as
the risk; with 255 quantile bins the split resolution exceeds h2o's default
nbins=20, and tests pin accuracy against sklearn GBMs.

Bin layout per column: code 0 = NA, codes 1..nbins = data bins.
Numeric: quantile buckets (edges stored for predict-time rebinning).
Categorical: code = category_id + 1; domains wider than 254 levels clamp the
tail into the last bin (h2o groups rare levels similarly at nbins_cats).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel.mesh import row_sharding

MAX_BINS = 255  # codes 1..255 fit uint8 with 0 reserved for NA


# ---------------------------------------------------------------------------
# shape-bucket ladder (H2O3_TPU_SHAPE_BUCKETS): AutoML/grid builds differ in
# data-dependent shapes (actual quantile-bin count, feature count after
# drops), and every distinct shape is a fresh multi-second XLA compile of the
# whole-tree program. Rounding bins/cols up to a coarse ladder collapses
# near-identical shapes onto one compiled program. The padding is inert by
# construction: padded bins are empty (every candidate split there fails
# min_rows and loses the argmax to a real bin), padded columns carry
# cols_enabled=0 and the NA code everywhere, and the column-sampling RNG is
# drawn at the REAL column count — so a bucketed build scores identically to
# an exact-shape build (pinned by tests).


def _buckets_enabled() -> bool:
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_SHAPE_BUCKETS")


def bucket_nbins(n_bins: int) -> int:
    """Histogram bin-axis bucket: next power of two (min 8, cap 256)."""
    if not _buckets_enabled() or n_bins >= 256:
        return n_bins
    b = 8
    while b < n_bins:
        b <<= 1
    return b


def bucket_cols(n_cols: int) -> int:
    """Feature-axis bucket: next multiple of 4 (min 4).

    Histogram cost is ∝ columns, so every padded column is pure overhead on
    every build that hits the program — a multiple-of-8 ladder costs the
    28-col headline +14% histogram work forever to save compiles it never
    needs. Multiple-of-4 keeps the compile-collapse for the odd widths
    AutoML feature-drops produce at ≤3 padded columns."""
    if not _buckets_enabled():
        return n_cols
    return max(4, -(-n_cols // 4) * 4)


@dataclass
class BinSpec:
    """Fitted binning for one frame's feature set."""

    names: list[str]
    is_cat: np.ndarray  # (C,) bool
    nbins: np.ndarray  # (C,) int, actual bin count per column (excl. NA bin)
    edges: np.ndarray  # (C, MAX_BINS-1) float32 right-inclusive bin edges, +inf padded
    cards: np.ndarray  # (C,) categorical cardinality (0 for numeric)
    domains: list | None = None  # train-time cat domains (for test adaptation)

    @property
    def ncols(self) -> int:
        return len(self.names)

    @property
    def max_bins(self) -> int:
        return int(self.nbins.max()) + 1  # +1 for the NA bin 0


_EDGE_PROG: dict = {}


def _device_quantile_edges(frame: Frame, names: list[str], nbins: int, sample: int):
    """Per-column quantile edges computed ON DEVICE — a 4 MB column pull over
    a tunneled TPU costs ~0.5 s, so fit_bins pulling every column dominated
    GBM build time; this pulls only (Cn, nbins-1) edges + counts (KBs)."""
    nrow = frame.nrow
    ns = min(nrow, sample)
    key = (nbins, ns, jax.default_backend())
    prog = _EDGE_PROG.get(key)
    if prog is None:

        def run(X):  # (ns, Cn)
            xs = jnp.sort(X, axis=0)  # NaN sort to the end
            m = (~jnp.isnan(X)).sum(axis=0)  # (Cn,)
            q = jnp.linspace(0.0, 1.0, nbins + 1)[1:-1]  # (nbins-1,)
            pos = q[None, :] * jnp.maximum(m[:, None] - 1, 0)  # (Cn, nbins-1)
            lo = jnp.floor(pos).astype(jnp.int32)
            frac = (pos - lo).astype(jnp.float32)
            hi = jnp.minimum(lo + 1, jnp.maximum(m[:, None] - 1, 0))
            g = lambda idx: jnp.take_along_axis(xs.T, idx, axis=1)
            e = g(lo) * (1 - frac) + g(hi) * frac  # (Cn, nbins-1)
            return e.astype(jnp.float32), m

        prog = jax.jit(run)
        _EDGE_PROG[key] = prog

    idx = np.round(np.linspace(0, nrow - 1, ns)).astype(np.int32)
    idx_dev = jnp.asarray(idx)
    X = jnp.stack([frame.vec(n).data[idx_dev] for n in names], axis=1)
    e, m = prog(X)
    return np.asarray(e), np.asarray(m)


def fit_bins(frame: Frame, cols: list[str], nbins: int = MAX_BINS, sample: int = 200_000, seed: int = 7, nbins_cats: int | None = None) -> BinSpec:
    """Compute per-column quantile edges from (a sample of) the data.

    CPU: host numpy on pulled columns (the exact path tests pin). TPU: one
    fused device program + a KB-sized pull (see _device_quantile_edges).
    """
    nbins = min(nbins, MAX_BINS)
    C = len(cols)
    is_cat = np.zeros(C, bool)
    nb = np.zeros(C, np.int64)
    edges = np.full((C, MAX_BINS - 1), np.inf, np.float32)
    cards = np.zeros(C, np.int64)
    domains: list = [None] * C
    rng = np.random.default_rng(seed)

    numeric: list[int] = []
    for ci, name in enumerate(cols):
        v = frame.vec(name)
        if v.is_categorical():
            is_cat[ci] = True
            cards[ci] = v.cardinality
            # nbins_cats (upstream's categorical cap): levels past the cap
            # group into the last bin via the binning clip below. Like
            # upstream, it is INDEPENDENT of the numeric nbins — only the
            # uint8 code space bounds it
            cap = MAX_BINS if nbins_cats is None else min(nbins_cats, MAX_BINS)
            nb[ci] = min(v.cardinality, max(cap, 1))
            domains[ci] = v.domain
        else:
            numeric.append(ci)

    if numeric and jax.default_backend() != "cpu":
        e_dev, m = _device_quantile_edges(
            frame, [cols[ci] for ci in numeric], nbins, sample
        )
        for row, ci in enumerate(numeric):
            if m[row] == 0:
                nb[ci] = 1
                continue
            e = np.unique(e_dev[row].astype(np.float32))
            e = e[np.isfinite(e)]
            nb[ci] = len(e) + 1
            edges[ci, : len(e)] = e
    else:
        for ci in numeric:
            x = frame.vec(cols[ci]).to_numpy()
            x = x[~np.isnan(x)]
            if len(x) == 0:
                nb[ci] = 1
                continue
            if len(x) > sample:
                x = rng.choice(x, sample, replace=False)
            qs = np.quantile(x, np.linspace(0, 1, nbins + 1)[1:-1])
            e = np.unique(qs.astype(np.float32))
            nb[ci] = len(e) + 1
            edges[ci, : len(e)] = e
    return BinSpec(list(cols), is_cat, nb, edges, cards, domains)


def fit_bins_for(params, frame: Frame, cols: list[str]) -> BinSpec:
    """fit_bins driven by a SharedTreeParams-style object — the one place
    the tree builders derive binning from params (and the one place the
    nbins_top_level no-op is disclosed at runtime)."""
    from h2o3_tpu.utils.log import Log

    if getattr(params, "nbins_top_level", 1024) != 1024:
        Log.warn(
            "nbins_top_level has no effect: bins are static quantiles fit "
            "once (upstream re-bins per level); tune nbins / nbins_cats, or "
            "the H2O3_TPU_BIN_ADAPT env knob for per-level coarsening")
    return fit_bins(
        frame, cols, nbins=params.nbins,
        seed=abs(params.seed) or 7,
        nbins_cats=getattr(params, "nbins_cats", None),
    )


_BINFRAME_PROG: dict = {}


def _u8_cache_enabled() -> bool:
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_TREE_U8CACHE")


def _spec_fingerprint(spec: BinSpec) -> tuple:
    """Content fingerprint of a BinSpec — the u8 bin-code cache key.

    Two specs with equal fingerprints bin a given frame to the identical
    code matrix, so a cache hit returns the same buffer a fresh bin_frame
    call would produce (the knob's bit-for-bit guarantee)."""
    doms = tuple(
        tuple(d) if d is not None else None
        for d in (spec.domains or [None] * spec.ncols)
    )
    return (
        tuple(spec.names), spec.is_cat.tobytes(), spec.nbins.tobytes(),
        spec.edges.tobytes(), doms, jax.default_backend(),
    )


def bin_frame(spec: BinSpec, frame: Frame):
    """Prebin all feature columns to a row-sharded (npad, C) uint8 matrix.

    All columns bin in ONE fused device program (per-column dispatch costs
    dominate on a tunneled TPU).

    u8-code-native frames (ISSUE 16, ``H2O3_TPU_TREE_U8CACHE``): the code
    matrix is memoized on the frame keyed by the spec's content
    fingerprint, so repeated builds over one frame (AutoML, grids, CV,
    checkpoint restarts) stop re-reading every f32 column per build — the
    dominant frame HBM traffic of a multi-model session. The traffic an
    ACTUAL binning pass moves (one f32 read + one u8 write per cell) is
    tallied under ``tree_hist_hbm_bytes_total{path=rebin}``; cache hits
    move nothing and tally nothing, which is what the wave-2 A/B measures.
    """
    from h2o3_tpu.models.datainfo import _adapt_codes

    from h2o3_tpu.parallel.mesh import mesh_epoch

    cache = None
    fp = None
    if _u8_cache_enabled():
        fp = _spec_fingerprint(spec)
        cache = frame.__dict__.setdefault("_bin_cache", {})
        hit = cache.get(fp)
        if hit is not None:
            epoch, B = hit
            if epoch == mesh_epoch():
                return B
            # cached codes were padded/placed for a dead topology (elastic
            # reform, ISSUE 17): drop and rebin on the new mesh
            cache.pop(fp, None)

    datas = []
    for ci, name in enumerate(spec.names):
        v = frame.vec(name)
        if spec.is_cat[ci]:
            dom = spec.domains[ci] if spec.domains else v.domain
            datas.append(_adapt_codes(v, dom))
        else:
            datas.append(v.data)

    key = (tuple(bool(c) for c in spec.is_cat), tuple(int(n) for n in spec.nbins),
           jax.default_backend())
    prog = _BINFRAME_PROG.get(key)
    if prog is None:
        is_cat_t, nbins_t = key[0], key[1]

        def run(datas, edges):
            cols = []
            for ci in range(len(is_cat_t)):
                d = datas[ci]
                if is_cat_t[ci]:
                    cols.append(jnp.clip(d + 1, 0, nbins_t[ci]).astype(jnp.uint8))
                else:
                    e = edges[ci, : max(nbins_t[ci] - 1, 0)]
                    b = jnp.searchsorted(e, d, side="left").astype(jnp.int32) + 1
                    b = jnp.where(jnp.isnan(d), 0, b)
                    cols.append(b.astype(jnp.uint8))
            return jnp.stack(cols, axis=1)

        prog = jax.jit(run)
        _BINFRAME_PROG[key] = prog

    B = prog(tuple(datas), jnp.asarray(spec.edges))
    B = jax.device_put(B, row_sharding())
    # rebin traffic model: one f32 read + one u8 write per (row, col) cell
    # (lazy import: shared_tree imports this module)
    from h2o3_tpu.models.tree.shared_tree import _HIST_HBM_BYTES

    _HIST_HBM_BYTES.inc(5.0 * B.shape[0] * B.shape[1], path="rebin")
    if cache is not None:
        cache[fp] = (mesh_epoch(), B)
    return B


# ---------------------------------------------------------------------------
# Exclusive feature bundling (ISSUE 16, H2O3_TPU_TREE_EFB — arXiv:1706.08359
# §4). Sparse/one-hot suites carry many columns that sit at one dominant bin
# code almost everywhere; two such columns whose non-default rows never
# overlap can share ONE u8 column (their non-default codes mapped to
# disjoint sub-ranges), shrinking the histogram C dimension before the
# kernel grid sees it. The pass is host-side and greedy at BinSpec build
# time, requires ZERO conflicts (no row non-default in two bundled columns
# at once — the lossless regime, unlike LightGBM's bounded-conflict mode),
# and the device histogram is expanded back to real columns right after
# accumulation (expand_hist), so split records, varimp, MOJO and scoring
# never see bundle ids. The default-bin cell is reconstructed as
# node_total − Σ(non-default cells): exact whenever the stat lanes are
# dyadic/in-range (the parity suites), within f32 associativity otherwise.


@dataclass
class EFBPlan:
    """Host-side exclusive-feature-bundling plan for one BinSpec."""

    n_cols: int          # real feature count C
    n_bins: int          # total code space per column (spec.max_bins)
    bundles: list        # list[list[int]] — real col ids per bundled column
    src_col: np.ndarray  # (C,) int32: bundled column carrying real col f
    offset: np.ndarray   # (C,) int32: code offset of col f inside its bundle
    default: np.ndarray  # (C,) int32: dominant code d_f; -1 = pass-through
    nbins: np.ndarray    # (C,) int32: non-default code count per column

    @property
    def n_cols_b(self) -> int:
        return len(self.bundles)

    @property
    def key(self) -> tuple:
        """Hashable content fingerprint for program caches."""
        return (self.n_cols, self.n_bins, self.src_col.tobytes(),
                self.offset.tobytes(), self.default.tobytes(),
                self.nbins.tobytes())


def fit_efb(spec: BinSpec, bins_u8, nrow: int | None = None):
    """Greedy zero-conflict bundling over the frame's host bin codes.

    Returns an :class:`EFBPlan` when bundling shrinks the column count,
    else ``None``. O(C · bundles · rows) host work on the pulled u8 matrix
    — a one-time cost per BinSpec, dwarfed by the per-tree device work it
    removes."""
    B_host = np.asarray(bins_u8)
    if nrow is not None:
        B_host = B_host[:nrow]
    n, C = B_host.shape
    if C != spec.ncols or n == 0:
        return None
    total_codes = spec.max_bins

    # dominant code + non-default mask per column (cols at >50% non-default
    # rows can hardly co-bundle and skip straight to pass-through)
    dominant = np.zeros(C, np.int32)
    nz_masks: list = [None] * C
    order: list[int] = []
    for f in range(C):
        codes, counts = np.unique(B_host[:, f], return_counts=True)
        d = int(codes[np.argmax(counts)])
        nnz = n - int(counts.max())
        if nnz > n // 2 or int(spec.nbins[f]) + 1 > total_codes:
            continue
        dominant[f] = d
        nz_masks[f] = B_host[:, f] != d
        order.append(f)
    order.sort(key=lambda f: int(nz_masks[f].sum()))

    src_col = np.zeros(C, np.int32)
    offset = np.zeros(C, np.int32)
    default = np.full(C, -1, np.int32)
    nbins_nd = np.asarray(spec.nbins, np.int32).copy()  # non-default codes

    bundles: list[list[int]] = []
    occ: list[np.ndarray] = []   # per-bundle occupied-rows mask
    used: list[int] = []         # per-bundle consumed code count
    multi: set[int] = set()      # bundles holding >1 column
    for f in order:
        need = int(nbins_nd[f])
        placed = False
        for bi in range(len(bundles)):
            if used[bi] + need > total_codes - 1:
                continue
            if np.any(occ[bi] & nz_masks[f]):
                continue
            src_col[f] = bi
            offset[f] = used[bi]
            default[f] = dominant[f]
            bundles[bi].append(f)
            occ[bi] |= nz_masks[f]
            used[bi] += need
            multi.add(bi)
            placed = True
            break
        if not placed:
            src_col[f] = len(bundles)
            offset[f] = 0
            default[f] = dominant[f]
            bundles.append([f])
            occ.append(nz_masks[f].copy())
            used.append(need)
    # cols skipped above (dense / wide) pass through unchanged
    for f in range(C):
        if nz_masks[f] is None:
            src_col[f] = len(bundles)
            bundles.append([f])
            occ.append(np.zeros(0, bool))
            used.append(0)
    # a column alone in its bundle needs no re-coding: pass it through so
    # its histogram column is bit-identical (no rank mapping at all)
    for bi, group in enumerate(bundles):
        if bi not in multi and len(group) == 1:
            default[group[0]] = -1
            offset[group[0]] = 0

    if len(bundles) >= C:
        return None
    return EFBPlan(C, total_codes, bundles, src_col, offset, default,
                   nbins_nd)


_BUNDLE_PROG: dict = {}


def bundle_bins(plan: EFBPlan, bins_u8):
    """Build the (npad, Cb) bundled u8 code matrix on device.

    Bundle code 0 = every member at its default; member f's code c != d_f
    maps to ``offset_f + rank_f(c)`` where rank skips d_f (rank 1..nbins_f)
    — a bijection, since zero conflicts mean at most one member is
    non-default per row. Pass-through columns copy verbatim."""
    key = (plan.key, jax.default_backend())
    prog = _BUNDLE_PROG.get(key)
    if prog is None:
        groups = [list(g) for g in plan.bundles]
        offs = plan.offset.copy()
        defs = plan.default.copy()

        def run(B):
            cols = []
            for group in groups:
                if len(group) == 1 and defs[group[0]] < 0:
                    cols.append(B[:, group[0]])
                    continue
                acc = jnp.zeros(B.shape[0], jnp.int32)
                for f in group:
                    c = B[:, f].astype(jnp.int32)
                    d = int(defs[f])
                    rank = jnp.where(c < d, c + 1, c)
                    acc = acc + jnp.where(c == d, 0, int(offs[f]) + rank)
                cols.append(acc.astype(jnp.uint8))
            return jnp.stack(cols, axis=1)

        prog = jax.jit(run)
        _BUNDLE_PROG[key] = prog
    return jax.device_put(prog(bins_u8), row_sharding())


def expand_arrays(plan: EFBPlan, n_cols_pad: int, n_bins_h: int):
    """Precompute the (Cp, Bh) gather tables expand_hist consumes.

    ``kind``: 0 = structurally-zero cell, 1 = gather from src_bin of the
    carrying bundled column, 2 = the default cell (node_total − Σ
    non-default). Padded columns (f >= C) reproduce the all-codes-NA
    padding histogram: all node mass in bin 0."""
    Cp, Bh = n_cols_pad, n_bins_h
    src_col = np.zeros(Cp, np.int32)
    src_bin = np.zeros((Cp, Bh), np.int32)
    kind = np.zeros((Cp, Bh), np.int8)
    for f in range(plan.n_cols):
        src_col[f] = plan.src_col[f]
        ncodes = int(plan.nbins[f]) + 1  # real codes 0..nbins_f
        d = int(plan.default[f])
        for b in range(min(ncodes, Bh)):
            if d < 0:  # pass-through: identity gather
                src_bin[f, b] = b
                kind[f, b] = 1
            elif b == d:
                kind[f, b] = 2
            else:
                rank = b + 1 if b < d else b
                src_bin[f, b] = int(plan.offset[f]) + rank
                kind[f, b] = 1
    for f in range(plan.n_cols, Cp):
        kind[f, 0] = 2  # padded col: everything at the NA code
    return src_col, src_bin, kind


def expand_hist(arrs, hist_b):
    """Expand a bundled histogram (N, Cb', Bh, S) to real columns
    (N, Cp, Bh, S) — pure traced function, usable inside the tree
    programs. ``node_total`` per (node, stat) comes from summing any one
    bundled column's bins (every row lands in exactly one code of every
    column)."""
    src_col, src_bin, kind = (jnp.asarray(a) for a in arrs)
    g = jnp.take(hist_b, src_col, axis=1)              # (N, Cp, Bh, S)
    idx = jnp.broadcast_to(src_bin[None, :, :, None], g.shape)
    G = jnp.take_along_axis(g, idx, axis=2)
    node_tot = hist_b[:, 0, :, :].sum(axis=1)          # (N, S)
    gather = (kind == 1)[None, :, :, None]
    dflt = node_tot[:, None, :] - jnp.where(gather, G, 0.0).sum(axis=2)
    return jnp.where(
        gather, G,
        jnp.where((kind == 2)[None, :, :, None], dflt[:, :, None, :], 0.0))
