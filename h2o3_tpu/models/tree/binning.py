"""Feature binning for histogram tree building — the quantile-bin successor
of ``hex.tree.DHistogram`` bin-edge derivation [UNVERIFIED upstream path,
SURVEY.md §2.2].

H2O re-derives per-(node,col) bin ranges from surviving rows at every level;
static quantile binning (the XGBoost-hist approach) computes edges ONCE from
global column quantiles and prebins every row to a uint8 code — trading
h2o's adaptive ranges for a single O(n) pass and a device-resident compressed
design matrix (the C1Chunk analog that actually pays on TPU: 1 byte/cell in
HBM, histograms indexed directly by code). SURVEY.md §7 flags AUC-parity as
the risk; with 255 quantile bins the split resolution exceeds h2o's default
nbins=20, and tests pin accuracy against sklearn GBMs.

Bin layout per column: code 0 = NA, codes 1..nbins = data bins.
Numeric: quantile buckets (edges stored for predict-time rebinning).
Categorical: code = category_id + 1; domains wider than 254 levels clamp the
tail into the last bin (h2o groups rare levels similarly at nbins_cats).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel.mesh import row_sharding

MAX_BINS = 255  # codes 1..255 fit uint8 with 0 reserved for NA


# ---------------------------------------------------------------------------
# shape-bucket ladder (H2O3_TPU_SHAPE_BUCKETS): AutoML/grid builds differ in
# data-dependent shapes (actual quantile-bin count, feature count after
# drops), and every distinct shape is a fresh multi-second XLA compile of the
# whole-tree program. Rounding bins/cols up to a coarse ladder collapses
# near-identical shapes onto one compiled program. The padding is inert by
# construction: padded bins are empty (every candidate split there fails
# min_rows and loses the argmax to a real bin), padded columns carry
# cols_enabled=0 and the NA code everywhere, and the column-sampling RNG is
# drawn at the REAL column count — so a bucketed build scores identically to
# an exact-shape build (pinned by tests).


def _buckets_enabled() -> bool:
    from h2o3_tpu import config

    return config.get_bool("H2O3_TPU_SHAPE_BUCKETS")


def bucket_nbins(n_bins: int) -> int:
    """Histogram bin-axis bucket: next power of two (min 8, cap 256)."""
    if not _buckets_enabled() or n_bins >= 256:
        return n_bins
    b = 8
    while b < n_bins:
        b <<= 1
    return b


def bucket_cols(n_cols: int) -> int:
    """Feature-axis bucket: next multiple of 4 (min 4).

    Histogram cost is ∝ columns, so every padded column is pure overhead on
    every build that hits the program — a multiple-of-8 ladder costs the
    28-col headline +14% histogram work forever to save compiles it never
    needs. Multiple-of-4 keeps the compile-collapse for the odd widths
    AutoML feature-drops produce at ≤3 padded columns."""
    if not _buckets_enabled():
        return n_cols
    return max(4, -(-n_cols // 4) * 4)


@dataclass
class BinSpec:
    """Fitted binning for one frame's feature set."""

    names: list[str]
    is_cat: np.ndarray  # (C,) bool
    nbins: np.ndarray  # (C,) int, actual bin count per column (excl. NA bin)
    edges: np.ndarray  # (C, MAX_BINS-1) float32 right-inclusive bin edges, +inf padded
    cards: np.ndarray  # (C,) categorical cardinality (0 for numeric)
    domains: list | None = None  # train-time cat domains (for test adaptation)

    @property
    def ncols(self) -> int:
        return len(self.names)

    @property
    def max_bins(self) -> int:
        return int(self.nbins.max()) + 1  # +1 for the NA bin 0


_EDGE_PROG: dict = {}


def _device_quantile_edges(frame: Frame, names: list[str], nbins: int, sample: int):
    """Per-column quantile edges computed ON DEVICE — a 4 MB column pull over
    a tunneled TPU costs ~0.5 s, so fit_bins pulling every column dominated
    GBM build time; this pulls only (Cn, nbins-1) edges + counts (KBs)."""
    nrow = frame.nrow
    ns = min(nrow, sample)
    key = (nbins, ns, jax.default_backend())
    prog = _EDGE_PROG.get(key)
    if prog is None:

        def run(X):  # (ns, Cn)
            xs = jnp.sort(X, axis=0)  # NaN sort to the end
            m = (~jnp.isnan(X)).sum(axis=0)  # (Cn,)
            q = jnp.linspace(0.0, 1.0, nbins + 1)[1:-1]  # (nbins-1,)
            pos = q[None, :] * jnp.maximum(m[:, None] - 1, 0)  # (Cn, nbins-1)
            lo = jnp.floor(pos).astype(jnp.int32)
            frac = (pos - lo).astype(jnp.float32)
            hi = jnp.minimum(lo + 1, jnp.maximum(m[:, None] - 1, 0))
            g = lambda idx: jnp.take_along_axis(xs.T, idx, axis=1)
            e = g(lo) * (1 - frac) + g(hi) * frac  # (Cn, nbins-1)
            return e.astype(jnp.float32), m

        prog = jax.jit(run)
        _EDGE_PROG[key] = prog

    idx = np.round(np.linspace(0, nrow - 1, ns)).astype(np.int32)
    idx_dev = jnp.asarray(idx)
    X = jnp.stack([frame.vec(n).data[idx_dev] for n in names], axis=1)
    e, m = prog(X)
    return np.asarray(e), np.asarray(m)


def fit_bins(frame: Frame, cols: list[str], nbins: int = MAX_BINS, sample: int = 200_000, seed: int = 7, nbins_cats: int | None = None) -> BinSpec:
    """Compute per-column quantile edges from (a sample of) the data.

    CPU: host numpy on pulled columns (the exact path tests pin). TPU: one
    fused device program + a KB-sized pull (see _device_quantile_edges).
    """
    nbins = min(nbins, MAX_BINS)
    C = len(cols)
    is_cat = np.zeros(C, bool)
    nb = np.zeros(C, np.int64)
    edges = np.full((C, MAX_BINS - 1), np.inf, np.float32)
    cards = np.zeros(C, np.int64)
    domains: list = [None] * C
    rng = np.random.default_rng(seed)

    numeric: list[int] = []
    for ci, name in enumerate(cols):
        v = frame.vec(name)
        if v.is_categorical():
            is_cat[ci] = True
            cards[ci] = v.cardinality
            # nbins_cats (upstream's categorical cap): levels past the cap
            # group into the last bin via the binning clip below. Like
            # upstream, it is INDEPENDENT of the numeric nbins — only the
            # uint8 code space bounds it
            cap = MAX_BINS if nbins_cats is None else min(nbins_cats, MAX_BINS)
            nb[ci] = min(v.cardinality, max(cap, 1))
            domains[ci] = v.domain
        else:
            numeric.append(ci)

    if numeric and jax.default_backend() != "cpu":
        e_dev, m = _device_quantile_edges(
            frame, [cols[ci] for ci in numeric], nbins, sample
        )
        for row, ci in enumerate(numeric):
            if m[row] == 0:
                nb[ci] = 1
                continue
            e = np.unique(e_dev[row].astype(np.float32))
            e = e[np.isfinite(e)]
            nb[ci] = len(e) + 1
            edges[ci, : len(e)] = e
    else:
        for ci in numeric:
            x = frame.vec(cols[ci]).to_numpy()
            x = x[~np.isnan(x)]
            if len(x) == 0:
                nb[ci] = 1
                continue
            if len(x) > sample:
                x = rng.choice(x, sample, replace=False)
            qs = np.quantile(x, np.linspace(0, 1, nbins + 1)[1:-1])
            e = np.unique(qs.astype(np.float32))
            nb[ci] = len(e) + 1
            edges[ci, : len(e)] = e
    return BinSpec(list(cols), is_cat, nb, edges, cards, domains)


def fit_bins_for(params, frame: Frame, cols: list[str]) -> BinSpec:
    """fit_bins driven by a SharedTreeParams-style object — the one place
    the tree builders derive binning from params (and the one place the
    nbins_top_level no-op is disclosed at runtime)."""
    from h2o3_tpu.utils.log import Log

    if getattr(params, "nbins_top_level", 1024) != 1024:
        Log.warn(
            "nbins_top_level has no effect: bins are static quantiles fit "
            "once (upstream re-bins per level); tune nbins / nbins_cats, or "
            "the H2O3_TPU_BIN_ADAPT env knob for per-level coarsening")
    return fit_bins(
        frame, cols, nbins=params.nbins,
        seed=abs(params.seed) or 7,
        nbins_cats=getattr(params, "nbins_cats", None),
    )


_BINFRAME_PROG: dict = {}


def bin_frame(spec: BinSpec, frame: Frame):
    """Prebin all feature columns to a row-sharded (npad, C) uint8 matrix.

    All columns bin in ONE fused device program (per-column dispatch costs
    dominate on a tunneled TPU)."""
    from h2o3_tpu.models.datainfo import _adapt_codes

    datas = []
    for ci, name in enumerate(spec.names):
        v = frame.vec(name)
        if spec.is_cat[ci]:
            dom = spec.domains[ci] if spec.domains else v.domain
            datas.append(_adapt_codes(v, dom))
        else:
            datas.append(v.data)

    key = (tuple(bool(c) for c in spec.is_cat), tuple(int(n) for n in spec.nbins),
           jax.default_backend())
    prog = _BINFRAME_PROG.get(key)
    if prog is None:
        is_cat_t, nbins_t = key[0], key[1]

        def run(datas, edges):
            cols = []
            for ci in range(len(is_cat_t)):
                d = datas[ci]
                if is_cat_t[ci]:
                    cols.append(jnp.clip(d + 1, 0, nbins_t[ci]).astype(jnp.uint8))
                else:
                    e = edges[ci, : max(nbins_t[ci] - 1, 0)]
                    b = jnp.searchsorted(e, d, side="left").astype(jnp.int32) + 1
                    b = jnp.where(jnp.isnan(d), 0, b)
                    cols.append(b.astype(jnp.uint8))
            return jnp.stack(cols, axis=1)

        prog = jax.jit(run)
        _BINFRAME_PROG[key] = prog

    B = prog(tuple(datas), jnp.asarray(spec.edges))
    return jax.device_put(B, row_sharding())
