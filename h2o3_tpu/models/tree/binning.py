"""Feature binning for histogram tree building — the quantile-bin successor
of ``hex.tree.DHistogram`` bin-edge derivation [UNVERIFIED upstream path,
SURVEY.md §2.2].

H2O re-derives per-(node,col) bin ranges from surviving rows at every level;
static quantile binning (the XGBoost-hist approach) computes edges ONCE from
global column quantiles and prebins every row to a uint8 code — trading
h2o's adaptive ranges for a single O(n) pass and a device-resident compressed
design matrix (the C1Chunk analog that actually pays on TPU: 1 byte/cell in
HBM, histograms indexed directly by code). SURVEY.md §7 flags AUC-parity as
the risk; with 255 quantile bins the split resolution exceeds h2o's default
nbins=20, and tests pin accuracy against sklearn GBMs.

Bin layout per column: code 0 = NA, codes 1..nbins = data bins.
Numeric: quantile buckets (edges stored for predict-time rebinning).
Categorical: code = category_id + 1; domains wider than 254 levels clamp the
tail into the last bin (h2o groups rare levels similarly at nbins_cats).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel.mesh import row_sharding

MAX_BINS = 255  # codes 1..255 fit uint8 with 0 reserved for NA


@dataclass
class BinSpec:
    """Fitted binning for one frame's feature set."""

    names: list[str]
    is_cat: np.ndarray  # (C,) bool
    nbins: np.ndarray  # (C,) int, actual bin count per column (excl. NA bin)
    edges: np.ndarray  # (C, MAX_BINS-1) float32 right-inclusive bin edges, +inf padded
    cards: np.ndarray  # (C,) categorical cardinality (0 for numeric)
    domains: list | None = None  # train-time cat domains (for test adaptation)

    @property
    def ncols(self) -> int:
        return len(self.names)

    @property
    def max_bins(self) -> int:
        return int(self.nbins.max()) + 1  # +1 for the NA bin 0


def fit_bins(frame: Frame, cols: list[str], nbins: int = MAX_BINS, sample: int = 200_000, seed: int = 7) -> BinSpec:
    """Compute per-column quantile edges from (a sample of) the data."""
    nbins = min(nbins, MAX_BINS)
    C = len(cols)
    is_cat = np.zeros(C, bool)
    nb = np.zeros(C, np.int64)
    edges = np.full((C, MAX_BINS - 1), np.inf, np.float32)
    cards = np.zeros(C, np.int64)
    domains: list = [None] * C
    rng = np.random.default_rng(seed)
    for ci, name in enumerate(cols):
        v = frame.vec(name)
        if v.is_categorical():
            is_cat[ci] = True
            cards[ci] = v.cardinality
            nb[ci] = min(v.cardinality, nbins)
            domains[ci] = v.domain
            continue
        x = v.to_numpy()
        x = x[~np.isnan(x)]
        if len(x) == 0:
            nb[ci] = 1
            continue
        if len(x) > sample:
            x = rng.choice(x, sample, replace=False)
        qs = np.quantile(x, np.linspace(0, 1, nbins + 1)[1:-1])
        e = np.unique(qs.astype(np.float32))
        nb[ci] = len(e) + 1
        edges[ci, : len(e)] = e
    return BinSpec(list(cols), is_cat, nb, edges, cards, domains)


def bin_frame(spec: BinSpec, frame: Frame):
    """Prebin all feature columns to a row-sharded (npad, C) uint8 matrix."""
    cols = []
    for ci, name in enumerate(spec.names):
        v = frame.vec(name)
        if spec.is_cat[ci]:
            from h2o3_tpu.models.datainfo import _adapt_codes

            dom = spec.domains[ci] if spec.domains else v.domain
            codes = _adapt_codes(v, dom)
            # cap codes into bin range; NA (-1) -> 0
            capped = jnp.clip(codes + 1, 0, int(spec.nbins[ci]))
            cols.append(capped.astype(jnp.uint8))
        else:
            e = jnp.asarray(spec.edges[ci, : max(int(spec.nbins[ci]) - 1, 0)])
            x = v.data
            b = jnp.searchsorted(e, x, side="left").astype(jnp.int32) + 1
            b = jnp.where(jnp.isnan(x), 0, b)
            cols.append(b.astype(jnp.uint8))
    B = jnp.stack(cols, axis=1)
    return jax.device_put(B, row_sharding())
