"""Level-wise distributed tree builder — successor of ``hex.tree.SharedTree``
/ ``DTree`` (``UndecidedNode``/``DecidedNode``, ``findBestSplitPoint``) /
``ScoreBuildHistogram2`` [UNVERIFIED upstream paths, SURVEY.md §2.2 §3.3].

Per level (SURVEY §3.3 call stack, TPU-native form):
1. ``build_histograms`` — the ScoreBuildHistogram pass: scatter {w,wy,wy²,wh}
   into (node,col,bin) cells per row shard, psum across the mesh.
2. ``find_best_splits`` — DTree.findBestSplitPoint vectorized over all
   (node, col) pairs on device: SE-reduction gain scan over bin prefixes,
   NA-direction both ways (DHistogram's NA trick), categorical bins sorted
   by mean response (DHistogram's categorical bin-sort).
3. Host: decide split-vs-leaf per node (min_rows / min_split_improvement /
   depth), assign compacted child ids (active-leaf frontier, NOT full 2^d
   indexing — this is how depth-20 DRF stays bounded).
4. ``_partition_update`` — the DecidedNode re-labeling: rows map to child
   nids; rows landing in finalized leaves add the leaf value to the running
   prediction and retire with nid=-1.

Trees are recorded per level as compact arrays; prediction replays the same
partition walk on a prebinned test matrix (CompressedTree.score0 successor).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -1e30


# ---------------------------------------------------------------------------
# split finding


@partial(jax.jit, static_argnames=())
def _split_scan(hist, is_cat, col_mask, min_rows, min_split_improvement):
    """Best split per node from hist (N, C, B, 4). Returns per-node arrays.

    Stats axis: 0=w, 1=wy, 2=wy2, 3=wh. Bin 0 is the NA bin.
    """
    N, C, B, _ = hist.shape
    total = hist.sum(axis=2)  # (N, C, 4)
    na = hist[:, :, 0, :]  # (N, C, 4)
    data = hist[:, :, 1:, :]  # (N, C, B-1, 4)

    def se(s):  # squared error: wy2 - wy^2/w
        w = s[..., 0]
        return s[..., 2] - jnp.where(w > 0, s[..., 1] ** 2 / jnp.maximum(w, 1e-30), 0.0)

    parent_se = se(total[:, 0:1, :]).squeeze(1)  # same for every col: (N,)

    # ---- numeric: prefix split over natural bin order ----
    cum = jnp.cumsum(data, axis=2)  # (N, C, B-1, 4)
    tot_nonna = cum[:, :, -1:, :]
    left_n = cum[:, :, :-1, :]  # split after data-bin t: left = bins 1..t+1
    right_n = tot_nonna - left_n

    def gain_with_na(L, R):
        gl = se(L)
        gr = se(R)
        ok = (L[..., 0] >= min_rows) & (R[..., 0] >= min_rows)
        g = parent_se[:, None, None] - gl - gr
        return jnp.where(ok, g, _NEG)

    g_naleft = gain_with_na(left_n + na[:, :, None, :], right_n)
    g_naright = gain_with_na(left_n, right_n + na[:, :, None, :])
    g_num = jnp.maximum(g_naleft, g_naright)  # (N, C, B-2)
    num_best_t = jnp.argmax(g_num, axis=2)  # (N, C)
    num_best_gain = jnp.take_along_axis(g_num, num_best_t[:, :, None], 2).squeeze(2)
    num_na_left = (
        jnp.take_along_axis(g_naleft, num_best_t[:, :, None], 2).squeeze(2)
        >= jnp.take_along_axis(g_naright, num_best_t[:, :, None], 2).squeeze(2)
    )

    # ---- categorical: prefix split in mean-sorted bin order ----
    w_bins = data[..., 0]
    mean = jnp.where(w_bins > 0, data[..., 1] / jnp.maximum(w_bins, 1e-30), jnp.inf)
    order = jnp.argsort(mean, axis=2)  # (N, C, B-1) empty bins (inf) last
    sdata = jnp.take_along_axis(data, order[..., None], axis=2)
    scum = jnp.cumsum(sdata, axis=2)
    s_tot = scum[:, :, -1:, :]
    s_left = scum[:, :, :-1, :]
    s_right = s_tot - s_left
    gc_naleft = gain_with_na(s_left + na[:, :, None, :], s_right)
    gc_naright = gain_with_na(s_left, s_right + na[:, :, None, :])
    g_cat = jnp.maximum(gc_naleft, gc_naright)
    cat_best_k = jnp.argmax(g_cat, axis=2)  # (N, C) prefix length-1
    cat_best_gain = jnp.take_along_axis(g_cat, cat_best_k[:, :, None], 2).squeeze(2)
    cat_na_left = (
        jnp.take_along_axis(gc_naleft, cat_best_k[:, :, None], 2).squeeze(2)
        >= jnp.take_along_axis(gc_naright, cat_best_k[:, :, None], 2).squeeze(2)
    )

    # ---- choose per column kind, then best column per node ----
    col_gain = jnp.where(is_cat[None, :], cat_best_gain, num_best_gain)
    col_gain = jnp.where(col_mask > 0, col_gain, _NEG)
    best_col = jnp.argmax(col_gain, axis=1)  # (N,)
    best_gain = jnp.take_along_axis(col_gain, best_col[:, None], 1).squeeze(1)

    take = lambda a: jnp.take_along_axis(a, best_col[:, None], 1).squeeze(1)
    bc_is_cat = is_cat[best_col]
    bc_t = take(num_best_t)
    bc_k = take(cat_best_k)
    bc_na_left = jnp.where(bc_is_cat, take(cat_na_left), take(num_na_left))

    # split_bin: numeric → left iff 1 <= bin <= t+1
    split_bin = bc_t + 1

    # cat membership mask over ALL B bins (bin 0 NA handled separately):
    # rank of data-bin j (order position) <= k  → left
    ranks = jnp.argsort(order, axis=2)  # (N, C, B-1) rank of each data bin
    idx = jnp.broadcast_to(best_col[:, None, None], (ranks.shape[0], 1, ranks.shape[2]))
    best_ranks = jnp.take_along_axis(ranks, idx, axis=1).squeeze(1)  # (N, B-1)
    cat_left = best_ranks <= bc_k[:, None]  # (N, B-1) for data bins 1..B-1
    cat_mask = jnp.concatenate(
        [bc_na_left[:, None], cat_left], axis=1
    )  # (N, B): bin0 = NA direction

    # child stats for the chosen split (needed for leaf values of children)
    def chosen_child_stats():
        # numeric
        Ln = jnp.take_along_axis(
            left_n, num_best_t[:, :, None, None].repeat(4, 3), 2
        ).squeeze(2)  # (N, C, 4)
        Rn = jnp.take_along_axis(
            right_n, num_best_t[:, :, None, None].repeat(4, 3), 2
        ).squeeze(2)
        # categorical
        Lc = jnp.take_along_axis(
            s_left, cat_best_k[:, :, None, None].repeat(4, 3), 2
        ).squeeze(2)
        Rc = jnp.take_along_axis(
            s_right, cat_best_k[:, :, None, None].repeat(4, 3), 2
        ).squeeze(2)
        L = jnp.where(is_cat[None, :, None], Lc, Ln)
        R = jnp.where(is_cat[None, :, None], Rc, Rn)
        nac = na
        na_left_c = jnp.where(bc_is_cat, take(cat_na_left), take(num_na_left))
        Lb = jnp.take_along_axis(L, best_col[:, None, None].repeat(4, 2), 1).squeeze(1)
        Rb = jnp.take_along_axis(R, best_col[:, None, None].repeat(4, 2), 1).squeeze(1)
        nab = jnp.take_along_axis(nac, best_col[:, None, None].repeat(4, 2), 1).squeeze(1)
        Lb = Lb + jnp.where(na_left_c[:, None], nab, 0.0)
        Rb = Rb + jnp.where(na_left_c[:, None], 0.0, nab)
        return Lb, Rb

    Lstats, Rstats = chosen_child_stats()

    node_w = total[:, 0, 0]
    node_wy = total[:, 0, 1]
    node_wh = total[:, 0, 3]
    ok_split = best_gain >= min_split_improvement

    return {
        "gain": best_gain,
        "ok": ok_split,
        "col": best_col,
        "is_cat": bc_is_cat,
        "split_bin": split_bin,
        "na_left": bc_na_left,
        "cat_mask": cat_mask,
        "left_stats": Lstats,
        "right_stats": Rstats,
        "node_w": node_w,
        "node_wy": node_wy,
        "node_wh": node_wh,
    }


# ---------------------------------------------------------------------------
# partition update (DecidedNode re-labeling + leaf retirement)


@jax.jit
def _partition_update(
    bins_u8, nid, preds, split_col, split_bin, is_cat, cat_mask, na_left, leaf_now, leaf_val, child_base
):
    active = nid >= 0
    node = jnp.where(active, nid, 0)
    col = split_col[node]
    b = jnp.take_along_axis(bins_u8, col[:, None].astype(jnp.int32), axis=1).squeeze(1).astype(jnp.int32)
    go_left = jnp.where(
        b == 0,
        na_left[node],
        jnp.where(is_cat[node], cat_mask[node, b], b <= split_bin[node]),
    )
    child = child_base[node] + jnp.where(go_left, 0, 1)
    retired = leaf_now[node]
    new_nid = jnp.where(active, jnp.where(retired, -1, child), -1)
    new_preds = preds + jnp.where(active & retired, leaf_val[node], 0.0)
    return new_nid.astype(jnp.int32), new_preds


# ---------------------------------------------------------------------------
# recorded tree (for prediction replay)


@dataclass
class TreeLevel:
    split_col: np.ndarray
    split_bin: np.ndarray
    is_cat: np.ndarray
    cat_mask: np.ndarray
    na_left: np.ndarray
    leaf_now: np.ndarray
    leaf_val: np.ndarray
    child_base: np.ndarray
    gain: np.ndarray | None = None  # per-node split gain (varimp source)


@dataclass
class Tree:
    levels: list[TreeLevel] = field(default_factory=list)

    @property
    def n_leaves(self) -> int:
        return int(sum(l.leaf_now.sum() for l in self.levels))

    @property
    def depth(self) -> int:
        return len(self.levels)

    def replay(self, bins_u8, nid, preds):
        """Accumulate this tree's contribution into preds (device walk)."""
        for lv in self.levels:
            nid, preds = _partition_update(
                bins_u8,
                nid,
                preds,
                jnp.asarray(lv.split_col),
                jnp.asarray(lv.split_bin),
                jnp.asarray(lv.is_cat),
                jnp.asarray(lv.cat_mask),
                jnp.asarray(lv.na_left),
                jnp.asarray(lv.leaf_now),
                jnp.asarray(lv.leaf_val),
                jnp.asarray(lv.child_base),
            )
        return nid, preds


# ---------------------------------------------------------------------------
# the level-wise builder


def build_tree(
    bins_u8,
    w,
    t,
    h,
    *,
    n_bins: int,
    is_cat_cols: np.ndarray,
    max_depth: int,
    min_rows: float,
    min_split_improvement: float,
    learn_rate: float,
    preds,
    col_sample_rate: float = 1.0,
    cols_enabled: np.ndarray | None = None,
    rng: np.random.Generator | None = None,
    max_abs_leaf: float = np.inf,
) -> tuple[Tree, "jnp.ndarray"]:
    """Build one tree; mutates the running prediction vector via leaf adds.

    Inputs are row-sharded device arrays: ``bins_u8`` (npad,C), per-row
    weight ``w`` (0 = out of this tree), target ``t`` (residual), hessian
    ``h``. Returns the recorded Tree and the updated preds.
    """
    from h2o3_tpu.ops.histogram import build_histograms

    C = bins_u8.shape[1]
    is_cat_dev = jnp.asarray(is_cat_cols)
    wy = w * t
    wy2 = w * t * t
    wh = jnp.where(w > 0, h, 0.0)  # sampled-out rows carry no hessian either
    # ALL rows walk the tree (sampled-out rows contribute nothing to hists
    # via w=0, but must still receive leaf predictions — GBM's next-iteration
    # gradients depend on F for every row).
    nid = jnp.zeros(bins_u8.shape[0], jnp.int32)
    tree = Tree()
    n_active = 1

    for depth in range(max_depth + 1):
        n_pad = max(1, 1 << (n_active - 1).bit_length())
        hist = build_histograms(bins_u8, nid, w, wy, wy2, wh, n_pad, n_bins)

        force_leaf_all = depth == max_depth
        if force_leaf_all:
            sp = None
            node_w = np.asarray(hist.sum(axis=(1, 2))[:, 0] / max(C, 1))
            # hist sums each col over full node; per-col totals identical — take col 0
            tot = np.asarray(hist[:, 0, :, :].sum(axis=1))
            node_w = tot[:, 0]
            node_wy = tot[:, 1]
            node_wh = tot[:, 3]
            ok = np.zeros(n_pad, bool)
        else:
            col_mask = np.ones((n_pad, C), np.float32)
            if cols_enabled is not None:
                col_mask *= cols_enabled[None, :].astype(np.float32)
            if col_sample_rate < 1.0 and rng is not None:
                keep = rng.random((n_pad, C)) < col_sample_rate
                # guarantee at least one column per node
                keep[np.arange(n_pad), rng.integers(0, C, n_pad)] = True
                col_mask *= keep
            sp = _split_scan(
                hist,
                is_cat_dev,
                jnp.asarray(col_mask),
                jnp.float32(min_rows),
                jnp.float32(min_split_improvement),
            )
            sp = {k: np.asarray(v) for k, v in sp.items()}
            ok = np.asarray(sp["ok"], bool).copy()
            ok[n_active:] = False
            node_w = sp["node_w"]
            node_wy = sp["node_wy"]
            node_wh = sp["node_wh"]

        # leaf decision: no valid split, or empty node
        leaf_now = ~ok
        leaf_now[node_w <= 0] = True  # empty padding nodes: place as leaf w/ 0 val
        leaf_val = np.where(
            node_wh > 0, node_wy / np.maximum(node_wh, 1e-30), 0.0
        )
        leaf_val = np.clip(leaf_val, -max_abs_leaf, max_abs_leaf) * learn_rate
        leaf_val = np.where(leaf_now, leaf_val, 0.0).astype(np.float32)

        splitting = ~leaf_now
        n_split = int(splitting.sum())
        child_base = np.full(n_pad, 0, np.int32)
        child_base[splitting] = 2 * np.arange(n_split, dtype=np.int32)

        if sp is None:
            lv = TreeLevel(
                split_col=np.zeros(n_pad, np.int32),
                split_bin=np.zeros(n_pad, np.int32),
                is_cat=np.zeros(n_pad, bool),
                cat_mask=np.zeros((n_pad, n_bins), bool),
                na_left=np.zeros(n_pad, bool),
                leaf_now=leaf_now,
                leaf_val=leaf_val,
                child_base=child_base,
                gain=np.zeros(n_pad, np.float32),
            )
        else:
            lv = TreeLevel(
                split_col=sp["col"].astype(np.int32),
                split_bin=sp["split_bin"].astype(np.int32),
                is_cat=sp["is_cat"].astype(bool),
                cat_mask=sp["cat_mask"].astype(bool),
                na_left=sp["na_left"].astype(bool),
                leaf_now=leaf_now,
                leaf_val=leaf_val,
                child_base=child_base,
                gain=np.where(~leaf_now, np.maximum(sp["gain"], 0.0), 0.0).astype(
                    np.float32
                ),
            )
        tree.levels.append(lv)

        nid, preds = _partition_update(
            bins_u8,
            nid,
            preds,
            jnp.asarray(lv.split_col),
            jnp.asarray(lv.split_bin),
            jnp.asarray(lv.is_cat),
            jnp.asarray(lv.cat_mask),
            jnp.asarray(lv.na_left),
            jnp.asarray(lv.leaf_now),
            jnp.asarray(lv.leaf_val),
            jnp.asarray(lv.child_base),
        )

        n_active = 2 * n_split
        if n_active == 0:
            break

    return tree, preds
